"""retry-discipline: control-plane calls must ride the shared policy.

Round-7 (`retrying.py`) replaced every ad-hoc ``except Exception:
retry later`` control-plane loop with one taxonomy (transient vs
fatal), jittered backoff and a deadline — and the incidents it closed
(synchronized retry stampedes on a restarting config server, retry
budgets burned on malformed-JSON errors that can never heal) come
straight back the first time a new call site regresses. This pass
keeps the tree honest:

- raw ``urllib.request.urlopen`` / ``socket.create_connection`` calls
  anywhere outside the blessed wrapper modules (``retrying.py`` and
  the ``fetch_url``/``put_url`` home, ``peer.py``) are flagged —
  control-plane HTTP goes through the policy, full stop;
- bare ``except:`` and over-broad ``except Exception`` /
  ``except BaseException`` handlers are flagged unless the handler
  re-raises (cleanup-then-propagate is fine), the enclosing function
  is ``__del__`` (interpreter teardown throws anything), or the site
  carries an explicit ``# kflint: disable=retry-discipline`` with its
  justification — the satellite migration narrowed every other site
  to an explicit exception list.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from .core import Finding, Source, call_name

NAME = "retry-discipline"

#: modules allowed to touch urllib/socket directly: the policy itself
#: and the fetch_url/put_url wrappers every other site must use.
_WRAPPER_MODULES = {"retrying.py", "peer.py"}

_RAW_CALLS = {
    "urllib.request.urlopen": "urlopen",
    "urlopen": "urlopen",
    "socket.create_connection": "socket.create_connection",
}

_BROAD = {"Exception", "BaseException"}


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    """True when the handler contains a bare ``raise``, re-raises its
    own bound exception, or wraps-and-propagates it (``raise X(...)
    from e``) — cleanup/translate-then-propagate swallows nothing, so
    broadness costs nothing."""
    bound = handler.name

    def names_bound(n):
        return (bound and isinstance(n, ast.Name) and n.id == bound)

    # the handler's OWN statements only: a `raise` inside a function
    # merely DEFINED here runs later (if ever) and propagates nothing
    stack = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Raise):
            if node.exc is None:
                return True
            if names_bound(node.exc) or names_bound(node.cause):
                return True
        stack.extend(ast.iter_child_nodes(node))
    return False


def _broad_names(handler: ast.ExceptHandler) -> List[str]:
    if handler.type is None:
        return ["<bare>"]
    types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    out = []
    for t in types:
        name = t.id if isinstance(t, ast.Name) else (
            t.attr if isinstance(t, ast.Attribute) else None)
        if name in _BROAD:
            out.append(name)
    return out


class _Visitor(ast.NodeVisitor):
    def __init__(self, src: Source, in_wrapper: bool):
        self.src = src
        self.in_wrapper = in_wrapper
        self.findings: List[Finding] = []
        self._func: List[str] = []  # enclosing function-name stack

    def _add(self, node: ast.AST, message: str) -> None:
        f = self.src.finding(node, NAME, message)
        if f:
            self.findings.append(f)

    def visit_FunctionDef(self, node):
        self._func.append(node.name)
        self.generic_visit(node)
        self._func.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call):
        if not self.in_wrapper:
            cn = call_name(node)
            if cn in _RAW_CALLS:
                self._add(
                    node,
                    f"raw {_RAW_CALLS[cn]} outside retrying.py's policy "
                    "— use peer.fetch_url/put_url (or wrap the call in "
                    "a RetryPolicy) so the transient/fatal taxonomy, "
                    "backoff and deadline apply")
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if self._innermost() == "__del__":
            self.generic_visit(node)
            return  # teardown may see anything; broad is right there
        broad = _broad_names(node)
        if broad and not _handler_reraises(node):
            what = ("bare except" if broad == ["<bare>"]
                    else f"except {'/'.join(broad)}")
            self._add(
                node,
                f"{what} swallows the error taxonomy — narrow to the "
                "exceptions this site can actually heal (see "
                "retrying.is_transient), re-raise after cleanup, or "
                "justify with # kflint: disable=retry-discipline")
        self.generic_visit(node)

    def _innermost(self) -> Optional[str]:
        return self._func[-1] if self._func else None


class RetryDisciplinePass:
    name = NAME
    doc = ("control-plane urllib/socket calls outside retrying.py's "
           "policy, and bare/over-broad except handlers")

    def run(self, src: Source) -> List[Finding]:
        v = _Visitor(src, os.path.basename(src.path) in _WRAPPER_MODULES)
        v.visit(src.tree)
        return v.findings
