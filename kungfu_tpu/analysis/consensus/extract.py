"""kfconsensus extractor: lift the consensus state machine out of the code.

The model checker in :mod:`.model` explores a SPEC of the replicated
control plane — term/vote transitions, the append→WAL→push→ack
dataflow, the ``(seq_term, seq)`` vote-completeness guard — not the
code itself. A hand-written spec rots: the PR 5 lesson (the bucket
name template in ``protocol/explore.py``) is that the model must be
EXTRACTED from the tree and the extraction must RAISE when the code
drifts, so the checker can never keep proving a machine the code no
longer implements.

This module walks the kfverify :class:`ProjectIndex` over
``elastic/replica.py`` + ``elastic/wal.py`` and matches the exact AST
shapes of every guard the model relies on:

- ``_on_vote``: the ``granted = req_term > max(self.term,
  self.voted_term)`` term rule (the comparison OPERATOR is extracted —
  a drift to ``>=`` re-grants within a term), the
  ``(self.seq_term, self.seq)`` log-completeness tuple (order
  matters), and ``_wal_save_term`` ordered before the grant returns;
- ``_run_election``: the candidacy persisted before the vote sweep;
- ``_commit``: WAL append before the first ``apply_delta`` push,
  the ``entry["ok"] = True`` ack after it, and the fenced-409
  step-down-and-fail path before the ack;
- ``_on_apply_delta`` / ``_on_apply`` / ``_on_heartbeat``: the
  stale-term 409 fences, the seq-domain gap answer, the strict
  ``expect = self.seq + 1`` contiguity run, the same-domain duplicate
  guard, the domain-aware ``behind`` rule;
- ``_push_state`` / ``_push_snapshot_to``: the snapshot built
  lexically under ``_mut_mu`` (the stamp must be exact — op replay is
  not idempotent);
- ``wal.py``: ``replay`` truncating a torn tail, ``_read_records``
  verifying the per-record digest, ``save_term`` persisting through
  the atomic tmp+fsync+rename path.

Every matcher raises :class:`ValueError` naming the missing shape.
All extracted booleans are therefore True in a returned spec; they
exist as FIELDS so the checker's MUST-FIRE fixtures can ablate each
guard with ``dataclasses.replace`` and prove the scenario catches its
absence (the PR 16/17/18 incident shapes).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..core import Source
from ..protocol.project import FuncInfo, ProjectIndex

#: attribute spelling of the two election-state fields, in the order
#: the term rule compares them
_TERM_ATTRS = ("term", "voted_term")


@dataclass(frozen=True)
class ConsensusSpec:
    """The extracted control-plane state machine, one field per guard.

    Extraction always yields the safe value for every field; the model
    checker ablates individual fields (``dataclasses.replace``) to
    prove each MUST-FIRE incident fixture diverges without its guard.
    """

    vote_term_op: str          # ">": an equal/stale term never re-grants
    vote_log_position: bool    # §5.4.1 completeness: (seq_term, seq) >= voter's
    persist_before_grant: bool  # meta.json durable before the grant returns
    persist_before_sweep: bool  # candidacy durable before the vote sweep
    wal_before_push: bool      # leader fsyncs the batch before any follower sees it
    ack_after_replicate: bool  # entry["ok"] only after the push loop
    step_down_on_409: bool     # fenced leader deposes itself, fails the batch
    delta_term_fence: bool     # follower 409s a stale-term delta
    delta_domain_check: bool   # cross-seq-domain delta answers gap
    delta_contiguous: bool     # strict seq+1 run; first hole stops the replay
    delta_wal_append: bool     # follower logs the replayed batch before ok
    apply_term_fence: bool     # follower 409s a stale-term snapshot
    apply_dup_guard: bool      # same-domain older snapshot is a no-op
    heartbeat_domain_behind: bool  # cross-domain seq is incomparable => behind
    snapshot_stamp_exact: bool  # snapshots stamped under _mut_mu (exact seq)
    truncate_torn_tail: bool   # replay truncates a torn tail record
    term_persist_atomic: bool  # save_term goes through tmp+fsync+rename


def _fail(where: str, what: str) -> ValueError:
    return ValueError(
        f"kfconsensus extractor: {where}: {what} changed or moved; "
        "the consensus surface drifted — update "
        "kungfu_tpu/analysis/consensus/ to match (the model must "
        "never silently diverge from the code)")


def _method(index: ProjectIndex, name: str, suffix: str,
            cls: Optional[str] = None) -> FuncInfo:
    info = index.method(name, cls=cls, module_suffix=suffix)
    if info is None:
        raise _fail(f"{suffix}::{name}",
                    "the anchor method (missing or ambiguous)")
    return info


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _calls(node: ast.AST, name: str) -> List[ast.Call]:
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            fn = n.func
            simple = (fn.attr if isinstance(fn, ast.Attribute)
                      else fn.id if isinstance(fn, ast.Name) else None)
            if simple == name:
                out.append(n)
    return out


def _rpc_calls_to(node: ast.AST, route: str) -> List[ast.Call]:
    """``_rpc(base, "/replica/<x>", ...)`` call sites for one route."""
    return [c for c in _calls(node, "_rpc")
            if any(isinstance(a, ast.Constant) and a.value == route
                   for a in c.args)]


def _has_const(node: ast.AST, value) -> bool:
    return any(isinstance(n, ast.Constant) and n.value == value
               for n in ast.walk(node))


def _returns_status(node: ast.AST, status: int) -> bool:
    """A Return under ``node`` whose tuple starts with ``status``."""
    for n in ast.walk(node):
        if isinstance(n, ast.Return) and isinstance(n.value, ast.Tuple) \
                and n.value.elts and isinstance(n.value.elts[0],
                                                ast.Constant) \
                and n.value.elts[0].value == status:
            return True
    return False


# -- replica.py matchers ------------------------------------------------------

def _extract_vote(fn: FuncInfo) -> Tuple[str, bool, bool]:
    where = "replica.py::_on_vote"
    op = None
    for n in ast.walk(fn.node):
        if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and n.targets[0].id == "granted"
                and isinstance(n.value, ast.Compare)
                and isinstance(n.value.left, ast.Name)
                and n.value.left.id == "req_term"
                and len(n.value.ops) == 1):
            continue
        cmp = n.value.comparators[0]
        if (isinstance(cmp, ast.Call) and isinstance(cmp.func, ast.Name)
                and cmp.func.id == "max"
                and tuple(_self_attr(a) for a in cmp.args)
                == _TERM_ATTRS):
            op = {ast.Gt: ">", ast.GtE: ">="}.get(type(n.value.ops[0]))
    if op is None:
        raise _fail(where, "the 'granted = req_term OP max(self.term, "
                           "self.voted_term)' term rule")

    # the §5.4.1 completeness guard: mine = (self.seq_term, self.seq)
    # — ORDER matters, term dominates — then granted = theirs >= mine
    mine_ok = any(
        isinstance(n, ast.Assign) and len(n.targets) == 1
        and isinstance(n.targets[0], ast.Name)
        and n.targets[0].id == "mine"
        and isinstance(n.value, ast.Tuple)
        and tuple(_self_attr(e) for e in n.value.elts)
        == ("seq_term", "seq")
        for n in ast.walk(fn.node))
    cmp_ok = any(
        isinstance(n, ast.Assign) and len(n.targets) == 1
        and isinstance(n.targets[0], ast.Name)
        and n.targets[0].id == "granted"
        and isinstance(n.value, ast.Compare)
        and isinstance(n.value.left, ast.Name)
        and n.value.left.id == "theirs"
        and len(n.value.ops) == 1
        and isinstance(n.value.ops[0], ast.GtE)
        and isinstance(n.value.comparators[0], ast.Name)
        and n.value.comparators[0].id == "mine"
        for n in ast.walk(fn.node))
    if not (mine_ok and cmp_ok):
        raise _fail(where, "the (seq_term, seq) log-completeness guard "
                           "('mine'/'theirs >= mine')")

    saves = _calls(fn.node, "_wal_save_term")
    grants = [n for n in ast.walk(fn.node)
              if isinstance(n, ast.Return) and n.value is not None
              and _has_const(n, "granted")]
    if not saves or not grants or \
            min(s.lineno for s in saves) >= max(g.lineno for g in grants):
        raise _fail(where, "the _wal_save_term() persisted BEFORE the "
                           "grant returns")
    return op, True, True


def _extract_election(fn: FuncInfo) -> bool:
    where = "replica.py::_run_election"
    saves = _calls(fn.node, "_wal_save_term")
    sweeps = _rpc_calls_to(fn.node, "/replica/vote")
    if not saves or not sweeps or \
            min(s.lineno for s in saves) >= min(c.lineno for c in sweeps):
        raise _fail(where, "the candidacy persisted (_wal_save_term) "
                           "BEFORE the /replica/vote sweep")
    return True


def _extract_commit(fn: FuncInfo) -> Tuple[bool, bool, bool]:
    where = "replica.py::_commit"
    appends = _calls(fn.node, "_wal_append")
    pushes = _rpc_calls_to(fn.node, "/replica/apply_delta")
    acks = [n for n in ast.walk(fn.node)
            if isinstance(n, ast.Assign) and len(n.targets) == 1
            and isinstance(n.targets[0], ast.Subscript)
            and isinstance(n.targets[0].value, ast.Name)
            and n.targets[0].value.id == "entry"
            and isinstance(n.targets[0].slice, ast.Constant)
            and n.targets[0].slice.value == "ok"
            and isinstance(n.value, ast.Constant)
            and n.value.value is True]
    if not appends or not pushes:
        raise _fail(where, "the _wal_append / apply_delta push pair")
    if not acks:
        raise _fail(where, "the 'entry[\"ok\"] = True' ack")
    append_l = min(a.lineno for a in appends)
    push_l = min(p.lineno for p in pushes)
    ack_l = min(a.lineno for a in acks)
    if not append_l < push_l:
        raise _fail(where, "the log-then-replicate order (_wal_append "
                           "before the push loop)")
    if not push_l < ack_l:
        raise _fail(where, "the replicate-before-ack order (push loop "
                           "before entry[\"ok\"])")
    # fencing: `except _RPCReject` classifying e.status == 409, and an
    # `if fenced:` that steps down, fails the batch and RETURNS before
    # the ack can run
    fence_409 = any(
        isinstance(h, ast.ExceptHandler)
        and _has_const(h, 409)
        for h in ast.walk(fn.node) if isinstance(h, ast.ExceptHandler))
    depose = None
    for n in ast.walk(fn.node):
        if (isinstance(n, ast.If) and isinstance(n.test, ast.Name)
                and n.test.id == "fenced"
                and _calls(n, "_step_down") and _calls(n, "_fail")
                and any(isinstance(x, ast.Return) for b in n.body
                        for x in ast.walk(b))):
            depose = n
    if not fence_409 or depose is None or depose.lineno >= ack_l:
        raise _fail(where, "the fenced-409 step-down/fail/return path "
                           "before the ack")
    return True, True, True


def _extract_apply_delta(fn: FuncInfo) -> Tuple[bool, bool, bool, bool]:
    where = "replica.py::_on_apply_delta"
    fence = any(
        isinstance(n, ast.If) and isinstance(n.test, ast.Compare)
        and isinstance(n.test.left, ast.Name)
        and n.test.left.id == "req_term"
        and len(n.test.ops) == 1 and isinstance(n.test.ops[0], ast.Lt)
        and _self_attr(n.test.comparators[0]) == "term"
        and _returns_status(n, 409)
        for n in ast.walk(fn.node))
    if not fence:
        raise _fail(where, "the stale-term 409 fence")
    domain = any(
        isinstance(n, ast.If) and isinstance(n.test, ast.Compare)
        and isinstance(n.test.left, ast.Name)
        and n.test.left.id == "req_term"
        and len(n.test.ops) == 1
        and isinstance(n.test.ops[0], ast.NotEq)
        and _self_attr(n.test.comparators[0]) == "seq_term"
        and _has_const(n, "gap")
        for n in ast.walk(fn.node))
    if not domain:
        raise _fail(where, "the cross-seq-domain gap answer "
                           "(req_term != self.seq_term)")
    contiguous = any(
        isinstance(n, ast.Assign) and len(n.targets) == 1
        and isinstance(n.targets[0], ast.Name)
        and n.targets[0].id == "expect"
        and isinstance(n.value, ast.BinOp)
        and isinstance(n.value.op, ast.Add)
        and _self_attr(n.value.left) == "seq"
        and isinstance(n.value.right, ast.Constant)
        and n.value.right.value == 1
        for n in ast.walk(fn.node)) and any(
        isinstance(n, ast.Compare) and len(n.ops) == 1
        and isinstance(n.ops[0], ast.NotEq)
        and isinstance(n.comparators[0], ast.Name)
        and n.comparators[0].id == "expect"
        for n in ast.walk(fn.node))
    if not contiguous:
        raise _fail(where, "the strict 'expect = self.seq + 1' "
                           "contiguity run")
    if not _calls(fn.node, "_wal_append"):
        raise _fail(where, "the follower-side _wal_append of the "
                           "replayed batch")
    return True, True, True, True


def _extract_apply(fn: FuncInfo) -> Tuple[bool, bool]:
    where = "replica.py::_on_apply"
    fence = any(
        isinstance(n, ast.If) and isinstance(n.test, ast.Compare)
        and isinstance(n.test.left, ast.Name)
        and n.test.left.id == "req_term"
        and len(n.test.ops) == 1 and isinstance(n.test.ops[0], ast.Lt)
        and _self_attr(n.test.comparators[0]) == "term"
        and _returns_status(n, 409)
        for n in ast.walk(fn.node))
    if not fence:
        raise _fail(where, "the stale-term 409 fence")
    dup = any(
        isinstance(n, ast.If) and isinstance(n.test, ast.BoolOp)
        and isinstance(n.test.op, ast.And)
        and len(n.test.values) == 2
        and isinstance(n.test.values[0], ast.Compare)
        and isinstance(n.test.values[0].ops[0], ast.Eq)
        and _self_attr(n.test.values[0].comparators[0]) == "seq_term"
        and isinstance(n.test.values[1], ast.Compare)
        and isinstance(n.test.values[1].ops[0], ast.LtE)
        and _self_attr(n.test.values[1].comparators[0]) == "seq"
        for n in ast.walk(fn.node))
    if not dup:
        raise _fail(where, "the same-domain duplicate guard (req_term "
                           "== self.seq_term and req_seq <= self.seq)")
    return True, True


def _extract_heartbeat(fn: FuncInfo) -> bool:
    where = "replica.py::_on_heartbeat"
    for n in ast.walk(fn.node):
        if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and n.targets[0].id == "behind"
                and isinstance(n.value, ast.BoolOp)
                and isinstance(n.value.op, ast.Or)
                and len(n.value.values) == 2):
            continue
        first, second = n.value.values
        if (isinstance(first, ast.Compare)
                and isinstance(first.ops[0], ast.NotEq)
                and _self_attr(first.left) == "seq_term"
                and isinstance(second, ast.Compare)
                and isinstance(second.ops[0], ast.Lt)
                and _self_attr(second.left) == "seq"):
            return True
    raise _fail(where, "the domain-aware behind rule (seq_term != "
                       "req_term or seq < req seq)")


def _extract_snapshot_stamp(fn: FuncInfo) -> bool:
    """Every ``state_snapshot()`` call in ``fn`` lexically under a
    ``with ...._mut_mu:`` — the exact-stamp discipline."""
    where = f"replica.py::{fn.name}"

    hits = []

    def walk(node: ast.AST, held: bool):
        if isinstance(node, ast.With):
            for item in node.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Attribute) \
                        and ctx.attr == "_mut_mu":
                    held = True
        if isinstance(node, ast.Call):
            fnc = node.func
            if isinstance(fnc, ast.Attribute) \
                    and fnc.attr == "state_snapshot":
                hits.append(held)
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    walk(fn.node, False)
    if not hits or not all(hits):
        raise _fail(where, "the snapshot stamped under _mut_mu "
                           "(state_snapshot inside 'with ..._mut_mu:')")
    return True


# -- wal.py matchers ----------------------------------------------------------

def _extract_wal(index: ProjectIndex) -> Tuple[bool, bool]:
    replay = _method(index, "replay", "wal.py", cls="WriteAheadLog")
    if not _calls(replay.node, "truncate"):
        raise _fail("wal.py::replay", "the torn-tail truncate")
    reader = _method(index, "_read_records", "wal.py",
                     cls="WriteAheadLog")
    digest_checked = _calls(reader.node, "_digest") and any(
        isinstance(n, ast.Compare) and isinstance(n.ops[0], ast.NotEq)
        for n in ast.walk(reader.node))
    if not digest_checked:
        raise _fail("wal.py::_read_records",
                    "the per-record digest verification")
    save = _method(index, "save_term", "wal.py", cls="WriteAheadLog")
    if not _calls(save.node, "_write_atomic"):
        raise _fail("wal.py::save_term",
                    "the atomic tmp+fsync+rename persist")
    return True, True


# -- entry points -------------------------------------------------------------

def extract_consensus_spec(index: ProjectIndex) -> ConsensusSpec:
    """Extract the spec from an index holding ``elastic/replica.py``
    and ``elastic/wal.py``; raises ValueError on any drift."""
    vote = _method(index, "_on_vote", "replica.py",
                   cls="ReplicaConfigServer")
    op, log_pos, persist_grant = _extract_vote(vote)
    persist_sweep = _extract_election(
        _method(index, "_run_election", "replica.py",
                cls="ReplicaConfigServer"))
    wal_first, ack_last, depose = _extract_commit(
        _method(index, "_commit", "replica.py",
                cls="ReplicaConfigServer"))
    d_fence, d_domain, d_contig, d_wal = _extract_apply_delta(
        _method(index, "_on_apply_delta", "replica.py",
                cls="ReplicaConfigServer"))
    a_fence, a_dup = _extract_apply(
        _method(index, "_on_apply", "replica.py",
                cls="ReplicaConfigServer"))
    hb_domain = _extract_heartbeat(
        _method(index, "_on_heartbeat", "replica.py",
                cls="ReplicaConfigServer"))
    stamp = all(_extract_snapshot_stamp(
        _method(index, name, "replica.py", cls="ReplicaConfigServer"))
        for name in ("_push_state", "_push_snapshot_to",
                     "_wal_maybe_compact"))
    torn, atomic = _extract_wal(index)
    return ConsensusSpec(
        vote_term_op=op,
        vote_log_position=log_pos,
        persist_before_grant=persist_grant,
        persist_before_sweep=persist_sweep,
        wal_before_push=wal_first,
        ack_after_replicate=ack_last,
        step_down_on_409=depose,
        delta_term_fence=d_fence,
        delta_domain_check=d_domain,
        delta_contiguous=d_contig,
        delta_wal_append=d_wal,
        apply_term_fence=a_fence,
        apply_dup_guard=a_dup,
        heartbeat_domain_behind=hb_domain,
        snapshot_stamp_exact=stamp,
        truncate_torn_tail=torn,
        term_persist_atomic=atomic,
    )


def consensus_paths() -> List[str]:
    """The two source files the spec is extracted from."""
    here = os.path.dirname(os.path.abspath(__file__))
    pkg = os.path.dirname(os.path.dirname(here))
    return [os.path.join(pkg, "elastic", "replica.py"),
            os.path.join(pkg, "elastic", "wal.py")]


def default_spec() -> ConsensusSpec:
    """Extract the spec from the repo's own control plane."""
    index = ProjectIndex({p: Source.parse(p) for p in consensus_paths()})
    return extract_consensus_spec(index)
