"""Small-scope model checking of the replicated control plane.

Runs the EXTRACTED consensus spec (:mod:`.extract` — never a
hand-written twin of the code) over deterministic 2–3-replica
scenarios composing election × group-commit × crash-restart × WAL
replay, and checks the four invariants the docs/control_plane.md
honesty table claims:

1. **at-most-one-leader-per-term** — the leaders ledger never records
   two replicas leading the same term;
2. **no-double-vote** — a voter never grants the same term to two
   candidates, INCLUDING across a crash-restart (the meta.json
   fsync-before-grant ordering, PR 18);
3. **every-acked-write-survives** — any op acked to a client is
   present exactly once in the settled leader's state after any
   single crash in the scenario (replicate-before-ack, PR 16);
4. **seq-gap-freedom / convergence** — after the repair paths settle,
   every live replica's state equals the leader's, holds no duplicate
   op (replay is NOT idempotent, PR 18) and no op that was never
   issued (a torn WAL record must never replay as state, PR 17).

Scope honesty — small-scope means SMALL: replicas fail by crashing
(restartable, WAL intact) or by transiently dropping messages; there
are no symmetric network partitions. Under a partition the tier's
majority-of-responding elections are documented unsafe
(`elastic/replica.py` module docstring, docs/control_plane.md) — a
model that "proved" safety there would be lying, so the scope stops
where the implementation's claims stop.

Everything is single-threaded and deterministic: scenarios enumerate
crash points, message-loss windows and candidacy orders explicitly
instead of sampling thread schedules, the `explore.py` precedent.

MUST-FIRE fixtures: :data:`ABLATIONS` maps each incident shape to the
spec field whose guard prevents it. ``explore_consensus`` over an
ablated spec must produce at least one violation (with a trace); the
CLI and tests enforce both directions.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .extract import ConsensusSpec

#: state marker for a torn WAL record replayed without truncation —
#: never in any issued-op set, so invariant 4 flags it on sight
CORRUPT = "⊥"

#: heartbeat/repair rounds the settle phase runs; 3 covers the longest
#: repair chain a scenario can produce (gap -> snapshot -> converge)
_SETTLE_ROUNDS = 3


@dataclass
class Violation:
    """One invariant breach, with the event history that led to it."""

    invariant: str
    scenario: str
    detail: str
    history: List[str] = field(default_factory=list)

    def trace(self) -> str:
        lines = [f"invariant violated: {self.invariant}",
                 f"  scenario: {self.scenario}",
                 f"  detail:   {self.detail}",
                 "  history:"]
        lines += [f"    {i:3d}. {ev}" for i, ev in
                  enumerate(self.history, 1)]
        return "\n".join(lines)


class MWal:
    """A replica's durable state: meta (term, voted_term), snapshot,
    delta-log records, and whether the tail record is torn."""

    def __init__(self):
        self.term = 0
        self.voted_term = 0
        self.snapshot: Optional[Tuple[int, int, Tuple[str, ...]]] = None
        self.log: List[Tuple[int, Tuple[Tuple[int, str], ...]]] = []
        self.torn = False  # last log record cut mid-append

    def save_term(self, term: int, voted: int) -> None:
        self.term, self.voted_term = term, voted

    def save_snapshot(self, seq_term: int, seq: int,
                      state: Tuple[str, ...]) -> None:
        # durable snapshot supersedes the log (wal.save_snapshot
        # truncates after the snapshot is on disk)
        self.snapshot = (seq_term, seq, state)
        self.log = []
        self.torn = False


class MReplica:
    """One replica of the modeled tier."""

    def __init__(self, idx: int, world: "World"):
        self.idx = idx
        self.world = world
        self.spec = world.spec
        self.alive = True
        self.unreachable = False  # transient: drops its messages
        self.wal = MWal()
        self.term = 0
        self.voted_term = 0
        self.role = "follower"
        self.seq = 0
        self.seq_term = 0
        self.state: Tuple[str, ...] = ()

    # -- helpers -------------------------------------------------------------

    @property
    def reachable(self) -> bool:
        return self.alive and not self.unreachable

    def others(self) -> List["MReplica"]:
        return [r for r in self.world.replicas if r is not self]

    def log(self, ev: str) -> None:
        self.world.log(f"r{self.idx}: {ev}")

    # -- election ------------------------------------------------------------

    def on_vote(self, term: int, cand: int, cseq: int,
                cseq_term: int) -> Dict:
        sp = self.spec
        if sp.vote_term_op == ">":
            granted = term > max(self.term, self.voted_term)
        else:  # ablated: an equal term re-grants
            granted = term >= max(self.term, self.voted_term)
        if granted and sp.vote_log_position:
            # §5.4.1 completeness: refuse a candidate behind our log
            granted = (cseq_term, cseq) >= (self.seq_term, self.seq)
        changed = term > self.term or granted
        if granted:
            self.voted_term = term
            if self.role == "leader":
                self.role = "follower"
        self.term = max(self.term, term)
        if changed and sp.persist_before_grant:
            # durable BEFORE the candidate hears the grant
            self.wal.save_term(self.term, self.voted_term)
        if granted:
            self.world.record_vote(self.idx, term, cand)
        self.log(f"vote req t={term} from r{cand}: "
                 f"{'granted' if granted else 'refused'}")
        return {"granted": granted, "term": self.term}

    def run_election(self, crash_mid_sweep: bool = False) -> bool:
        """One candidacy, mirroring ``_run_election``. Returns True
        when this replica became leader."""
        sp = self.spec
        if not self.alive or self.role == "leader":
            return False
        term = self.term + 1
        self.voted_term = max(self.voted_term, term)  # vote for self
        self.world.record_vote(self.idx, term, self.idx)
        if sp.persist_before_sweep:
            # candidacy durable before anyone hears it — a forgotten
            # self-vote could re-vote differently at this term
            self.wal.save_term(self.term, self.voted_term)
        self.log(f"candidacy t={term}")
        if crash_mid_sweep:
            self.log(f"CRASH mid-candidacy t={term}")
            self.crash()
            return False
        votes = reachable = 1  # self
        for r in self.others():
            if not r.reachable:
                continue  # unreachable abstains (majority-of-responding)
            out = r.on_vote(term, self.idx, self.seq, self.seq_term)
            reachable += 1
            if out["granted"]:
                votes += 1
            if out["term"] > term:
                self.term = max(self.term, out["term"])
                return False  # someone is ahead; follow them
        if votes >= reachable // 2 + 1:
            self._become_leader(term)
            return True
        self.term = max(self.term, term)
        self.log(f"lost t={term} ({votes}/{reachable})")
        return False

    def _become_leader(self, term: int) -> None:
        self.term = term
        self.role = "leader"
        self.world.record_leader(term, self.idx)
        self.log(f"LEADER t={term}")
        # takeover catch-up: full snapshot at the new term so every
        # follower converges onto the new seq domain
        self.push_state()

    def step_down(self, term: int) -> None:
        self.term = max(self.term, term)
        if self.role == "leader":
            self.role = "follower"
            self.log(f"deposed at t={term}")

    # -- crash / restart -----------------------------------------------------

    def crash(self) -> None:
        self.alive = False
        self.role = "dead"
        self.log("crash")

    def restart(self) -> None:
        """WAL replay, mirroring ``_recover_from_wal``/``wal.replay``."""
        sp = self.spec
        self.alive = True
        self.unreachable = False
        self.role = "follower"
        self.term = self.wal.term
        self.voted_term = self.wal.voted_term
        if self.wal.snapshot is not None:
            self.seq_term, self.seq, self.state = self.wal.snapshot
        else:
            self.seq = self.seq_term = 0
            self.state = ()
        log = list(self.wal.log)
        if self.wal.torn and log:
            if sp.truncate_torn_tail:
                # torn tail truncated: the op was never acked, the
                # clean prefix is the durable truth
                log = log[:-1]
                self.wal.log = list(log)
                self.wal.torn = False
                self.log("replay: torn tail truncated")
            else:
                # ABLATED: the torn record advances seq but its op
                # bytes are unreadable — a corrupt projection
                t, ops = log[-1]
                log[-1] = (t, tuple((s, CORRUPT) for s, _ in ops))
                self.log("replay: torn tail REPLAYED (ablated)")
        for t, ops in log:
            for s, op in ops:
                if s > self.seq:
                    self.state += (op,)
                    self.seq = s
                    self.seq_term = t
        self.log(f"restart: replayed seq={self.seq} "
                 f"dom={self.seq_term} t={self.term}")

    # -- replication: leader side --------------------------------------------

    def client_write(self, op: str, crash_after: Optional[int] = None,
                     ) -> bool:
        """One group-commit of one op, mirroring ``_on_mutation`` +
        ``_commit``. ``crash_after`` kills the leader after that many
        commit steps (0 = right after the local apply). Returns True
        when the write was acked."""
        sp = self.spec
        w = self.world
        w.issued.add(op)
        if not self.alive or self.role != "leader":
            return False
        # local apply + seq assignment (the _mut_mu critical section)
        self.seq += 1
        self.seq_term = self.term
        self.state += (op,)
        batch = ((self.seq, op),)
        term = self.term
        acked = []

        def do_wal():
            self.wal.log.append((term, batch))
            self.log(f"wal append {op}")

        def do_push():
            fenced = 0
            for r in self.others():
                if not r.reachable:
                    self.log(f"push {op}: r{r.idx} unreachable, skipped")
                    continue
                out = r.on_apply_delta(term, self.idx, batch)
                if out.get("status") == 409:
                    fenced = max(fenced, out["term"])
                elif out.get("gap"):
                    self.push_snapshot_to(r)
            return fenced

        def do_ack():
            w.acked.append(op)
            acked.append(op)
            self.log(f"ACK {op}")

        # replicate-before-ack, log-then-replicate — or the ablated
        # orders the incidents shipped with
        if not sp.ack_after_replicate:
            steps = [("ack", do_ack), ("wal", do_wal), ("push", do_push)]
        elif sp.wal_before_push:
            steps = [("wal", do_wal), ("push", do_push), ("ack", do_ack)]
        else:
            steps = [("push", do_push), ("ack", do_ack), ("wal", do_wal)]
        for i, (name, step) in enumerate(steps):
            fenced = step() if name == "push" else (step() or 0)
            if name == "push" and fenced and sp.step_down_on_409:
                # term fencing: we are deposed — fail, never ack
                self.step_down(fenced)
                self.log(f"write {op} failed (fenced t={fenced})")
                return bool(acked)
            if crash_after is not None and crash_after == i + 1:
                self.log(f"CRASH after step '{name}' of {op}")
                self.crash()
                return bool(acked)
        return bool(acked)

    def torn_write(self, op: str) -> None:
        """Crash DURING the WAL append of ``op``: the record's length
        prefix landed, the payload did not. Never pushed, never
        acked."""
        self.world.issued.add(op)
        self.seq += 1
        self.seq_term = self.term
        self.state += (op,)
        self.wal.log.append((self.term, ((self.seq, op),)))
        self.wal.torn = True
        self.log(f"CRASH mid-append of {op} (torn tail)")
        self.crash()

    def push_state(self) -> None:
        """Full-snapshot push to every follower (``_push_state``):
        seq bump + snapshot, stamped atomically."""
        if self.role != "leader" or not self.alive:
            return
        self.seq += 1
        self.seq_term = self.term
        stamp = (self.term, self.seq, self.state)
        self.wal.save_snapshot(self.term, self.seq, self.state)
        self.log(f"full push t={self.term} seq={self.seq}")
        fenced = 0
        for r in self.others():
            if not r.reachable:
                continue
            out = r.on_apply(stamp[0], stamp[1], stamp[2], self.idx)
            if out.get("status") == 409:
                fenced = max(fenced, out["term"])
        if fenced:
            self.step_down(fenced)

    def push_snapshot_to(self, r: "MReplica") -> None:
        """Repair ONE follower (``_push_snapshot_to``) — exact stamp,
        no bump."""
        if self.role != "leader" or not r.reachable:
            return
        out = r.on_apply(self.seq_term, self.seq, self.state, self.idx)
        if out.get("status") == 409:
            self.step_down(out["term"])

    def racing_full_push(self, op: str, to: "MReplica") -> None:
        """A full-snapshot repair with a client write racing it — the
        op-replay-non-idempotence shape. With the exact-stamp guard
        (``_mut_mu``) the snapshot closes BEFORE the write applies;
        ablated, the write slips inside the stamp window and the
        follower replays it twice."""
        sp = self.spec
        w = self.world
        w.issued.add(op)
        self.seq += 1
        self.seq_term = self.term
        stamp_seq = self.seq
        base_state = self.state

        def apply_write():
            self.seq += 1
            self.state += (op,)
            self.wal.log.append((self.term, ((self.seq, op),)))
            w.acked.append(op)
            self.log(f"ACK {op} (racing the snapshot)")

        if sp.snapshot_stamp_exact:
            snap_state = base_state  # stamped under _mut_mu: exact
            apply_write()
        else:  # ABLATED: the racing op is inside the stamped state
            apply_write()
            snap_state = self.state
        to.on_apply(self.term, stamp_seq, snap_state, self.idx)
        to.on_apply_delta(self.term, self.idx, ((self.seq, op),))

    def heartbeat(self) -> None:
        """One leader heartbeat round (``_heartbeat``): any follower
        answering behind gets a full push."""
        if self.role != "leader" or not self.alive:
            return
        behind = False
        for r in self.others():
            if not r.reachable:
                continue
            out = r.on_heartbeat(self.term, self.seq, self.idx)
            if out.get("status") == 409:
                self.step_down(out["term"])
                return
            if out.get("behind"):
                behind = True
        if behind:
            self.push_state()

    # -- replication: follower side ------------------------------------------

    def on_apply_delta(self, term: int, leader: int,
                       batch: Tuple[Tuple[int, str], ...]) -> Dict:
        sp = self.spec
        if sp.delta_term_fence and term < self.term:
            return {"status": 409, "term": self.term}
        self.term = term  # ablated fence: a stale push LOWERS the term
        if self.role == "leader" and leader != self.idx:
            self.role = "follower"
        if sp.delta_domain_check and term != self.seq_term:
            self.log(f"delta t={term}: gap (domain {self.seq_term})")
            return {"gap": True, "seq": self.seq}
        fresh = [(s, op) for s, op in batch if s > self.seq]
        if not fresh:
            return {"ok": True, "seq": self.seq}
        run: List[Tuple[int, str]] = []
        if sp.delta_contiguous:
            expect = self.seq + 1
            for s, op in fresh:
                if s != expect:
                    break  # a full-push bump consumed a seq
                run.append((s, op))
                expect += 1
            if not run:
                self.log(f"delta t={term}: gap (expect {self.seq + 1})")
                return {"gap": True, "seq": self.seq}
        else:  # ABLATED: holes replay silently
            run = fresh
        gap = len(run) < len(fresh)
        for s, op in run:
            self.state += (op,)
            self.seq = s
        if sp.delta_wal_append:
            self.wal.log.append((term, tuple(run)))
        self.log(f"delta t={term}: applied "
                 f"{','.join(op for _, op in run)} seq={self.seq}")
        if gap:
            return {"gap": True, "seq": self.seq}
        return {"ok": True, "seq": self.seq}

    def on_apply(self, seq_term: int, seq: int, state: Tuple[str, ...],
                 leader: int) -> Dict:
        sp = self.spec
        if sp.apply_term_fence and seq_term < self.term:
            return {"status": 409, "term": self.term}
        self.term = seq_term
        if self.role == "leader" and leader != self.idx:
            self.role = "follower"
        if sp.apply_dup_guard and seq_term == self.seq_term \
                and seq <= self.seq:
            return {"ok": True, "seq": self.seq}  # ours is newer
        self.seq = seq
        self.seq_term = seq_term
        self.state = state
        self.wal.save_snapshot(seq_term, seq, state)
        self.log(f"snapshot t={seq_term} seq={seq} adopted")
        return {"ok": True, "seq": seq}

    def on_heartbeat(self, term: int, seq: int, leader: int) -> Dict:
        sp = self.spec
        if term < self.term:
            return {"status": 409, "term": self.term}
        self.term = term
        if self.role == "leader" and leader != self.idx:
            self.role = "follower"
        if sp.heartbeat_domain_behind:
            # a seq from another domain is incomparable: behind until
            # that leader's snapshot lands, whatever the numbers say
            behind = self.seq_term != term or self.seq < seq
        else:  # ABLATED: numeric compare only
            behind = self.seq < seq
        return {"behind": behind, "term": term}


class World:
    """The tier plus the god's-eye ledgers the invariants read."""

    def __init__(self, n: int, spec: ConsensusSpec, scenario: str):
        self.spec = spec
        self.scenario = scenario
        self.replicas = [MReplica(i, self) for i in range(n)]
        self.leaders: Dict[int, set] = {}   # term -> replica idxs
        self.votes: Dict[Tuple[int, int], set] = {}  # (voter, term)
        self.acked: List[str] = []
        self.issued: set = set()
        self.history: List[str] = []
        self.violations: List[Violation] = []

    def log(self, ev: str) -> None:
        self.history.append(ev)

    def violate(self, invariant: str, detail: str) -> None:
        self.violations.append(Violation(
            invariant, self.scenario, detail, list(self.history)))

    def record_leader(self, term: int, idx: int) -> None:
        self.leaders.setdefault(term, set()).add(idx)
        if len(self.leaders[term]) > 1:
            self.violate(
                "at-most-one-leader-per-term",
                f"term {term} led by replicas "
                f"{sorted(self.leaders[term])}")

    def record_vote(self, voter: int, term: int, cand: int) -> None:
        key = (voter, term)
        self.votes.setdefault(key, set()).add(cand)
        if len(self.votes[key]) > 1:
            self.violate(
                "no-double-vote",
                f"r{voter} granted term {term} to candidates "
                f"{sorted(self.votes[key])}")

    # -- driving -------------------------------------------------------------

    def elect_someone(self, order: List[int]) -> Optional[MReplica]:
        """Candidacies in ``order`` until the tier has a leader —
        the staggered-timeout election loop, with the stand-first
        order made an explicit scenario parameter."""
        for _ in range(2):  # a lost round retries at a higher term
            for i in order:
                r = self.replicas[i]
                if r.reachable and r.run_election():
                    return r
        return None

    def leader(self) -> Optional[MReplica]:
        live = [r for r in self.replicas
                if r.alive and r.role == "leader"]
        if not live:
            return None
        return max(live, key=lambda r: r.term)

    def settle(self) -> None:
        """Heartbeat/repair rounds until the tier converges (bounded)."""
        for _ in range(_SETTLE_ROUNDS):
            led = self.leader()
            if led is not None:
                led.heartbeat()

    # -- invariant sweep -----------------------------------------------------

    def check(self) -> List[Violation]:
        led = self.leader()
        if led is not None:
            for op in self.acked:
                n = led.state.count(op)
                if n == 0:
                    self.violate(
                        "every-acked-write-survives",
                        f"acked op {op} missing from leader "
                        f"r{led.idx}'s settled state {led.state}")
                elif n > 1:
                    self.violate(
                        "every-acked-write-survives",
                        f"acked op {op} applied {n}x on leader "
                        f"r{led.idx} (replay is not idempotent)")
            for r in self.replicas:
                if not r.reachable:
                    continue
                if r is not led and r.state != led.state:
                    self.violate(
                        "seq-gap-freedom",
                        f"r{r.idx} settled at {r.state}, leader "
                        f"r{led.idx} at {led.state}")
                for op in r.state:
                    if op not in self.issued:
                        self.violate(
                            "seq-gap-freedom",
                            f"r{r.idx} state holds {op!r}, which no "
                            "client ever issued (corrupt replay)")
                    elif r.state.count(op) > 1:
                        self.violate(
                            "seq-gap-freedom",
                            f"r{r.idx} applied {op} "
                            f"{r.state.count(op)}x")
        return self.violations


# -- scenarios ----------------------------------------------------------------
#
# Each scenario is (name, fn(spec, n) -> World-after-run). They are
# deterministic compositions of the fault windows the tier claims to
# survive; ``explore_consensus`` runs every scenario at every tier
# size and sweeps the invariants.

ScenarioFn = Callable[[ConsensusSpec, int], World]


def s_election_race(spec: ConsensusSpec, n: int) -> World:
    """Two candidacies racing for the SAME term: r1 misses r0's sweep
    (transient loss) and stands at the term r0 already won."""
    w = World(n, spec, f"election-race/n={n}")
    w.replicas[1].unreachable = True
    w.replicas[0].run_election()
    w.replicas[1].unreachable = False
    w.replicas[1].run_election()
    w.settle()
    w.check()
    return w


def s_voter_restart(spec: ConsensusSpec, n: int) -> World:
    """A voter grants, crash-restarts, and is asked again at the SAME
    term by a different candidate (PR 18 double-vote)."""
    w = World(n, spec, f"voter-restart/n={n}")
    if n >= 3:
        w.replicas[2].unreachable = True  # r2 never hears term 1
    w.replicas[1].run_election()  # r0 grants r1 term 1
    w.replicas[0].crash()
    w.replicas[0].restart()
    if n >= 3:
        w.replicas[2].unreachable = False
        w.replicas[2].run_election()  # stands at term 1 again
    else:
        w.replicas[0].run_election()  # its own candidacy post-restart
    w.settle()
    w.check()
    return w


def s_candidacy_amnesia(spec: ConsensusSpec, n: int) -> World:
    """A candidate self-votes, crashes before the sweep, restarts —
    then another candidate asks for the same term."""
    w = World(n, spec, f"candidacy-amnesia/n={n}")
    w.replicas[0].run_election(crash_mid_sweep=True)
    w.replicas[0].restart()
    w.replicas[1].run_election()  # term 1 again; r0 must refuse
    if w.leader() is None:
        w.replicas[1].run_election()  # retry at a fresh term
    w.settle()
    w.check()
    return w


def s_commit_crash(spec: ConsensusSpec, n: int) -> World:
    """The leader dies at every commit step of an in-flight write,
    restarts, and the tier re-elects in every stand-first order."""
    last = None
    for crash_after in (1, 2, 3):
        for first in range(n):
            name = (f"commit-crash/n={n}/after-step-{crash_after}"
                    f"/stands-first=r{first}")
            w = World(n, spec, name)
            w.elect_someone([0])
            w.replicas[0].client_write("w1")
            w.replicas[0].client_write("w2", crash_after=crash_after)
            w.replicas[0].restart()
            w.elect_someone([first] + [i for i in range(n)
                                       if i != first])
            w.settle()
            w.check()
            if w.violations:
                return w
            last = w
    return last


def s_unreachable_commit(spec: ConsensusSpec, n: int) -> World:
    """ONE follower transiently drops the push window, the write acks
    on the responding majority, then the leader crashes. The leader's
    own WAL — written BEFORE the push — plus the §5.4.1 completeness
    guard are all that stand between the acked op and oblivion.
    (Exactly one fault window + one crash: making EVERY follower deaf
    is a multi-fault run where majority-of-responding is documented
    unsafe — see the module docstring's scope honesty note.)"""
    last = None
    for crash_after in (2, 3):
        for first in range(n):
            name = (f"unreachable-commit/n={n}/after-step-"
                    f"{crash_after}/stands-first=r{first}")
            w = World(n, spec, name)
            w.elect_someone([0])
            w.replicas[0].client_write("w1")
            deaf = w.replicas[1]
            deaf.unreachable = True
            w.replicas[0].client_write("w2", crash_after=crash_after)
            deaf.unreachable = False
            w.replicas[0].restart()
            w.elect_someone([first] + [i for i in range(n)
                                       if i != first])
            w.settle()
            w.check()
            if w.violations:
                return w
            last = w
    return last


def s_stale_leader(spec: ConsensusSpec, n: int) -> World:
    """A deposed-but-unaware leader keeps pushing at its old term
    (PR 16 incident): the followers' 409 fence must depose it before
    it acks anything the new history will erase."""
    w = World(n, spec, f"stale-leader/n={n}")
    w.elect_someone([0])
    w.replicas[0].client_write("w1")
    # r0 goes transiently deaf; the rest elect a new leader and move on
    w.replicas[0].unreachable = True
    w.elect_someone([1])
    w.replicas[1].client_write("v1")
    w.replicas[0].unreachable = False
    # a client still bound to r0 writes through the stale leader
    w.replicas[0].client_write("w2")
    w.settle()
    w.check()
    return w


def s_domain_repair(spec: ConsensusSpec, n: int) -> World:
    """PR 17 incident: a restarted replica rejoins with an OLD-term
    seq numerically equal to the new leader's. Only the domain-aware
    ``behind`` rule gets it repaired."""
    w = World(n, spec, f"domain-repair/n={n}")
    w.elect_someone([0])
    w.replicas[0].client_write("w1")
    # crash right after the WAL append: seq advanced on r0 alone
    w.replicas[0].client_write("w2", crash_after=1)
    w.elect_someone([1])  # new leader bumps onto a fresh seq domain
    w.replicas[0].restart()
    w.settle()
    w.check()
    return w


def s_delta_gap(spec: ConsensusSpec, n: int) -> World:
    """A follower misses one delta window; the next delta must answer
    gap and trigger the snapshot repair, not replay around the hole."""
    w = World(n, spec, f"delta-gap/n={n}")
    w.elect_someone([0])
    w.replicas[0].client_write("w1")
    w.replicas[n - 1].unreachable = True
    w.replicas[0].client_write("w2")
    w.replicas[n - 1].unreachable = False
    w.replicas[0].client_write("w3")
    w.settle()
    w.check()
    return w


def s_whole_tier(spec: ConsensusSpec, n: int) -> World:
    """Whole-tier death and WAL rejoin, with and without a torn tail
    on the old leader (PR 17/18 durable-control-plane shape)."""
    last = None
    for torn in (False, True):
        for first in range(n):
            name = (f"whole-tier/n={n}/torn={int(torn)}"
                    f"/stands-first=r{first}")
            w = World(n, spec, name)
            w.elect_someone([0])
            w.replicas[0].client_write("w1")
            w.replicas[0].client_write("w2")
            if torn:
                w.replicas[0].torn_write("w3")
            else:
                w.replicas[0].crash()
            for r in w.replicas[0].others():
                r.crash()
            for r in w.replicas:
                r.restart()
            w.elect_someone([first] + [i for i in range(n)
                                       if i != first])
            w.settle()
            w.check()
            if w.violations:
                return w
            last = w
    return last


def s_racing_snapshot(spec: ConsensusSpec, n: int) -> World:
    """A snapshot repair racing a client write: the stamp must be
    exact or the follower replays the racing op twice (PR 18
    non-idempotent-replay shape)."""
    w = World(n, spec, f"racing-snapshot/n={n}")
    w.elect_someone([0])
    straggler = w.replicas[n - 1]
    straggler.unreachable = True
    w.replicas[0].client_write("w1")
    straggler.unreachable = False
    w.replicas[0].racing_full_push("w2", to=straggler)
    w.settle()
    w.check()
    return w


SCENARIOS: List[Tuple[str, ScenarioFn]] = [
    ("election-race", s_election_race),
    ("voter-restart", s_voter_restart),
    ("candidacy-amnesia", s_candidacy_amnesia),
    ("commit-crash", s_commit_crash),
    ("unreachable-commit", s_unreachable_commit),
    ("stale-leader", s_stale_leader),
    ("domain-repair", s_domain_repair),
    ("delta-gap", s_delta_gap),
    ("whole-tier", s_whole_tier),
    ("racing-snapshot", s_racing_snapshot),
]

#: MUST-FIRE fixtures: incident name -> the spec ablation that revives
#: it. ``explore_consensus(ablate(spec, name))`` must produce at least
#: one violation — a fixture that stops firing means the model lost
#: the scenario that catches the incident. (delta_domain_check,
#: apply_term_fence, apply_dup_guard, delta_wal_append and
#: term_persist_atomic are extracted and modeled but have no dedicated
#: ablation: within the crash-only scope their failure shapes are
#: subsumed by the contiguity/fence/completeness fixtures below.)
ABLATIONS: Dict[str, Dict] = {
    "vote-term-op": {"vote_term_op": ">="},
    "double-vote": {"persist_before_grant": False},
    "candidacy-amnesia": {"persist_before_sweep": False},
    "vote-completeness": {"vote_log_position": False},
    "ack-before-replicate": {"ack_after_replicate": False},
    "wal-before-push": {"wal_before_push": False},
    "stale-leader-409": {"step_down_on_409": False},
    "delta-term-fence": {"delta_term_fence": False},
    "delta-contiguity": {"delta_contiguous": False},
    "seq-domain-repair": {"heartbeat_domain_behind": False},
    "torn-tail": {"truncate_torn_tail": False},
    "replay-idempotence": {"snapshot_stamp_exact": False},
}


def ablate(spec: ConsensusSpec, name: str) -> ConsensusSpec:
    return dataclasses.replace(spec, **ABLATIONS[name])


def explore_consensus(spec: ConsensusSpec,
                      scope: Tuple[int, ...] = (2, 3)
                      ) -> List[Violation]:
    """Run every scenario at every tier size; return all violations."""
    out: List[Violation] = []
    for n in scope:
        for _, fn in SCENARIOS:
            out.extend(fn(spec, n).violations)
    return out
