"""kfconsensus CLI: ``python -m kungfu_tpu.analysis.consensus``.

The consensus gate, in one run:

1. **extract** — lift the election/replication state machine out of
   ``elastic/replica.py`` + ``elastic/wal.py``; ANY drift between the
   code and the shapes the extractor matches aborts the run loudly
   (exit 1) — a model of code it no longer mirrors proves nothing;
2. **must-hold** — every 2–3-replica interleaving of election ×
   group-commit × crash-restart × WAL replay upholds the four
   invariants (at-most-one-leader-per-term, no double vote across
   restarts, every acked write survives a single crash, follower
   seq-gap freedom);
3. **must-fire** — re-run the scope once per ablation with exactly
   one guard removed (the PR 16/17/18 incident shapes); an ablation
   that produces NO divergence means the model lost the very hazard
   the guard exists for, and fails the gate just as hard.

Violations and silent ablations surface as kflint-style findings, so
``--json`` / ``--baseline`` ride the same stable-ID machinery as
``python -m kungfu_tpu.analysis`` and CI diffs instead of gating on
absolute counts. The committed baseline lives at
``scripts/kfconsensus_baseline.json`` (empty: the gate is clean).

``--show ABLATION`` prints the first divergence trace for one
ablation — the incident replay, step by step.

Exit status: 0 clean, 1 violations / silent ablations / drift /
new-vs-baseline, 2 usage errors.
"""

from __future__ import annotations

import argparse
import sys
from types import SimpleNamespace

from ..core import Finding
from ..__main__ import (diff_baseline, finding_id, load_baseline,
                        to_json)
from .extract import consensus_paths, default_spec
from .model import ABLATIONS, SCENARIOS, ablate, explore_consensus

#: findings anchor on the file whose guard the violation concerns
_ANCHOR = "kungfu_tpu/elastic/replica.py"

_PASSES = (SimpleNamespace(name="consensus-model"),
           SimpleNamespace(name="consensus-ablation"))


def _model_findings(violations) -> list:
    out = []
    for v in violations:
        out.append(Finding(
            path=_ANCHOR, line=1, pass_name="consensus-model",
            message=f"{v.invariant} violated in scenario "
                    f"{v.scenario}: {v.detail}"))
    return out


def _ablation_findings(silent) -> list:
    return [Finding(
        path=_ANCHOR, line=1, pass_name="consensus-ablation",
        message=f"MUST-FIRE ablation {name!r} produced no divergence "
                "— the model no longer exercises the hazard this "
                "guard exists for")
        for name in silent]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kungfu_tpu.analysis.consensus",
        description="kfconsensus: small-scope model checking of the "
                    "replicated control plane against the spec "
                    "extracted from elastic/replica.py + wal.py "
                    "(see docs/static_analysis.md)")
    ap.add_argument("--scope", default="2,3", metavar="N[,N...]",
                    help="replica counts to explore (default: 2,3)")
    ap.add_argument("--list", action="store_true", dest="list_parts",
                    help="list scenarios and must-fire ablations, "
                         "then exit")
    ap.add_argument("--show", metavar="ABLATION",
                    help="print the first divergence trace for one "
                         "ablation and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings with stable IDs")
    ap.add_argument("--baseline", metavar="FILE",
                    help="diff findings against a committed baseline: "
                         "exit 1 only on NEW finding IDs")
    args = ap.parse_args(argv)

    if args.list_parts:
        print("scenarios:")
        for name, fn in SCENARIOS:
            doc = (fn.__doc__ or "").strip().split("\n")[0]
            print(f"  {name:24s} {doc}")
        print("must-fire ablations:")
        for name in ABLATIONS:
            print(f"  {name}")
        return 0

    try:
        scope = tuple(int(x) for x in args.scope.split(",") if x)
    except ValueError:
        print(f"kfconsensus: bad --scope {args.scope!r} (want e.g. "
              "2,3)", file=sys.stderr)
        return 2
    if not scope or any(n < 2 or n > 3 for n in scope):
        print("kfconsensus: --scope entries must be 2 or 3 (the "
              "small-scope hypothesis is argued for that range only)",
              file=sys.stderr)
        return 2

    try:
        spec = default_spec()
    except (ValueError, OSError) as e:
        # drift: the code moved out from under the model — that is a
        # gate failure, never a skip
        print(f"kfconsensus: {e}", file=sys.stderr)
        return 1
    print(f"kfconsensus: extracted consensus spec from "
          f"{', '.join(consensus_paths())}", file=sys.stderr)

    if args.show:
        if args.show not in ABLATIONS:
            print(f"kfconsensus: unknown ablation {args.show!r} "
                  f"(known: {', '.join(sorted(ABLATIONS))})",
                  file=sys.stderr)
            return 2
        violations = explore_consensus(ablate(spec, args.show),
                                       scope=scope)
        if not violations:
            print(f"kfconsensus: ablation {args.show!r} produced no "
                  "divergence", file=sys.stderr)
            return 1
        print(violations[0].trace())
        return 0

    findings = _model_findings(explore_consensus(spec, scope=scope))
    silent = []
    for name in ABLATIONS:
        if not explore_consensus(ablate(spec, name), scope=scope):
            silent.append(name)
    findings.extend(_ablation_findings(silent))

    new = fixed = None
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"kfconsensus: cannot read baseline "
                  f"{args.baseline}: {e}", file=sys.stderr)
            return 2  # an unreadable baseline must not green the gate
        new, fixed = diff_baseline({finding_id(f) for f in findings},
                                   baseline)

    if args.as_json:
        print(to_json(findings, _PASSES, new, fixed))
    else:
        for f in findings:
            marker = ""
            if new is not None:
                marker = ("" if finding_id(f) in new
                          else " [baseline]")
            print(f"{f}{marker}")

    n_abl = len(ABLATIONS)
    summary = (f"{len(findings)} finding(s); scope={scope}; "
               f"{n_abl - len(silent)}/{n_abl} ablations fired")
    if args.baseline:
        if fixed:
            print(f"kfconsensus: {len(fixed)} baseline finding(s) "
                  "fixed — regenerate the baseline to ratchet",
                  file=sys.stderr)
        if new:
            print(f"kfconsensus: {len(new)} NEW finding(s) vs "
                  f"baseline ({summary})", file=sys.stderr)
            return 1
        print(f"kfconsensus: no new findings vs baseline ({summary})",
              file=sys.stderr)
        return 0
    if findings:
        print(f"kfconsensus: {summary}", file=sys.stderr)
        return 1
    print(f"kfconsensus: clean ({summary})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
