"""Static passes for the replicated control plane's safety rules.

Three rules the PR 16–18 consensus surface relies on, each pinned
today by exactly one example test, promoted to whole-tree lint:

- **ack-ordering**: a handler that logs a replicated mutation
  (``_on_mutation``) must do it under ``_mut_mu`` (log order ==
  application order), must KEEP the returned wait-callable, and must
  not send a success reply that isn't dominated by a call to it —
  replicate-before-ack is a dataflow property, and a new mutation
  route added without the wait would ack writes a leader crash loses
  (the PR 16 incident);
- **term-fence**: a replica handler that reads a term out of a peer
  message and then mutates consensus state must compare that term
  against its own state FIRST — an unfenced handler lets a stale
  leader rewrite a newer history (the 409 fence, PR 16);
- **handler-exception-safety**: an HTTP handler class serving
  keep-alive connections (``protocol_version = "HTTP/1.1"``) must
  firewall every ``do_*`` entry with a broad except that still sends
  a reply — an escaped exception kills the handler thread without a
  response and the pooled client (peer.py keeps connections hot)
  blocks on the dead read until its timeout. Plain HTTP/1.0 handlers
  close the connection per request and are out of scope: the client
  sees the close, not a hang.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..core import Finding, Source, dotted_name

#: calls that count as sending something back on the wire
_REPLY_CALLS = {"_reply", "send_error", "send_response"}


def _own_scope(fn: ast.AST):
    """Statements/expressions of ``fn`` excluding nested defs (each
    function is analyzed in its own scope)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _strip_doc(body: List[ast.stmt]) -> List[ast.stmt]:
    if body and isinstance(body[0], ast.Expr) \
            and isinstance(body[0].value, ast.Constant) \
            and isinstance(body[0].value.value, str):
        return body[1:]
    return body


class AckOrderingPass:
    name = "ack-ordering"
    doc = ("mutation handlers whose success reply is not dominated by "
           "the _on_mutation replication wait")

    def run(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_fn(src, node))
        return findings

    def _check_fn(self, src: Source, fn: ast.AST) -> List[Finding]:
        # own-scope statements only; collect the _on_mutation calls,
        # whether each is under a `with ..._mut_mu:`, which names bind
        # their results, and every _reply site
        muts: List[Tuple[ast.Call, bool]] = []
        bound: set = set()
        discarded: List[ast.AST] = []
        wait_calls: List[int] = []
        replies: List[ast.Call] = []

        def walk(node: ast.AST, held: bool):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return
            if isinstance(node, ast.With):
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Attribute) \
                            and ctx.attr == "_mut_mu":
                        held = True
            if isinstance(node, ast.Assign):
                calls = [c for c in ast.walk(node.value)
                         if self._is_mutation_call(c)]
                if calls and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    bound.add(node.targets[0].id)
            if isinstance(node, ast.Expr) \
                    and self._is_mutation_call(node.value):
                discarded.append(node)
            if isinstance(node, ast.Call):
                if self._is_mutation_call(node):
                    muts.append((node, held))
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "_reply":
                    replies.append(node)
                if isinstance(f, ast.Name):
                    wait_calls.append((f.id, node.lineno))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        walk(fn, False)
        if not muts:
            return []
        findings: List[Finding] = []
        for call, held in muts:
            if not held:
                f = src.finding(
                    call, self.name,
                    "replicated mutation logged outside "
                    "'with ..._mut_mu:' — the delta log can record "
                    "ops out of application order")
                if f:
                    findings.append(f)
        for node in discarded:
            f = src.finding(
                node, self.name,
                "replication wait-callable discarded — the handler "
                "can never block on replicate-before-ack")
            if f:
                findings.append(f)
        waited = sorted(ln for name, ln in wait_calls if name in bound)
        first_mut = min(c.lineno for c, _ in muts)
        for reply in replies:
            if reply.lineno <= first_mut:
                continue  # pre-mutation error answers
            if self._is_error_reply(reply):
                continue
            if not any(ln < reply.lineno for ln in waited):
                f = src.finding(
                    reply, self.name,
                    "success reply not dominated by the replication "
                    "wait — a 200 here can ack a write the leader's "
                    "death loses")
                if f:
                    findings.append(f)
        return findings

    @staticmethod
    def _is_mutation_call(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_on_mutation")

    @staticmethod
    def _is_error_reply(call: ast.Call) -> bool:
        if not call.args:
            return False
        a = call.args[0]
        return isinstance(a, ast.Constant) and isinstance(a.value, int) \
            and a.value >= 400


class TermFencePass:
    name = "term-fence"
    doc = ("replica handlers that adopt a peer message's term without "
           "comparing it against their own state first")

    #: consensus state a message handler may only touch behind a fence
    _STATE = {"term", "voted_term", "seq", "seq_term", "role",
              "leader_base"}
    _STATE_CALLS = {"state_restore", "_apply_op"}
    _FENCE_ATTRS = {"term", "voted_term", "seq_term"}

    def run(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_fn(src, node))
        return findings

    def _check_fn(self, src: Source, fn: ast.AST) -> List[Finding]:
        bindings = []
        for n in _own_scope(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and self._reads_msg_term(n.value):
                bindings.append((n.targets[0].id, n.lineno))
        if not bindings:
            return []
        mutations = [n for n in _own_scope(fn) if self._mutates(n)]
        if not mutations:
            return []
        first = min(n.lineno for n in mutations)
        # handler shape only: the message term is read BEFORE state is
        # touched. A sender reading the term out of a peer's 409 body
        # after its own bump (_push_state) is not adopting anything.
        req_names = {name for name, ln in bindings if ln < first}
        if not req_names:
            return []
        for n in _own_scope(fn):
            if isinstance(n, ast.Compare) and n.lineno < first \
                    and self._fences(n, req_names):
                return []
        f = src.finding(
            fn, self.name,
            f"{fn.name} adopts a message term into replica state "
            "without fencing it first (compare against "
            "self.term/voted_term/seq_term before mutating — a stale "
            "leader must get a 409, not a rewrite)")
        return [f] if f else []

    @staticmethod
    def _reads_msg_term(node: ast.AST) -> bool:
        """``...get("term", ...)`` or ``...["term"]`` anywhere under
        ``node``."""
        for n in ast.walk(node):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "get" and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and n.args[0].value == "term":
                return True
            if isinstance(n, ast.Subscript) \
                    and isinstance(n.slice, ast.Constant) \
                    and n.slice.value == "term":
                return True
        return False

    def _mutates(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" \
                        and t.attr in self._STATE:
                    return True
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in self._STATE_CALLS:
            return True
        return False

    def _fences(self, cmp: ast.Compare, req_names: set) -> bool:
        names = {n.id for n in ast.walk(cmp) if isinstance(n, ast.Name)}
        attrs = {n.attr for n in ast.walk(cmp)
                 if isinstance(n, ast.Attribute)
                 and isinstance(n.value, ast.Name)
                 and n.value.id == "self"}
        return bool(names & req_names) and bool(attrs
                                                & self._FENCE_ATTRS)


class HandlerExceptionSafetyPass:
    name = "handler-exception-safety"
    doc = ("keep-alive HTTP handler entries a non-KfError exception "
           "can escape, hanging the pooled client")

    def run(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef) and self._in_scope(node):
                findings.extend(self._check_class(src, node))
        return findings

    @staticmethod
    def _in_scope(cls: ast.ClassDef) -> bool:
        handler_base = any(
            (dotted_name(b) or "").endswith("HTTPRequestHandler")
            for b in cls.bases)
        if not handler_base:
            return False
        # only keep-alive handlers: an HTTP/1.0 handler closes the
        # connection per request, so the client sees EOF, not a hang
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == "protocol_version"
                            for t in stmt.targets) \
                    and isinstance(stmt.value, ast.Constant) \
                    and stmt.value.value == "HTTP/1.1":
                return True
        return False

    def _check_class(self, src: Source,
                     cls: ast.ClassDef) -> List[Finding]:
        methods: Dict[str, ast.AST] = {
            s.name: s for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}
        entries: Dict[str, ast.AST] = {
            name: m for name, m in methods.items()
            if name.startswith("do_")}
        # alias entries: `do_PUT = _do_update` points the verb at a
        # sibling method, which becomes the real entry to check
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Name) \
                    and stmt.value.id in methods:
                for t in stmt.targets:
                    if isinstance(t, ast.Name) \
                            and t.id.startswith("do_"):
                        entries[stmt.value.id] = methods[stmt.value.id]
                        entries.pop(t.id, None)
        findings: List[Finding] = []
        for name, fn in sorted(entries.items()):
            if self._entry_safe(methods, fn):
                continue
            f = src.finding(
                fn, self.name,
                f"{cls.name}.{name}: a non-KfError exception can "
                "escape this keep-alive handler entry without a "
                "reply — the pooled client blocks on the dead read; "
                "firewall the body with a broad except that answers "
                "500 (or drops the connection)")
            if f:
                findings.append(f)
        return findings

    def _entry_safe(self, methods: Dict[str, ast.AST],
                    fn: ast.AST) -> bool:
        if self._is_firewall(methods, fn):
            return True
        body = _strip_doc(fn.body)
        # thin wrapper: a single call into a sibling method that IS
        # the firewall (the `self._crash_guard(self._get)` idiom)
        if len(body) == 1 and isinstance(body[0], ast.Expr) \
                and isinstance(body[0].value, ast.Call):
            callee = body[0].value.func
            if isinstance(callee, ast.Attribute) \
                    and isinstance(callee.value, ast.Name) \
                    and callee.value.id == "self" \
                    and callee.attr in methods:
                return self._is_firewall(methods,
                                         methods[callee.attr])
        return False

    def _is_firewall(self, methods: Dict[str, ast.AST],
                     fn: ast.AST) -> bool:
        body = _strip_doc(fn.body)
        if len(body) != 1 or not isinstance(body[0], ast.Try):
            return False
        return any(self._broad(h) and self._replies(methods, h)
                   for h in body[0].handlers)

    @staticmethod
    def _broad(handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True
        name = dotted_name(handler.type) or ""
        return name.split(".")[-1] in ("Exception", "BaseException")

    @staticmethod
    def _replies(methods: Dict[str, ast.AST],
                 handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(handler):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)):
                continue
            if n.func.attr in _REPLY_CALLS:
                return True
            # one-level resolution through a same-class helper
            if isinstance(n.func.value, ast.Name) \
                    and n.func.value.id == "self" \
                    and n.func.attr in methods:
                helper = methods[n.func.attr]
                if any(isinstance(c, ast.Call)
                       and isinstance(c.func, ast.Attribute)
                       and c.func.attr in _REPLY_CALLS
                       for c in ast.walk(helper)):
                    return True
        return False
