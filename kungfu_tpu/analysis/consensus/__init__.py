"""kfconsensus — static verification of the replicated control plane.

Three pieces, layered on the kfverify ``ProjectIndex``:

- :mod:`.extract` lifts the election/replication state machine out of
  ``elastic/replica.py`` + ``elastic/wal.py`` into a
  :class:`~kungfu_tpu.analysis.consensus.extract.ConsensusSpec`,
  RAISING when the code drifts from the shapes it matches — the model
  is only evidence while it provably mirrors the implementation;
- :mod:`.model` runs that spec over every 2–3-replica interleaving of
  election × group-commit × crash-restart × WAL replay and checks the
  four consensus invariants (at-most-one-leader-per-term,
  no-double-vote-after-restart, every-acked-write-survives-a-crash,
  follower seq-gap-freedom), plus 12 MUST-FIRE ablations replaying
  the PR 16/17/18 incident shapes with one guard removed each;
- :mod:`.passes` contributes three whole-tree lint passes
  (``ack-ordering``, ``term-fence``, ``handler-exception-safety``)
  to the 17-pass registry in :mod:`kungfu_tpu.analysis.core`.

CLI: ``python -m kungfu_tpu.analysis.consensus`` (``--json``,
``--baseline`` ride the same stable-ID machinery as kflint).
"""

from .extract import (ConsensusSpec, consensus_paths, default_spec,
                      extract_consensus_spec)
from .model import (ABLATIONS, SCENARIOS, Violation, World, ablate,
                    explore_consensus)
from .passes import (AckOrderingPass, HandlerExceptionSafetyPass,
                     TermFencePass)

__all__ = [
    "ConsensusSpec", "consensus_paths", "default_spec",
    "extract_consensus_spec",
    "ABLATIONS", "SCENARIOS", "Violation", "World", "ablate",
    "explore_consensus",
    "AckOrderingPass", "TermFencePass", "HandlerExceptionSafetyPass",
]
