"""shard-rules passes: sharding specs must be declarative, total, valid.

The kfspec engine (``parallel/rules.py``) turned PartitionSpecs from
code into data — ordered ``(path regex, spec)`` tables per model
family, registered with the model trees and mesh shapes they serve.
Three passes make that discipline enforceable, extending kflint from
protocol correctness (PR 4/6) to sharding correctness:

- ``shard-rules`` (per-file): literal ``PartitionSpec(...)``
  construction anywhere outside ``parallel/rules.py`` flags. A
  hand-rolled spec is exactly how the ``fused=(n == 1)``
  silent-degradation class regrew per composition: a layout decision
  the static passes cannot see. Suppression requires a written
  reason like every kflint disable.
- ``shard-rule-coverage`` (whole-tree): every leaf path of every
  registered model template must match a rule (tables are total), and
  every rule must win on at least one leaf — a rule that never fires
  is either DEAD (nothing matches its pattern: a path typo, or the
  model renamed a module and the split silently vanished — the
  sharding sibling of the fused-CE fallback) or SHADOWED (an earlier
  rule claims every leaf it would match: ordering bug).
- ``shard-rule-mesh`` (whole-tree): every table instantiates cleanly
  on every mesh shape it declares — axis names exist, sharded dims
  divide. This is the same :func:`~kungfu_tpu.parallel.rules
  .validate_specs` the runtime runs at plan time; running it here
  means a bad (table, mesh) pair fails lint, before any run.

Like ``vmem-budget``, the whole-tree passes import the REAL registry
and evaluate the REAL tables over abstract model templates
(``jax.eval_shape`` — no FLOPs): the single source of truth for the
rules is the engine, so the lint can never disagree with the plan the
runtime derives.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence

from .core import Finding, Source, dotted_name

NAME_SPEC = "shard-rules"
NAME_COVERAGE = "shard-rule-coverage"
NAME_MESH = "shard-rule-mesh"

#: the one module allowed to construct PartitionSpec literals
RULES_MODULE_SUFFIX = os.path.join("parallel", "rules.py")


def _is_rules_module(path: str) -> bool:
    """Exactly `.../parallel/rules.py` — separator-anchored so e.g.
    `dataparallel/rules.py` is NOT exempt."""
    return path == RULES_MODULE_SUFFIX \
        or path.endswith(os.sep + RULES_MODULE_SUFFIX)
#: where the whole-tree passes anchor their findings
RULES_PATH = os.path.join("kungfu_tpu", "parallel", "rules.py")


# -- shard-rules: hand-rolled-spec detection ----------------------------------


def _spec_aliases(tree: ast.AST) -> set:
    """Local names bound to jax.sharding.PartitionSpec by imports."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.endswith("sharding"):
                for a in node.names:
                    if a.name == "PartitionSpec":
                        out.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("jax.sharding", "jax"):
                    # jax.sharding.PartitionSpec / js.PartitionSpec
                    base = a.asname or a.name
                    out.add(f"{base}.PartitionSpec")
                    out.add(f"{base}.sharding.PartitionSpec")
    return out


class HandRolledSpecPass:
    name = NAME_SPEC
    doc = ("literal PartitionSpec(...) construction outside "
           "parallel/rules.py — specs are declarative table data, "
           "not per-module code")

    def run(self, src: Source) -> List[Finding]:
        if _is_rules_module(src.path):
            return []  # the engine is where specs live
        aliases = _spec_aliases(src.tree)
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = dotted_name(node.func)
            if cn is None:
                continue
            if cn in aliases or cn.endswith(".PartitionSpec"):
                f = src.finding(
                    node, NAME_SPEC,
                    f"hand-rolled PartitionSpec ({cn}(...)) outside "
                    "parallel/rules.py — use a rules table or a "
                    "rules.py spec helper (spec/stacked/rows/cols/"
                    "replicated) so the layout is statically "
                    "checkable data; a justified exception needs a "
                    "reasoned suppression")
                if f:
                    findings.append(f)
        return findings


# -- the whole-tree passes: evaluate the real registry ------------------------


def _covers_rules(paths: Sequence[str]) -> bool:
    for p in paths:
        if os.path.isfile(p) and p.endswith("rules.py"):
            return True
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                if root.endswith("parallel") and "rules.py" in files:
                    return True
    return False


def _load_registry():
    from ..parallel import rules

    return rules, rules.REGISTRY


def check_coverage(registry: Optional[Dict] = None) -> List[Finding]:
    """Coverage over the registered templates: unmatched leaves, dead
    rules, shadowed rules. ``registry`` defaults to the live one (the
    fixture tests pass a synthetic registry)."""
    reg = registry
    if reg is None:
        _, reg = _load_registry()
    from ..parallel.rules import _compiled, match_index

    findings: List[Finding] = []
    for name in sorted(reg):
        entry = reg[name]
        table = entry.table
        template = entry.template()
        winners: Dict[int, int] = {}   # rule index -> leaves won
        candidates: Dict[int, int] = {}  # rule index -> leaves matched
        for path, shape in sorted(template.items()):
            nd = len(shape)
            if nd == 0:
                continue  # scalars never consult the table
            for i, (pattern, s) in enumerate(table):
                if _compiled(pattern).fullmatch(path) is None \
                        or len(s) > nd:
                    continue
                candidates[i] = candidates.get(i, 0) + 1
            win = match_index(table, path, nd)
            if win is None:
                findings.append(Finding(
                    RULES_PATH, 1, NAME_COVERAGE,
                    f"table {name!r}: leaf {path!r} matches no rule — "
                    "tables must be total (add a rule or a "
                    "catch-all)"))
            else:
                winners[win] = winners.get(win, 0) + 1
        for i, (pattern, s) in enumerate(table):
            if winners.get(i):
                continue
            if candidates.get(i):
                findings.append(Finding(
                    RULES_PATH, 1, NAME_COVERAGE,
                    f"table {name!r}: rule {i} ({pattern!r}) is "
                    "SHADOWED — every leaf it matches is claimed by "
                    "an earlier rule (ordering bug: first match "
                    "wins)"))
            else:
                findings.append(Finding(
                    RULES_PATH, 1, NAME_COVERAGE,
                    f"table {name!r}: rule {i} ({pattern!r}) is DEAD "
                    "— no registered leaf matches it (path typo, or "
                    "the model renamed the module and this split "
                    "silently vanished)"))
    return findings


def check_mesh(registry: Optional[Dict] = None) -> List[Finding]:
    """Mesh validity: every registered table must instantiate on every
    mesh shape it declares (axis existence + divisibility) — the same
    validate_specs the runtime runs at plan time."""
    reg = registry
    if reg is None:
        _, reg = _load_registry()
    from ..parallel.rules import (PlanError, replicated, spec_for,
                                  validate_specs)

    import numpy as np

    findings: List[Finding] = []
    for name in sorted(reg):
        entry = reg[name]
        table = entry.table
        template = entry.template()
        # rebuild a flat tree of dummy leaves so validate_specs (the
        # runtime validator — a single implementation, not a copy of
        # its math) sees the registered shapes
        tree = {p: np.broadcast_to(np.zeros((), np.uint8), s)
                for p, s in template.items()}
        specs = {p: (spec_for(p, len(s), table) or replicated())
                 for p, s in template.items()}
        for mesh_shape in entry.mesh_shapes:
            declared = set(mesh_shape)
            missing = [ax for ax in table.axes if ax not in declared]
            for ax in missing:
                findings.append(Finding(
                    RULES_PATH, 1, NAME_MESH,
                    f"table {name!r}: names axis {ax!r} absent from "
                    f"declared mesh shape {dict(mesh_shape)} — a plan "
                    "on that mesh raises at runtime"))
            if missing:
                continue
            try:
                validate_specs(specs, tree, mesh_shape,
                               table_name=name)
            except PlanError as e:
                findings.append(Finding(
                    RULES_PATH, 1, NAME_MESH, str(e)))
    return findings


class RuleCoveragePass:
    name = NAME_COVERAGE
    doc = ("every leaf of every registered model tree matches a rule; "
           "dead and shadowed rules flag")

    def run_global(self, paths: Sequence[str]) -> List[Finding]:
        if not _covers_rules(paths):
            return []
        return check_coverage()


class MeshValidityPass:
    name = NAME_MESH
    doc = ("every registered rules table instantiates on every "
           "declared mesh shape (axes exist, dims divide)")

    def run_global(self, paths: Sequence[str]) -> List[Finding]:
        if not _covers_rules(paths):
            return []
        return check_mesh()
