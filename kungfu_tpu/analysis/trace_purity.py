"""trace-purity: no host impurity inside jitted/shard_mapped steps.

Chaos replay (round 7) re-executes a recorded fault schedule against a
deterministic training step: same seeds, same trace, same compiled
program. That determinism dies quietly the day someone traces a wall
clock, host RNG, or host synchronization into a step function — the
program still runs, but the traced value is frozen at compile time (a
``time.time()`` constant baked into the graph) or forces a blocking
device round-trip per step (``.item()``), and replay diverges from the
recording.

The pass finds functions that are jit boundaries — decorated with
``@jax.jit`` / ``@partial(jax.jit, ...)``, wrapped as ``jax.jit(f)``,
or used as a ``shard_map`` body — and flags, anywhere inside:

- wall clocks: ``time.time/perf_counter/monotonic/process_time``
- host RNG: ``np.random.*``, ``random.*`` (use ``jax.random`` with an
  explicit key)
- host sync: ``jax.device_get``, ``.item()``, ``.tolist()``,
  ``.block_until_ready()``
- tracer leaks where derivable: ``float(x)`` / ``int(x)`` / ``bool(x)``
  over a traced parameter, and Python ``if``/``while`` branching on a
  traced parameter (static metadata — ``.ndim`` / ``.shape`` /
  ``.dtype`` / ``len()`` — and ``is None`` checks are exempt; params
  named by ``static_argnames``/``static_argnums`` literals are not
  tracers and are exempt too)
- kftrace recorder calls: ``trace.span`` / ``trace.event`` /
  ``trace.counter`` / ``trace.complete`` / ``trace.flight_dump`` /
  ``trace.set_context`` (any ``trace``/``kftrace`` module prefix).
  A recorder call inside a jitted body runs at TRACE time — it
  records one event at compile, then never again — and the wall
  clocks inside `span` would be frozen constants. Instrumentation
  wraps the CALL SITE of a compiled step, never its body
  (docs/observability.md).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set

from .core import Finding, Source, call_name, scoped_calls

NAME = "trace-purity"

_CLOCK_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.time_ns", "time.perf_counter_ns",
}
_HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
_JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.pjit"}
_SHARD_MAP_NAMES = {"shard_map", "jax.shard_map"}
_STATIC_ATTRS = {"ndim", "shape", "dtype", "size", "sharding"}
_CASTS = {"float", "int", "bool"}
#: kftrace recorder entry points (kungfu_tpu/trace/__init__.py) — any
#: dotted call whose module segment is trace/kftrace and whose final
#: segment is one of these fires inside a jit/shard_map body
_RECORDER_FUNCS = {"span", "event", "counter", "complete",
                   "flight_dump", "set_context"}
_RECORDER_MODULES = {"trace", "kftrace"}


def _is_recorder_call(cn: Optional[str]) -> bool:
    if not cn or "." not in cn:
        return False
    parts = cn.split(".")
    return (parts[-1] in _RECORDER_FUNCS
            and parts[-2] in _RECORDER_MODULES)


def _is_jit_expr(node: ast.AST) -> bool:
    """True for `jax.jit`, `jit`, or `partial(jax.jit, ...)`."""
    name = call_name(node) if isinstance(node, ast.Call) else None
    if isinstance(node, (ast.Name, ast.Attribute)):
        from .core import dotted_name

        return dotted_name(node) in _JIT_NAMES
    if isinstance(node, ast.Call):
        if name in ("functools.partial", "partial") and node.args:
            return _is_jit_expr(node.args[0])
        return name in _JIT_NAMES
    return False


def _static_params(call: Optional[ast.Call]) -> Set[str]:
    """Literal static_argnames from a jit call expression (argnums are
    resolved by position later)."""
    names: Set[str] = set()
    if call is None:
        return names
    for k in call.keywords:
        if k.arg == "static_argnames":
            for n in ast.walk(k.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
    return names


def _static_argnums(call: Optional[ast.Call]) -> Set[int]:
    nums: Set[int] = set()
    if call is None:
        return nums
    for k in call.keywords:
        if k.arg == "static_argnums":
            for n in ast.walk(k.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
    return nums


def _tracer_params(fn: ast.AST, jit_call: Optional[ast.Call]) -> Set[str]:
    a = fn.args
    params = [p.arg for p in a.posonlyargs + a.args]
    static_names = _static_params(jit_call)
    for i in _static_argnums(jit_call):
        if 0 <= i < len(params):
            static_names.add(params[i])
    return {p for p in params if p not in static_names}


def _collect_jit_bodies(tree: ast.AST):
    """(function node, jit-call-or-None) for every jit boundary in the
    module: decorated defs, `jax.jit(f)` / `shard_map(f, ...)` over a
    local def, and jitted/shard_mapped lambdas. Call-form body names
    resolve scope-aware (core.scoped_calls) — several builders in one
    module each define a local `device_step`, and a module-wide
    last-wins map would silently skip all but one of them."""
    out = []
    seen = set()

    def add(fn, jit_call):
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append((fn, jit_call))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    add(node, dec if isinstance(dec, ast.Call) else None)

    def wraps_body(call: ast.Call) -> bool:
        cn = call_name(call)
        return bool(call.args) and (
            _is_jit_expr(call.func) or cn in _JIT_NAMES
            or cn in _SHARD_MAP_NAMES)

    for call, visible in scoped_calls(tree, wraps_body):
        target = call.args[0]
        cn = call_name(call)
        is_jit = _is_jit_expr(call.func) or cn in _JIT_NAMES
        jc = None
        if is_jit:
            # partial(jax.jit, static_argnames=...)(fn): the static
            # markers live on the INNER partial call, not the outer
            # application whose keywords are empty
            jc = (call.func if isinstance(call.func, ast.Call)
                  else call)
        if isinstance(target, ast.Lambda):
            add(target, jc)
        elif isinstance(target, ast.Name) and target.id in visible:
            add(visible[target.id], jc)
    return out


def _references_tracer(node: ast.AST, tracers: Set[str]) -> Optional[str]:
    """The first traced parameter referenced in ``node`` other than
    through static metadata (x.ndim / x.shape / x.dtype / len(x)) or
    an `is None` check; None when the expression is trace-safe."""

    def scan(n: ast.AST, parent: Optional[ast.AST]) -> Optional[str]:
        if isinstance(n, ast.Name) and n.id in tracers:
            if (isinstance(parent, ast.Attribute)
                    and parent.attr in _STATIC_ATTRS):
                return None
            if (isinstance(parent, ast.Call)
                    and isinstance(parent.func, ast.Name)
                    and parent.func.id == "len"):
                return None
            return n.id
        if isinstance(n, ast.Compare):
            ops_none = all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops)
            comparators_none = all(
                isinstance(c, ast.Constant) and c.value is None
                for c in n.comparators)
            if ops_none and comparators_none:
                return None  # `x is None`: x is then NOT a tracer
        for child in ast.iter_child_nodes(n):
            hit = scan(child, n)
            if hit:
                return hit
        return None

    return scan(node, None)


class TracePurityPass:
    name = NAME
    doc = ("wall clocks, host RNG, host sync, and derivable tracer "
           "leaks inside jit/shard_map step functions")

    def run(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        for fn, jit_call in _collect_jit_bodies(src.tree):
            tracers = _tracer_params(fn, jit_call)
            findings.extend(self._check_body(src, fn, tracers))
        return findings

    def _check_body(self, src: Source, fn: ast.AST,
                    tracers: Set[str]) -> List[Finding]:
        findings: List[Finding] = []

        def add(node, msg):
            f = src.finding(node, NAME, msg)
            if f:
                findings.append(f)

        body: Sequence[ast.AST] = (
            [fn.body] if isinstance(fn, ast.Lambda) else fn.body)
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    cn = call_name(node)
                    if _is_recorder_call(cn):
                        add(node, f"kftrace recorder call {cn}() "
                                  "inside a jitted step records at "
                                  "trace time, not per step — wrap "
                                  "the call site of the compiled "
                                  "step instead")
                    elif cn in _CLOCK_CALLS:
                        add(node, f"{cn}() is frozen into the trace at "
                                  "compile time — wall clocks cannot "
                                  "live inside a jitted step")
                    elif cn and (cn.startswith("np.random.")
                                 or cn.startswith("numpy.random.")
                                 or cn.startswith("random.")):
                        add(node, f"host RNG {cn}() inside a jitted step "
                                  "breaks chaos-replay determinism — "
                                  "use jax.random with an explicit key")
                    elif cn in ("jax.device_get", "device_get"):
                        add(node, "jax.device_get inside a jitted step "
                                  "forces a host round-trip per step")
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr in _HOST_SYNC_ATTRS
                          and not node.args):
                        add(node, f".{node.func.attr}() inside a jitted "
                                  "step synchronizes with the host — "
                                  "return the value instead")
                    elif (isinstance(node.func, ast.Name)
                          and node.func.id in _CASTS
                          and len(node.args) == 1):
                        hit = _references_tracer(node.args[0], tracers)
                        if hit:
                            add(node,
                                f"{node.func.id}() over traced value "
                                f"{hit!r} — concretizes a tracer (host "
                                "sync or trace error)")
                elif isinstance(node, (ast.If, ast.While)):
                    hit = _references_tracer(node.test, tracers)
                    if hit:
                        add(node,
                            f"Python branching on traced value {hit!r} "
                            "— use lax.cond/jnp.where, or mark the "
                            "argument static")
        return findings
