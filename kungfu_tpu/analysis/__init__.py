"""kflint — this repo's own static-analysis suite.

Generic linters catch generic bugs; the hazards that actually take an
elastic training run down at 3 a.m. are project-specific: a control-
plane call that regressed to a bare ``except``-and-retry loop, a
``psum`` axis name that drifted from its mesh declaration, a
``time.time()`` smuggled into a jitted step function (breaking the
determinism chaos replay depends on), a Pallas block plan that only
Mosaic-OOMs at a shape nobody benchmarked, or a write to threaded
shared state that forgot its lock. Each pass here encodes one of those
accumulated failure classes so it is caught at lint time, before the
recovery event.

Run the suite::

    python -m kungfu_tpu.analysis kungfu_tpu/

Passes (see ``docs/static_analysis.md`` for the incident rationale):

- ``retry-discipline``   control-plane calls must ride ``retrying.py``;
                         bare/over-broad ``except`` is flagged
- ``axis-consistency``   collective axis names inside ``shard_map``
                         bodies must match the declared mesh/spec axes;
                         spec arity must match the body where derivable
- ``trace-purity``       no wall clocks, host RNG, or host sync inside
                         jitted/shard_mapped step functions
- ``vmem-budget``        flash/fused_ce block plans must fit the VMEM
                         budget over the benchmark shape grid
- ``lock-discipline``    writes to ``# kf: guarded_by(lock)`` state must
                         hold the lock (instance attrs, module globals,
                         and closure-shared locals)
- ``unused-imports``     pyflakes-subset import hygiene (the container
                         ships no ruff; this keeps the F401 floor)

**kfverify** (``analysis/protocol/``) adds the interprocedural SPMD
protocol layer — the PR 5 joiner wire-name deadlock class that no
per-file pass can see:

- ``wire-name-determinism``  wire names must derive only from
                             cluster-agreed sources (epoch, agreed
                             step, schedule index); rank/clock/env/
                             undeclared-counter dataflow is flagged
                             through assignments, closures and call
                             sites
- ``collective-order``       per-entry-point collective sequences,
                             extracted across function boundaries;
                             collectives under rank-divergent branches
                             or value-dependent loops are flagged
- ``schedule-purity``        chunk_schedule/bucket_schedule inputs
                             must be shape-only (no tensor values, no
                             env reads after init)
- ``lock-order``             the whole-program lock acquisition graph
                             must be acyclic

``analysis/protocol/explore.py`` model-checks the EXTRACTED protocol
over small rank/interleaving scopes and prints divergence traces.

Suppression: ``# kflint: disable=<pass>[,<pass>]`` on the offending
line (or the line above); ``# kflint: skip-file`` near the top of a
file skips it entirely. ``unused-imports`` additionally honors
``# noqa`` so existing re-export markers keep working. Full runs audit
the suppressions themselves: a disable that no longer suppresses a
live finding is a ``stale-suppression`` finding (rot in the
written-reason policy), and ``--json``/``--baseline`` give CI stable
finding IDs to diff against.
"""

from .core import (Finding, Source, all_passes, run_paths,
                   run_project_texts, run_source)

__all__ = ["Finding", "Source", "all_passes", "run_paths",
           "run_project_texts", "run_source"]
