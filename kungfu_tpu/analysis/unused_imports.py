"""unused-imports: the pyflakes-F401 floor, self-hosted.

The container ships no ruff/pyflakes; ``pyproject.toml`` configures
them for machines that have them, and this pass keeps the one check
that most often hides real bugs (a refactor that stopped using a
module but kept importing it, masking a missing dependency edge)
enforceable everywhere the test suite runs.

Rules: a name bound by ``import`` / ``from .. import`` must be
referenced somewhere in the module, exported via ``__all__``, or
marked (``# noqa`` — the repo's existing re-export convention — or a
kflint disable). ``from __future__`` imports and ``__init__.py``
files (whose imports ARE the public API) are exempt.
"""

from __future__ import annotations

import ast
import os
from typing import List

from .core import Finding, Source

NAME = "unused-imports"


class UnusedImportsPass:
    name = NAME
    doc = "imports never referenced in their module (pyflakes F401)"

    def run(self, src: Source) -> List[Finding]:
        if os.path.basename(src.path) == "__init__.py":
            return []  # imports are the re-export surface there

        bound = []  # (local name, display name, node)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    bound.append((local, alias.name, node))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    bound.append((local, alias.name, node))

        used = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                pass  # the chain's root Name is walked separately
        # __all__ re-exports count as uses
        for node in src.tree.body:
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "__all__"
                            for t in node.targets)):
                for n in ast.walk(node.value):
                    if isinstance(n, ast.Constant) and isinstance(
                            n.value, str):
                        used.add(n.value)

        findings: List[Finding] = []
        for local, display, node in bound:
            if local in used or src.noqa(node.lineno):
                continue
            f = src.finding(
                node, NAME,
                f"'{display}' imported but unused (re-export? mark it "
                "# noqa)")
            if f:
                findings.append(f)
        return findings
