"""axis-consistency: collective axis names must match declared axes.

The hand-authored ``shard_map`` programs in ``parallel/`` (tensor,
expert, sequence, vocab_ce, zero, pipeline) call ``psum`` /
``all_gather`` / ``ppermute`` / ``all_to_all`` with axis names that
must agree with the enclosing mesh/PartitionSpec declarations. A typo
("modle" for "model") surfaces as an unbound-axis trace error at best
— and at worst as a silently *different* reduction when the wrong but
existing axis is named. XLA cannot catch the second case; only a
checker that knows which axes the call site declared can.

Statically derivable subset (conservative — dynamic axis names, the
common ``axis_name`` parameter idiom, are skipped, so the pass never
guesses):

- every **string-literal** axis name passed to a collective inside a
  ``shard_map``/``pjit`` body must appear among the axis names
  declared by that call's ``in_specs``/``out_specs`` literals, any
  ``Mesh(..., ("a", "b"))`` / ``axis_names=(...)`` literal in the same
  module, or the body's own spec literals. Locally-assigned string
  constants (``axis = "data"``) are propagated.
- **arity**: when ``in_specs`` is a tuple literal and the body is a
  def/lambda in the same module, the spec count must match the body's
  positional parameter count; when ``out_specs`` is a tuple literal,
  every ``return`` of a tuple literal must match its length. (This is
  the derivable slice of "PartitionSpec rank matches array rank": the
  rank mismatch Mosaic reports at trace time, the arity mismatch it
  reports as a shape error three layers deep.)
- **partial-wrapped bodies**: ``shard_map(partial(body, ...), ...)``
  resolves through the ``functools.partial`` to the wrapped def/lambda
  (previously these bodies were silently skipped); bound positional/
  keyword arguments reduce the body's effective arity for the
  ``in_specs`` check, and a **string literal** bound to the
  conventional ``axis_name=`` keyword is checked against the declared
  axes exactly like a literal inside the body.
- **rules-backed declared axes (specs-as-data)**: since kfspec, most
  modules build their specs from ``parallel/rules.py`` helpers
  (``stacked("data")`` — the literal argument declares the axis, the
  generic literal walk already sees it) or from a rules TABLE
  (``gpt_tp_rules()``), whose axis universe the pass resolves from
  the live engine registry (``rules.TABLE_AXES``) instead of
  re-deriving it from shard_map literals. The literal path stays as
  fallback: a table call with explicit axis arguments contributes
  those even when the engine is not importable.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import (Finding, Source, call_name, literal_strings,
                   scoped_calls)

NAME = "axis-consistency"

_COLLECTIVES = {
    "psum", "pmax", "pmin", "pmean", "all_gather", "ppermute",
    "all_to_all", "axis_index", "axis_size", "pbroadcast", "pswapaxes",
}

_SHARD_MAP_CALLS = {"shard_map", "jax.shard_map", "pjit", "jax.pjit"}


def _tail(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


class _ConstStrings(ast.NodeVisitor):
    """name -> string value for straight-line single-assignment local
    constants; reassigned or non-literal names resolve to nothing."""

    def __init__(self):
        self.values: Dict[str, Optional[str]] = {}

    def visit_Assign(self, node: ast.Assign):
        targets = []
        for t in node.targets:
            if isinstance(t, ast.Tuple):
                targets.extend(t.elts)
            else:
                targets.append(t)
        if (isinstance(node.value, ast.Tuple)
                and len(targets) == len(node.value.elts)):
            pairs = zip(targets, node.value.elts)
        else:
            pairs = [(t, node.value) for t in targets]
        for t, v in pairs:
            if isinstance(t, ast.Name):
                if (isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                        and t.id not in self.values):
                    self.values[t.id] = v.value
                else:
                    self.values[t.id] = None  # dynamic or reassigned
        self.generic_visit(node)


def _mesh_axis_literals(tree: ast.AST) -> Set[str]:
    """Axis names declared by Mesh(..., ("a", "b")) constructions or
    axis_names=(...) keywords anywhere in the module."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        tail = _tail(call_name(node))
        kw = _kw(node, "axis_names")
        if kw is not None:
            out.update(literal_strings(kw))
        if tail in ("Mesh", "make_mesh") and len(node.args) >= 2:
            out.update(literal_strings(node.args[1]))
    return out


def _rules_table_axes(tree: ast.AST) -> Set[str]:
    """Axes declared by kfspec rules-table constructor calls
    (specs-as-data): a module deriving its layout from
    ``gpt_tp_rules()`` declares that table's axis universe without
    re-stating it as string literals. Default axes resolve from the
    LIVE engine registry (``parallel.rules.TABLE_AXES`` — the tables
    are data, so the pass reads the data); literal axis arguments
    contribute regardless, which keeps the literal path as fallback
    when the engine is not importable (fixture runs outside the
    repo)."""
    calls = [n for n in ast.walk(tree)
             if isinstance(n, ast.Call)
             and (_tail(call_name(n)) or "").endswith("_rules")]
    if not calls:
        return set()
    try:
        from ..parallel.rules import TABLE_AXES
    except ImportError:
        TABLE_AXES = {}
    out: Set[str] = set()
    for node in calls:
        out.update(literal_strings(node))
        out.update(TABLE_AXES.get(_tail(call_name(node)), ()))
    return out


def _resolve_axis(node: ast.AST, consts: Dict[str, Optional[str]],
                  ) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _spec_axis_names(spec_node: ast.AST,
                     consts: Dict[str, Optional[str]]) -> Set[str]:
    """String axis names in a P(...)/PartitionSpec(...) expression tree
    (literals plus propagated local string constants)."""
    out: Set[str] = set()
    for n in ast.walk(spec_node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.add(n.value)
        elif isinstance(n, ast.Name) and consts.get(n.id):
            out.add(consts[n.id])
    return out


def _positional_arity(fn: ast.AST) -> Optional[int]:
    """Positional parameter count of a def/lambda, or None when *args
    (or a non-function) makes the count open-ended."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
        return None
    a = fn.args
    if a.vararg is not None:
        return None
    return len(a.posonlyargs) + len(a.args)


def _own_returns(fn: ast.AST) -> List[ast.Return]:
    """Return statements belonging to ``fn`` itself — nested defs have
    their own contract and are not descended into."""
    out: List[ast.Return] = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, ast.Return):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _collect_sites(tree: ast.AST):
    """shard_map call sites with scope-aware body resolution (see
    core.scoped_calls)."""
    return scoped_calls(
        tree, lambda c: call_name(c) in _SHARD_MAP_CALLS)


class AxisConsistencyPass:
    name = NAME
    doc = ("literal collective axis names inside shard_map bodies must "
           "match declared mesh/spec axes; spec arity must match the "
           "body where derivable")

    def run(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        mesh_axes = (_mesh_axis_literals(src.tree)
                     | _rules_table_axes(src.tree))
        consts_v = _ConstStrings()
        consts_v.visit(src.tree)
        consts = consts_v.values

        for call, defs in _collect_sites(src.tree):
            findings.extend(
                self._check_site(src, call, mesh_axes, defs, consts))
        return findings

    def _check_site(self, src: Source, call: ast.Call,
                    mesh_axes: Set[str], defs: Dict[str, ast.AST],
                    consts: Dict[str, Optional[str]]) -> List[Finding]:
        findings: List[Finding] = []
        in_specs = _kw(call, "in_specs")
        out_specs = _kw(call, "out_specs")

        declared = set(mesh_axes)
        for spec in (in_specs, out_specs):
            if spec is not None:
                declared |= _spec_axis_names(spec, consts)

        body: Optional[ast.AST] = None
        bound_args = 0       # positional/keyword params partial binds
        partial_kws: List[ast.keyword] = []
        if call.args:
            first = call.args[0]
            if isinstance(first, ast.Lambda):
                body = first
            elif isinstance(first, ast.Name):
                body = defs.get(first.id)
            elif (isinstance(first, ast.Call)
                  and _tail(call_name(first)) == "partial"
                  and first.args):
                inner = first.args[0]
                if isinstance(inner, ast.Lambda):
                    body = inner
                elif isinstance(inner, ast.Name):
                    body = defs.get(inner.id)
                partial_kws = first.keywords
                if any(k.arg is None for k in partial_kws):
                    body = None  # **kwargs splat: arity underivable
                elif body is not None:
                    # keyword binds consume a POSITIONAL slot only when
                    # they name a positional param (binding a
                    # keyword-only param must not shrink the arity)
                    positional = set()
                    if isinstance(body, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.Lambda)):
                        positional = {a.arg for a in
                                      (body.args.posonlyargs
                                       + body.args.args)}
                    bound_args = (len(first.args) - 1
                                  + sum(1 for k in partial_kws
                                        if k.arg in positional))

        # 1) literal axis names used by collectives in the body
        if body is not None and declared:
            for sub in ast.walk(body):
                if not isinstance(sub, ast.Call):
                    continue
                tail = _tail(call_name(sub))
                if tail not in _COLLECTIVES:
                    continue
                axis_node = _kw(sub, "axis_name")
                if axis_node is None and len(sub.args) >= 2:
                    axis_node = sub.args[1]
                if axis_node is None and tail in ("axis_index",
                                                  "axis_size"):
                    axis_node = sub.args[0] if sub.args else None
                if axis_node is None:
                    continue
                axis = _resolve_axis(axis_node, consts)
                if axis is not None and axis not in declared:
                    f = src.finding(
                        sub, NAME,
                        f"{tail}(..., {axis!r}) inside a shard_map body "
                        f"names an axis not declared by the call site "
                        f"(declared: {sorted(declared)})")
                    if f:
                        findings.append(f)

        # 1b) a string literal bound to the conventional axis_name=
        # keyword of a partial-wrapped body is an axis name too
        if declared:
            for kw in partial_kws:
                if kw.arg != "axis_name":
                    continue
                axis = _resolve_axis(kw.value, consts)
                if axis is not None and axis not in declared:
                    f = src.finding(
                        kw.value, NAME,
                        f"partial(..., axis_name={axis!r}) wrapping a "
                        f"shard_map body names an axis not declared by "
                        f"the call site (declared: {sorted(declared)})")
                    if f:
                        findings.append(f)

        # 2) arity: in_specs tuple vs body positional params (minus
        # whatever a wrapping partial already bound)
        if isinstance(in_specs, ast.Tuple) and body is not None:
            arity = _positional_arity(body)
            if arity is not None:
                arity -= bound_args
            if (arity is not None and arity >= 0
                    and arity != len(in_specs.elts)):
                f = src.finding(
                    call, NAME,
                    f"in_specs declares {len(in_specs.elts)} spec(s) but "
                    f"the shard_map body takes {arity} positional "
                    "argument(s)"
                    + (" after partial binding" if bound_args else ""))
                if f:
                    findings.append(f)

        # 3) arity: out_specs tuple vs tuple-literal returns
        if isinstance(out_specs, ast.Tuple) and isinstance(
                body, (ast.FunctionDef, ast.AsyncFunctionDef)):
            want = len(out_specs.elts)
            for sub in _own_returns(body):
                if (isinstance(sub.value, ast.Tuple)
                        and len(sub.value.elts) != want):
                    f = src.finding(
                        sub, NAME,
                        f"body returns a {len(sub.value.elts)}-tuple but "
                        f"out_specs declares {want} spec(s)")
                    if f:
                        findings.append(f)
        return findings
