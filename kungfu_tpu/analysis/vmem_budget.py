"""vmem-budget: block plans must fit VMEM over the benchmark grid.

Mosaic's scoped-vmem limit is a compile-time cliff: a block plan that
estimates past it OOMs with a compiler error at a shape nobody tried
until a user did (the round-6 calibration found h=1024 at 1024/1024
fused-CE blocks compiling 18.9 MB real against a 14.7 MB estimate).
``ops/flash.py`` and ``ops/fused_ce.py`` defend with budget-driven
auto-shrink (``flash_plan`` / ``_pick_blocks``); this pass evaluates
those exact plan functions over the declared benchmark shape grid and
fails the lint when any chosen plan's own VMEM estimate exceeds the
budget — so a drift between the block defaults, the estimate models
and the budget becomes a lint failure instead of a 3 a.m. Mosaic
crash at a new shape.

Unlike the AST passes this one imports the real modules (the plan
functions are pure host-side Python over ints): the single source of
truth for the estimate IS the implementation, so the lint can never
disagree with what the kernels will actually request.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from .core import Finding

NAME = "vmem-budget"

#: The shape grid the flash benchmarks sweep (benchmarks/flash_eff.py
#: defaults + the published BASELINE long-context points), extended to
#: the head dims that historically broke estimates (d=256 at long T).
FLASH_GRID = [
    # (t, d, dtype_name, causal, window)
    (1024, 64, "float32", False, None),
    (1024, 64, "bfloat16", True, None),
    (2048, 128, "bfloat16", True, None),
    (4096, 64, "bfloat16", True, None),
    (4096, 256, "float32", True, None),
    (8192, 128, "bfloat16", True, None),
    (8192, 256, "bfloat16", False, None),
    (16384, 64, "bfloat16", True, None),
    (16384, 64, "bfloat16", True, 512),
    (16384, 128, "bfloat16", True, 512),
    (16384, 256, "float32", True, None),
]

#: Fused-CE grid: GPT-2-small benchmark shapes (lm.py defaults), the
#: h=1024 OOM calibration point, the n=16384 full-model-graph shrink
#: point, and a non-divisible vocab.
FUSED_CE_GRID = [
    # (n, h, v)
    (1024, 256, 32000),
    (8184, 768, 50257),
    (8192, 1024, 50257),
    (16384, 768, 50257),
    (16384, 1024, 50304),
    (32768, 4096, 128256),
]


#: Paged-decode grid: the serving shapes `ops/paged_attn.paged_plan`
#: must cover — (max_len, block_tokens, num_heads, head_dim, dtype).
#: GPT-2 small/medium serving tiers at growing context plus the
#: big-pool long-context point that pushes the resident scheme past
#: any plausible budget (the plan must DEGRADE there, not OOM).
PAGED_GRID = [
    (1024, 16, 12, 64, "bfloat16"),
    (2048, 16, 16, 64, "bfloat16"),
    (4096, 32, 16, 64, "bfloat16"),
    (4096, 16, 32, 128, "bfloat16"),
    (8192, 32, 16, 64, "bfloat16"),
    (2048, 16, 12, 64, "float32"),
]


def check_paged(grid: Sequence = PAGED_GRID,
                budget: Optional[int] = None) -> List[Finding]:
    import jax.numpy as jnp

    from ..ops import paged_attn

    budget = paged_attn._VMEM_BUDGET if budget is None else budget
    findings = []
    for max_len, bt, heads, d, dtype_name in grid:
        dtype = jnp.dtype(dtype_name)
        max_blocks = -(-max_len // bt)
        plan = paged_attn.paged_plan(max_blocks, bt, heads, d,
                                     dtype=dtype)
        if plan["scheme"] == "functional":
            continue  # stock-JAX fallback: nothing to compile
        est = plan["vmem_bytes"]
        if est > budget:
            findings.append(Finding(
                "kungfu_tpu/ops/paged_attn.py", 1, NAME,
                f"paged decode plan at max_len={max_len} "
                f"block_tokens={bt} heads={heads} d={d} "
                f"dtype={dtype_name} picks scheme={plan['scheme']} "
                f"with VMEM estimate {est / 2**20:.1f} MB > budget "
                f"{budget / 2**20:.1f} MB — Mosaic would OOM at "
                "compile time"))
    return findings


def check_flash(grid: Sequence = FLASH_GRID,
                budget: Optional[int] = None) -> List[Finding]:
    import jax.numpy as jnp

    from ..ops import flash

    budget = flash._VMEM_BUDGET if budget is None else budget
    stream = {"fwd": flash._fwd_stream_vmem, "dq": flash._dq_stream_vmem,
              "dkv": flash._dkv_stream_vmem}
    findings = []
    for t, d, dtype_name, causal, window in grid:
        dtype = jnp.dtype(dtype_name)
        plan = flash.flash_plan(t, d, dtype=dtype, causal=causal,
                                window=window)
        if plan.get("scheme") == "plain":
            continue  # fallback path: nothing to compile, nothing to OOM
        bq, bk = plan["block_q"], plan["block_k"]
        isz = dtype.itemsize
        for which in ("fwd", "dq", "dkv"):
            scheme = plan[which]["scheme"]
            if scheme == "resident":
                est = flash._RES_VMEM[which](bq, bk, d, isz, t)
            elif which == "dkv":
                est = stream[which](bq, bk, d, isz, t)
            else:
                est = stream[which](bq, bk, d, isz)
            if est > budget:
                findings.append(Finding(
                    "kungfu_tpu/ops/flash.py", 1, NAME,
                    f"flash {which} plan at t={t} d={d} "
                    f"dtype={dtype_name} causal={causal} "
                    f"window={window} picks blocks ({bq}, {bk}) "
                    f"scheme={scheme} with VMEM estimate "
                    f"{est / 2**20:.1f} MB > budget "
                    f"{budget / 2**20:.1f} MB — Mosaic would OOM at "
                    "compile time"))
    return findings


def check_fused_ce(grid: Sequence = FUSED_CE_GRID,
                   budget: Optional[int] = None) -> List[Finding]:
    from ..ops import fused_ce

    budget = fused_ce._VMEM_BUDGET if budget is None else budget
    findings = []
    models = {"fwd": fused_ce._fwd_vmem_bytes,
              "recompute": fused_ce._recompute_vmem_bytes}
    for n, h, v in grid:
        for label, model in models.items():
            blocks = fused_ce._pick_blocks(n, h, v, vmem_bytes=model)
            if blocks is None:
                continue  # callers take the reference path: safe
            bn, bv = blocks
            est = model(bn, h, bv)
            if est > budget:
                findings.append(Finding(
                    "kungfu_tpu/ops/fused_ce.py", 1, NAME,
                    f"fused_ce {label} plan at n={n} h={h} v={v} picks "
                    f"blocks ({bn}, {bv}) with VMEM estimate "
                    f"{est / 2**20:.1f} MB > budget "
                    f"{budget / 2**20:.1f} MB — Mosaic would OOM at "
                    "compile time"))
    return findings


class VmemBudgetPass:
    name = NAME
    doc = ("flash/fused_ce/paged-decode block plans evaluated over "
           "the benchmark shape grid must fit the VMEM budget")

    def run_global(self, paths: Sequence[str]) -> List[Finding]:
        # only meaningful when the analyzed tree contains the kernels
        import os

        covers = any(
            os.path.isdir(p) and any(
                os.path.exists(os.path.join(root, "flash.py"))
                for root, _, _ in os.walk(p))
            or os.path.basename(p) in ("flash.py", "fused_ce.py",
                                       "paged_attn.py")
            for p in paths)
        if not covers:
            return []
        return check_flash() + check_fused_ce() + check_paged()
