"""kflint CLI: ``python -m kungfu_tpu.analysis [paths...]``.

Exit status 0 when the tree is clean, 1 when any pass fired, 2 on
usage errors — so `scripts/run-all.sh` (and CI) can gate on it like
any other linter.

Machine-readable mode: ``--json`` emits findings with STABLE IDs
(``pass:file:line:hash``, hash over pass+file+message so an unrelated
edit on the same line keeps the ID), and ``--baseline FILE`` diffs the
run against a committed baseline — CI then fails only on NEW findings
and reports fixed ones, instead of a bare pass/fail that blocks
landing a checker stricter than today's tree. The committed baseline
lives at scripts/kflint_baseline.json (empty: the tree is clean).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys

from .core import Finding, all_passes, run_paths


def finding_id(f: Finding) -> str:
    """pass:file:line:hash — the hash covers pass+file+message only,
    so the line-independent prefix+hash key survives line churn."""
    h = hashlib.sha1(
        f"{f.pass_name}|{f.path}|{f.message}".encode()).hexdigest()[:8]
    return f"{f.pass_name}:{f.path}:{f.line}:{h}"


def _line_free(fid: str) -> str:
    """The ID minus its line component: an edit that merely shifts a
    baselined finding down a few lines must not turn committed debt
    into a NEW gate failure (the hash already pins pass+file+message)."""
    head, _, tail = fid.rpartition(":")
    head, _, _line = head.rpartition(":")
    return f"{head}:{tail}"


def diff_baseline(ids, baseline):
    """(new, fixed) finding-ID sets, reconciled on the line-free key
    with multiplicity: a pure line shift cancels out; a second
    instance of an identical hazard still reports as new."""
    from collections import Counter

    cur = Counter(_line_free(i) for i in ids)
    base = Counter(_line_free(i) for i in baseline)
    new, fixed = set(), set()
    spare = cur - base
    for i in sorted(ids):
        k = _line_free(i)
        if spare.get(k, 0) > 0:
            spare[k] -= 1
            new.add(i)
    spare = base - cur
    for i in sorted(baseline):
        k = _line_free(i)
        if spare.get(k, 0) > 0:
            spare[k] -= 1
            fixed.add(i)
    return new, fixed


def to_json(findings, passes, new=None, fixed=None) -> str:
    doc = {
        "version": 1,
        "passes": sorted(p.name for p in passes),
        "count": len(findings),
        "findings": [
            {"id": finding_id(f), "pass": f.pass_name, "path": f.path,
             "line": f.line, "message": f.message}
            for f in findings
        ],
    }
    if new is not None:
        doc["new"] = sorted(new)
    if fixed is not None:
        doc["fixed"] = sorted(fixed)
    return json.dumps(doc, indent=2)


def load_baseline(path: str) -> set:
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        return set(doc)
    if not isinstance(doc, dict):
        # a truncated/corrupted write (e.g. `null`) must hit the
        # exit-2 diagnostic, not an uncaught traceback
        raise ValueError(f"baseline must be a JSON object or list, "
                         f"got {type(doc).__name__}")
    ids = doc.get("ids")
    if ids is None:
        ids = [f["id"] for f in doc.get("findings", [])]
    return set(ids)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kungfu_tpu.analysis",
        description="kflint+kfverify: this repo's project-specific "
                    "static-analysis suite (see docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*", default=["kungfu_tpu"],
                    help="files or directories to analyze "
                         "(default: kungfu_tpu)")
    ap.add_argument("--select", metavar="PASS[,PASS...]",
                    help="run only these passes (also skips the "
                         "stale-suppression audit)")
    ap.add_argument("--list", action="store_true", dest="list_passes",
                    help="list available passes and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings with stable IDs "
                         "(pass:file:line:hash) on stdout")
    ap.add_argument("--baseline", metavar="FILE",
                    help="diff findings against a committed baseline: "
                         "exit 1 only on NEW finding IDs, report fixed "
                         "ones")
    args = ap.parse_args(argv)

    if args.list_passes:
        for p in all_passes():
            print(f"{p.name:22s} {p.doc}")
        return 0

    select = args.select.split(",") if args.select else None
    if select and args.baseline:
        # the baseline is generated from FULL runs; diffing a subset
        # against it would report every other pass's baseline IDs as
        # "fixed" and invite a baseline regeneration that turns
        # pre-existing findings into NEW failures on the next full run
        print("kflint: --select and --baseline are mutually exclusive "
              "(the baseline is a full-run artifact)", file=sys.stderr)
        return 2
    try:
        findings = run_paths(args.paths or ["kungfu_tpu"], select=select)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2  # a typo'd path must not green the gate
    passes = [p for p in all_passes()
              if select is None or p.name in select]
    n_passes = len(passes)

    new = fixed = None
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"kflint: cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2  # an unreadable baseline must not green the gate
        new, fixed = diff_baseline({finding_id(f) for f in findings},
                                   baseline)

    if args.as_json:
        print(to_json(findings, passes, new, fixed))
    else:
        for f in findings:
            marker = ""
            if new is not None:
                marker = ("" if finding_id(f) in new
                          else " [baseline]")
            print(f"{f}{marker}")

    if args.baseline:
        if fixed:
            print(f"kflint: {len(fixed)} baseline finding(s) fixed — "
                  "regenerate the baseline to ratchet", file=sys.stderr)
        if new:
            print(f"kflint: {len(new)} NEW finding(s) vs baseline "
                  f"({len(findings)} total, {n_passes} passes)",
                  file=sys.stderr)
            return 1
        print(f"kflint: no new findings vs baseline ({n_passes} "
              "passes)", file=sys.stderr)
        return 0

    if findings:
        print(f"kflint: {len(findings)} finding(s) across {n_passes} "
              "pass(es)", file=sys.stderr)
        return 1
    print(f"kflint: clean ({n_passes} passes)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
