"""kflint CLI: ``python -m kungfu_tpu.analysis [paths...]``.

Exit status 0 when the tree is clean, 1 when any pass fired, 2 on
usage errors — so `scripts/run-all.sh` (and CI) can gate on it like
any other linter.
"""

from __future__ import annotations

import argparse
import sys

from .core import all_passes, run_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kungfu_tpu.analysis",
        description="kflint: this repo's project-specific static-"
                    "analysis suite (see docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*", default=["kungfu_tpu"],
                    help="files or directories to analyze "
                         "(default: kungfu_tpu)")
    ap.add_argument("--select", metavar="PASS[,PASS...]",
                    help="run only these passes")
    ap.add_argument("--list", action="store_true", dest="list_passes",
                    help="list available passes and exit")
    args = ap.parse_args(argv)

    if args.list_passes:
        for p in all_passes():
            print(f"{p.name:18s} {p.doc}")
        return 0

    select = args.select.split(",") if args.select else None
    try:
        findings = run_paths(args.paths or ["kungfu_tpu"], select=select)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2  # a typo'd path must not green the gate
    for f in findings:
        print(f)
    n_passes = len(select) if select else len(all_passes())
    if findings:
        print(f"kflint: {len(findings)} finding(s) across {n_passes} "
              "pass(es)", file=sys.stderr)
        return 1
    print(f"kflint: clean ({n_passes} passes)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
