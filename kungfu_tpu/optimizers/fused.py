"""Flat-buffer optimizer wrappers: fused updates for many-leaf trees.

`flatten_optimizer` wraps ANY elementwise optax transformation to run
on a single concatenated vector per param dtype, so the whole update
is a handful of big streaming kernels instead of one fusion per leaf.
Conceptually the TPU analogue of the reference's fused gradient path
(reference: srcs/python/kungfu/tensorflow/optimizers/sync_sgd.py
`nccl_fusion`/fuse): fuse many small per-tensor ops into few big ones.

**Whole-tree flattening measured NEGATIVE on v5e** (docs/benchmarks.md
round-5 attribution): the per-leaf adamw fusions were only 16.1 ms of
the 104.6 ms GPT-2 b=12 step, and the flat variant REGRESSED the step
to 131.1 ms — XLA lowers the 100-leaf concatenate to a serial
dynamic-update-slice loop and relayouts every 2-D tiled leaf to the
1-D linear layout and back. The wrapper is kept because it is correct
(bitwise-parity tested), cheap to maintain, and the trade can flip on
backends/shapes where concatenation is free.

`group_small_leaves` is the middle point that negative result actually
motivates (VERDICT r5: the adamw update runs ~3.7x above its HBM floor
because of the LONG TAIL OF SMALL LEAVES, each tiny fusion paying
launch + sub-line HBM overheads): only leaves below a size threshold —
the layernorm scales/biases and projection biases, ~half the leaf
COUNT but <1% of the BYTES — are concatenated into one streaming
update per dtype, while every large 2-D leaf keeps its per-leaf update
in its native tiled layout (no relayout, no serial DUS over big
buffers). The concat that regressed was the one over megabyte leaves;
the tail concat is a few hundred KB.

Correctness (both wrappers): valid for transformations whose update
math is elementwise per parameter (sgd, momentum, adam(w), rmsprop,
adafactor with factored=False). NOT valid inside the wrapper for
anything that couples elements ACROSS the tree — global-norm clipping
sees one flat vector PER DTYPE GROUP, so on a mixed f32/bf16 tree each
group would clip by its own norm (verified divergence in
tests/test_gpt_optimizers.py). Compose such transforms OUTSIDE:
``optax.chain(optax.clip_by_global_norm(c), flatten_optimizer(adam))``.
Per-leaf-shape-dependent transforms (factored adafactor, lars/lamb
trust ratios) can never be flattened; wrap those per-leaf.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class FlatState(NamedTuple):
    inner: Any          # {dtype_str: inner optax state on the flat vec}


def _group_leaves(tree):
    """leaves + treedef + {dtype: (indices, sizes, shapes)} grouping."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups = {}
    for i, leaf in enumerate(leaves):
        key = str(jnp.asarray(leaf).dtype)
        groups.setdefault(key, []).append(i)
    return leaves, treedef, groups


def _flatten_group(leaves, idxs):
    return jnp.concatenate([leaves[i].reshape(-1) for i in idxs])


def _unflatten_group(flat, leaves_like, idxs):
    # static Python offsets: traced split points would fail under jit
    offsets, total = [], 0
    for i in idxs:
        total += leaves_like[i].size
        offsets.append(total)
    parts = jnp.split(flat, offsets[:-1])
    return {i: p.reshape(leaves_like[i].shape)
            for i, p in zip(idxs, parts)}


def flatten_optimizer(inner: optax.GradientTransformation
                      ) -> optax.GradientTransformation:
    """Run `inner` on one flat vector per parameter dtype.

    The update tree comes back with each leaf's original shape and the
    dtype `inner` produced (optax.apply_updates casts to the param
    dtype as usual). Gradients and params are grouped by PARAM dtype so
    mixed trees (f32 master weights + bf16 expert stacks) stay exact.
    """

    def init(params):
        leaves, _, groups = _group_leaves(params)
        inner_states = {
            key: inner.init(_flatten_group(leaves, idxs))
            for key, idxs in groups.items()}
        return FlatState(inner=inner_states)

    def update(updates, state, params=None):
        # ALWAYS group by param dtype (matching init); grouping by the
        # grads' dtypes would mismatch the per-group inner states
        # whenever grad dtype differs from param dtype (e.g. f32 grads
        # for bf16 params). Without params the param dtypes are not
        # observable, and silently falling back to grad-dtype grouping
        # would corrupt the state lookup — refuse instead.
        if params is None:
            raise ValueError(
                "flatten_optimizer requires params at update() time: "
                "groups are keyed by param dtype (as at init)")
        g_leaves, treedef, _ = _group_leaves(updates)
        p_leaves, _, groups = _group_leaves(params)
        new_inner = {}
        out = [None] * len(g_leaves)
        for key, idxs in groups.items():
            flat_g = _flatten_group(g_leaves, idxs)
            flat_p = _flatten_group(p_leaves, idxs)
            flat_u, new_inner[key] = inner.update(
                flat_g, state.inner[key], flat_p)
            for i, u in _unflatten_group(flat_u, g_leaves, idxs).items():
                out[i] = u
        return (jax.tree_util.tree_unflatten(treedef, out),
                FlatState(inner=new_inner))

    return optax.GradientTransformation(init, update)


# -- grouped small-leaf updates ---------------------------------------------

#: leaves below this many elements join the flattened tail. 64k elems
#: (256 KiB at f32) keeps every GPT layernorm/bias leaf (<= 4*hidden)
#: and the lm_head bias in the tail while every 2-D projection matrix
#: (hidden^2 and up) stays per-leaf in its tiled layout.
SMALL_LEAF_ELEMS = 64 * 1024


class GroupedState(NamedTuple):
    small: Any          # {dtype_str: inner state on the flat tail vec}
    big: Any            # inner state on the tuple of large leaves


def _split_small(leaves, threshold):
    """(small_idxs_by_dtype, big_idxs) partition of leaf indices."""
    small, big = {}, []
    for i, leaf in enumerate(leaves):
        arr = jnp.asarray(leaf)
        if arr.size < threshold:
            small.setdefault(str(arr.dtype), []).append(i)
        else:
            big.append(i)
    return small, big


def group_small_leaves(inner: optax.GradientTransformation,
                       threshold: int = SMALL_LEAF_ELEMS
                       ) -> optax.GradientTransformation:
    """Run `inner` per-leaf on large leaves, fused on the small tail.

    Leaves with fewer than `threshold` elements are concatenated into
    one flat vector per PARAM dtype and updated as a single streaming
    kernel; the rest keep their per-leaf updates (and layouts). The
    update math is bitwise identical to per-leaf `inner` on the whole
    tree for elementwise transformations: concatenation commutes with
    elementwise ops, and the step counter advances identically in
    every partition (one `update` call each per step).

    Same caveats as `flatten_optimizer` (module docstring): compose
    cross-tree transforms OUTSIDE the wrapper.
    """

    def init(params):
        leaves, _ = jax.tree_util.tree_flatten(params)
        small, big = _split_small(leaves, threshold)
        return GroupedState(
            small={key: inner.init(_flatten_group(leaves, idxs))
                   for key, idxs in small.items()},
            big=inner.init(tuple(leaves[i] for i in big)),
        )

    def update(updates, state, params=None):
        # param-dtype/param-size partition, exactly as at init (see
        # flatten_optimizer.update for why grad-keyed grouping would
        # corrupt the state lookup)
        if params is None:
            raise ValueError(
                "group_small_leaves requires params at update() time: "
                "the partition is keyed by param size/dtype (as at "
                "init)")
        g_leaves, treedef = jax.tree_util.tree_flatten(updates)
        p_leaves, _ = jax.tree_util.tree_flatten(params)
        small, big = _split_small(p_leaves, threshold)
        out = [None] * len(g_leaves)
        new_small = {}
        for key, idxs in small.items():
            flat_u, new_small[key] = inner.update(
                _flatten_group(g_leaves, idxs), state.small[key],
                _flatten_group(p_leaves, idxs))
            for i, u in _unflatten_group(flat_u, g_leaves, idxs).items():
                out[i] = u
        big_u, new_big = inner.update(
            tuple(g_leaves[i] for i in big), state.big,
            tuple(p_leaves[i] for i in big))
        for i, u in zip(big, big_u):
            out[i] = u
        return (jax.tree_util.tree_unflatten(treedef, out),
                GroupedState(small=new_small, big=new_big))

    return optax.GradientTransformation(init, update)
