"""Flat-buffer optimizer wrapper: one fused update per dtype group.

`flatten_optimizer` wraps ANY elementwise optax transformation to run
on a single concatenated vector per param dtype, so the whole update
is a handful of big streaming kernels instead of one fusion per leaf.
Conceptually the TPU analogue of the reference's fused gradient path
(reference: srcs/python/kungfu/tensorflow/optimizers/sync_sgd.py
`nccl_fusion`/fuse): fuse many small per-tensor ops into few big ones.

**Measured NEGATIVE on v5e** (docs/benchmarks.md round-5 attribution):
the per-leaf adamw fusions were only 16.1 ms of the 104.6 ms GPT-2
b=12 step, and the flat variant REGRESSED the step to 131.1 ms — XLA
lowers the 100-leaf concatenate to a serial dynamic-update-slice loop
and relayouts every 2-D tiled leaf to the 1-D linear layout and back.
The wrapper is kept because it is correct (bitwise-parity tested),
cheap to maintain, and the trade can flip on backends/shapes where
concatenation is free; the in-repo benchmarks use per-leaf optimizers.

Correctness: valid for transformations whose update math is elementwise
per parameter (sgd, momentum, adam(w), rmsprop, adafactor with
factored=False). NOT valid inside the wrapper for anything that
couples elements ACROSS the tree — global-norm clipping sees one
flat vector PER DTYPE GROUP, so on a mixed f32/bf16 tree each group
would clip by its own norm (verified divergence in
tests/test_gpt_optimizers.py). Compose such transforms OUTSIDE:
``optax.chain(optax.clip_by_global_norm(c), flatten_optimizer(adam))``.
Per-leaf-shape-dependent transforms (factored adafactor, lars/lamb
trust ratios) can never be flattened; wrap those per-leaf.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class FlatState(NamedTuple):
    inner: Any          # {dtype_str: inner optax state on the flat vec}


def _group_leaves(tree):
    """leaves + treedef + {dtype: (indices, sizes, shapes)} grouping."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups = {}
    for i, leaf in enumerate(leaves):
        key = str(jnp.asarray(leaf).dtype)
        groups.setdefault(key, []).append(i)
    return leaves, treedef, groups


def _flatten_group(leaves, idxs):
    return jnp.concatenate([leaves[i].reshape(-1) for i in idxs])


def _unflatten_group(flat, leaves_like, idxs):
    # static Python offsets: traced split points would fail under jit
    offsets, total = [], 0
    for i in idxs:
        total += leaves_like[i].size
        offsets.append(total)
    parts = jnp.split(flat, offsets[:-1])
    return {i: p.reshape(leaves_like[i].shape)
            for i, p in zip(idxs, parts)}


def flatten_optimizer(inner: optax.GradientTransformation
                      ) -> optax.GradientTransformation:
    """Run `inner` on one flat vector per parameter dtype.

    The update tree comes back with each leaf's original shape and the
    dtype `inner` produced (optax.apply_updates casts to the param
    dtype as usual). Gradients and params are grouped by PARAM dtype so
    mixed trees (f32 master weights + bf16 expert stacks) stay exact.
    """

    def init(params):
        leaves, _, groups = _group_leaves(params)
        inner_states = {
            key: inner.init(_flatten_group(leaves, idxs))
            for key, idxs in groups.items()}
        return FlatState(inner=inner_states)

    def update(updates, state, params=None):
        # ALWAYS group by param dtype (matching init); grouping by the
        # grads' dtypes would mismatch the per-group inner states
        # whenever grad dtype differs from param dtype (e.g. f32 grads
        # for bf16 params). Without params the param dtypes are not
        # observable, and silently falling back to grad-dtype grouping
        # would corrupt the state lookup — refuse instead.
        if params is None:
            raise ValueError(
                "flatten_optimizer requires params at update() time: "
                "groups are keyed by param dtype (as at init)")
        g_leaves, treedef, _ = _group_leaves(updates)
        p_leaves, _, groups = _group_leaves(params)
        new_inner = {}
        out = [None] * len(g_leaves)
        for key, idxs in groups.items():
            flat_g = _flatten_group(g_leaves, idxs)
            flat_p = _flatten_group(p_leaves, idxs)
            flat_u, new_inner[key] = inner.update(
                flat_g, state.inner[key], flat_p)
            for i, u in _unflatten_group(flat_u, g_leaves, idxs).items():
                out[i] = u
        return (jax.tree_util.tree_unflatten(treedef, out),
                FlatState(inner=new_inner))

    return optax.GradientTransformation(init, update)
