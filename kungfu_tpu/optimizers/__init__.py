"""Distributed optimizers as optax gradient transformations.

TPU-native rebuilds of the reference's six distributed optimizers
(reference: srcs/python/kungfu/tensorflow/optimizers/): instead of wrapping
a TF optimizer object, each is an `optax.GradientTransformation` factory
that wraps an inner optax transform and injects ICI collectives. They are
designed to run *inside* the jitted SPMD train step (under `shard_map` over
a mesh axis), so the communication compiles onto ICI.

- `sync_sgd` — synchronous S-SGD: pmean of gradients (Horovod-equivalent).
- `sync_sgd_bucketed` — S-SGD with the pmean issued as fixed-byte
  reverse-backward-order buckets (the ICI mirror of the DCN
  `kungfu_tpu.grad_pipeline`); bitwise-identical values, fewer and
  larger collectives.
- `sma` — synchronous model averaging (SMA/EA-SGD): per-step weight
  averaging blended with factor alpha, overlapped with local updates.
- `pair_averaging` — AD-PSGD's ICI-native form: rotating ring-gossip
  weight averaging via collective_permute (the async DCN form lives in
  kungfu_tpu.parallel.pair_host).
- `ada_sgd` — adaptive hybrid: SMA before `change_step`, S-SGD after.
- `monitor_gradient_noise_scale`, `monitor_gradient_variance` — S-SGD plus
  online training-health statistics in optimizer state.
"""

from .ada_sgd import ada_sgd
from .fused import SMALL_LEAF_ELEMS, flatten_optimizer, group_small_leaves
from .async_sgd import PairAveragingState, pair_averaging
from .monitors import (
    attach_gradient_noise_scale,
    GNSMonitorState,
    VarianceMonitorState,
    monitor_gradient_noise_scale,
    monitor_gradient_variance,
)
from .sma_sgd import sma
from .sync_sgd import (bucketed_all_reduce_mean, sync_sgd,
                       sync_sgd_bucketed)

__all__ = [
    "flatten_optimizer",
    "group_small_leaves",
    "SMALL_LEAF_ELEMS",
    "sync_sgd",
    "sync_sgd_bucketed",
    "bucketed_all_reduce_mean",
    "sma",
    "pair_averaging",
    "PairAveragingState",
    "ada_sgd",
    "monitor_gradient_noise_scale",
    "monitor_gradient_variance",
    "attach_gradient_noise_scale",
    "GNSMonitorState",
    "VarianceMonitorState",
]
