"""Synchronous model averaging (SMA / EA-SGD).

Every step, each worker blends its weights toward the cluster-average model
with factor alpha while still applying its *local* gradients (reference:
srcs/python/kungfu/tensorflow/optimizers/sma_sgd.py:45-74; SMA paper
"CrossBow", EA-SGD NIPS'15). The weight averaging decouples convergence
from global batch size — the property that keeps accuracy at large
cluster sizes where plain S-SGD degrades (reference README.md:188-193).

In update-delta form (optax semantics):

    delta = inner_update(local_grads) + alpha * (mean(params) - params)

which equals the reference's assign-then-apply sequence exactly, since the
gradients were computed at the pre-blend parameters there too.
"""

from __future__ import annotations

import jax
import optax

from ..ops.collective import all_reduce_mean


def sma(
    inner: optax.GradientTransformation,
    alpha: float = 0.1,
    axis_name: str = "data",
) -> optax.GradientTransformation:
    def init(params):
        return inner.init(params)

    def update(grads, state, params):
        if params is None:
            raise ValueError("sma() requires params to average")
        avg_params = all_reduce_mean(params, axis_name)
        updates, new_state = inner.update(grads, state, params)
        updates = jax.tree_util.tree_map(
            lambda u, p, a: u + alpha * (a - p), updates, params, avg_params
        )
        return updates, new_state

    return optax.GradientTransformation(init, update)
