"""Pair averaging (AD-PSGD) — ICI-native synchronous-gossip form.

The reference's PairAveragingOptimizer pulls a random peer's model over
TCP, averages 0.5/0.5, applies local gradients, and publishes its model
(reference: srcs/python/kungfu/tensorflow/optimizers/async_sgd.py:78-142).
XLA has no one-sided async P2P inside a compiled step, so the framework
offers the algorithm in two forms (SURVEY §7 "hard parts"):

1. **This module** — gossip over ICI: each step, workers pair up around the
   ring with a rotating stride and average weights 0.5/0.5 via
   `collective_permute`. Deterministic pairing replaces random peer choice
   (ppermute's permutation must be static), cycling through all strides so
   information mixes like AD-PSGD's random walk. Everything stays inside
   the jitted step at ICI bandwidth. Measured evidence (BASELINE.json
   `resnet50_pair_averaging_convergence_proxy`): at a full training
   budget every worker row reaches sync-SGD accuracy (gap 0.0); at a
   deliberately tight budget the worst row trails sync SGD by ~1.3% —
   the expected mixing lag, not divergence.

2. `kungfu_tpu.parallel.pair_host` — the faithful asynchronous DCN form:
   random peer, model pulled via the libkf P2P store with double-buffered
   prefetch, matching the reference's AsyncRequestModel design.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..ops.collective import ring_neighbor


class PairAveragingState(NamedTuple):
    step: jnp.ndarray
    inner: optax.OptState


def pair_averaging(
    inner: optax.GradientTransformation,
    axis_name: str = "data",
    blend: float = 0.5,
) -> optax.GradientTransformation:
    def init(params):
        return PairAveragingState(
            step=jnp.zeros((), dtype=jnp.int32), inner=inner.init(params)
        )

    def update(grads, state, params):
        if params is None:
            raise ValueError("pair_averaging() requires params")
        n = lax.axis_size(axis_name)
        updates, new_inner = inner.update(grads, state.inner, params)
        if n > 1:
            # Hypercube gossip: cycle through power-of-two strides
            # {1, 2, 4, ..., <n}. ppermute permutations must be static, so
            # lax.switch selects among the precompiled strides — O(log n)
            # branches (cycling all n-1 strides would compile O(n) copies
            # of the whole-model rotation). Power-of-two pairings mix any
            # initial spread in one sweep of log2(n) steps, which
            # dominates uniform-random pairing in mixing rate.
            strides = []
            s = 1
            while s < n:
                strides.append(s)
                s *= 2
            branches = [
                (lambda t, s=s: jax.tree_util.tree_map(
                    lambda x: ring_neighbor(x, axis_name, s), t))
                for s in strides
            ]
            idx = state.step % len(branches)
            peer_params = lax.switch(idx, branches, params)
            updates = jax.tree_util.tree_map(
                lambda u, p, q: u + blend * (q - p), updates, params,
                peer_params,
            )
        return updates, PairAveragingState(step=state.step + 1,
                                           inner=new_inner)

    return optax.GradientTransformation(init, update)
