"""Adaptive SGD: SMA before `change_step`, synchronous SGD after.

The reference's AdaptiveSGDOptimizer exploits that model averaging helps
early, noisy training while S-SGD converges faster late (reference:
srcs/python/kungfu/tensorflow/optimizers/ada_sgd.py:26-83). The switch is
a `lax.cond` on the step counter — every worker holds the same counter, so
all chips take the same branch and the collectives stay aligned. The
reference's AdaSGDHook re-broadcast at the switch point is unnecessary
here: SMA's final blend already has every replica within alpha-contraction
of the mean, and the caller can invoke
`kungfu_tpu.parallel.broadcast_params` at the boundary for bit-exactness.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..ops.collective import all_reduce_mean


class AdaSGDState(NamedTuple):
    step: jnp.ndarray
    inner: optax.OptState


def ada_sgd(
    inner: optax.GradientTransformation,
    change_step: int,
    alpha: float = 0.1,
    axis_name: str = "data",
) -> optax.GradientTransformation:
    def init(params):
        return AdaSGDState(
            step=jnp.zeros((), dtype=jnp.int32), inner=inner.init(params)
        )

    def update(grads, state, params):
        if params is None:
            raise ValueError("ada_sgd() requires params")

        # Both branches perform exactly one pmean over the same-sized tree
        # (params vs grads share structure), so either branch keeps every
        # chip's collective schedule identical.
        def sma_branch(args):
            grads_, params_ = args
            avg_params = all_reduce_mean(params_, axis_name)
            updates, new_inner = inner.update(grads_, state.inner, params_)
            updates = jax.tree_util.tree_map(
                lambda u, p, a: u + alpha * (a - p), updates, params_,
                avg_params,
            )
            return updates, new_inner

        def ssgd_branch(args):
            grads_, params_ = args
            avg_grads = all_reduce_mean(grads_, axis_name)
            return inner.update(avg_grads, state.inner, params_)

        updates, new_inner = lax.cond(
            state.step < change_step, sma_branch, ssgd_branch, (grads, params)
        )
        return updates, AdaSGDState(step=state.step + 1, inner=new_inner)

    return optax.GradientTransformation(init, update)
