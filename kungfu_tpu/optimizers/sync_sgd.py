"""Synchronous SGD: gradient all-reduce before the inner update.

The Horovod-equivalent S-SGD data-parallel optimizer (reference:
srcs/python/kungfu/tensorflow/optimizers/sync_sgd.py:48-79). On TPU the
per-gradient all-reduce graph machinery reduces to a single `pmean` per
leaf, which XLA fuses and schedules onto ICI; no fuse/defuse or NCCL order
negotiation is needed (SURVEY §5.8, §7).
"""

from __future__ import annotations

import optax

from ..ops.collective import all_reduce_mean


def sync_sgd(
    inner: optax.GradientTransformation, axis_name: str = "data"
) -> optax.GradientTransformation:
    """Wrap `inner` so gradients are cluster-averaged before it runs.

    Use inside a shard_map'd train step:

        tx = sync_sgd(optax.sgd(0.1))
        updates, opt_state = tx.update(grads, opt_state, params)
    """

    def init(params):
        return inner.init(params)

    def update(grads, state, params=None):
        grads = all_reduce_mean(grads, axis_name)
        return inner.update(grads, state, params)

    return optax.GradientTransformation(init, update)
