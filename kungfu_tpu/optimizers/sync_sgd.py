"""Synchronous SGD: gradient all-reduce before the inner update.

The Horovod-equivalent S-SGD data-parallel optimizer (reference:
srcs/python/kungfu/tensorflow/optimizers/sync_sgd.py:48-79). On TPU the
per-gradient all-reduce graph machinery reduces to a single `pmean` per
leaf, which XLA fuses and schedules onto ICI; no fuse/defuse or NCCL order
negotiation is needed (SURVEY §5.8, §7).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..ops.collective import all_reduce_mean, bucket_schedule


def sync_sgd(
    inner: optax.GradientTransformation, axis_name: str = "data"
) -> optax.GradientTransformation:
    """Wrap `inner` so gradients are cluster-averaged before it runs.

    Use inside a shard_map'd train step:

        tx = sync_sgd(optax.sgd(0.1))
        updates, opt_state = tx.update(grads, opt_state, params)
    """

    def init(params):
        return inner.init(params)

    def update(grads, state, params=None):
        grads = all_reduce_mean(grads, axis_name)
        return inner.update(grads, state, params)

    return optax.GradientTransformation(init, update)


def bucketed_all_reduce_mean(grads, axis_name: str = "data",
                             bucket_bytes: int = 1 << 20):
    """pmean of a gradient pytree as fixed-byte reverse-order buckets.

    The ICI mirror of the DCN `GradBucketPipeline`: instead of one
    pmean per leaf (hundreds of tiny collectives for a transformer's
    layernorm/bias tail), leaves are concatenated into
    `bucket_schedule`'s dtype-homogeneous, reverse-backward-order
    buckets and each bucket is ONE pmean. XLA sees a handful of
    well-sized collectives it can schedule against the backward
    instead of a fusion puzzle. Bitwise-identical to the per-leaf form:
    psum is elementwise, so bucketing changes the op count, never a
    value. Must be called inside `shard_map`/`pmap` over `axis_name`.
    """
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    flat = [jnp.ravel(l) for l in leaves]
    pieces = [[] for _ in leaves]  # (offset, reduced-slice) per leaf
    for _, spans in bucket_schedule(grads, bucket_bytes):
        bucket = jnp.concatenate([flat[i][o:o + n] for i, o, n in spans])
        red = lax.pmean(bucket, axis_name)
        off = 0
        for i, o, n in spans:
            pieces[i].append((o, red[off:off + n]))
            off += n
    out = []
    for i, l in enumerate(leaves):
        if not pieces[i]:  # zero-size leaf
            out.append(l)
            continue
        parts = [p for _, p in sorted(pieces[i], key=lambda t: t[0])]
        out.append(jnp.reshape(jnp.concatenate(parts), jnp.shape(l)))
    return jax.tree_util.tree_unflatten(treedef, out)


def sync_sgd_bucketed(
    inner: optax.GradientTransformation, axis_name: str = "data",
    bucket_bytes: int = 1 << 20,
) -> optax.GradientTransformation:
    """`sync_sgd` with the gradient pmean bucketed
    (`bucketed_all_reduce_mean`). Same values bit-for-bit; fewer,
    larger collectives on the wire."""

    def init(params):
        return inner.init(params)

    def update(grads, state, params=None):
        grads = bucketed_all_reduce_mean(grads, axis_name, bucket_bytes)
        return inner.update(grads, state, params)

    return optax.GradientTransformation(init, update)
