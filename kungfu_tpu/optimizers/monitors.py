"""S-SGD with online training-health monitors in optimizer state.

Rebuilds of MonitorGradientNoiseScaleOptimizer and
MonitorGradientVarianceOptimizer (reference: srcs/python/kungfu/tensorflow/
optimizers/{grad_noise_scale,grad_variance}.py). Where the reference
prints via tf.print, these keep the latest statistic in optimizer state so
the training loop (or an adaptation policy) reads it directly — the
statistic is what drives adaptive batch-size/cluster-size decisions.

Collective cost: the S-SGD forms (`monitor_gradient_noise_scale`,
`monitor_gradient_variance`) piggyback on the gradient all-reduce they
already perform (GNS reuses local + averaged gradients; variance adds one
psum of squared gradients). `attach_gradient_noise_scale` wraps transforms
that do NOT average gradients, so its all-reduce is a real extra per-step
collective.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..ops.collective import all_reduce_mean
from ..ops.monitor import (
    GradNoiseScaleState,
    gradient_variance,
    init_noise_scale,
    tree_sq_norm,
    update_noise_scale_from_sq,
)


class GNSMonitorState(NamedTuple):
    step: jnp.ndarray
    gns: GradNoiseScaleState
    noise_scale: jnp.ndarray  # latest (EMA-smoothed) estimate
    inner: optax.OptState


def _gns_monitored(
    inner: optax.GradientTransformation,
    device_batch_size: int,
    axis_name: str,
    alpha: float,
    interval: int,
    feed_averaged_to_inner: bool,
) -> optax.GradientTransformation:
    """Shared GNS-monitor builder; the flag selects which gradients the
    inner transform consumes (averaged = S-SGD semantics, raw = leave the
    inner optimizer's own collective behavior untouched).

    `interval` gates only the statistic's EMA commit: the extra
    all-reduce + norm reductions run every step regardless (the tick is a
    traced value, so XLA cannot elide the collective — an interval > 1
    reduces estimate churn, not cost).
    """

    def init(params):
        return GNSMonitorState(
            step=jnp.zeros((), dtype=jnp.int32),
            gns=init_noise_scale(),
            noise_scale=jnp.zeros((), dtype=jnp.float32),
            inner=inner.init(params),
        )

    def update(grads, state, params=None):
        n = lax.axis_size(axis_name)
        avg_grads = all_reduce_mean(grads, axis_name)
        new_gns, estimate = update_noise_scale_from_sq(
            state.gns,
            batch_small=device_batch_size,
            batch_big=device_batch_size * n,
            g_sq_small=tree_sq_norm(grads),
            g_sq_big=tree_sq_norm(avg_grads),
            alpha=alpha,
            axis_name=axis_name,
        )
        tick = (state.step % interval) == 0
        gns_state = jax.tree_util.tree_map(
            lambda new, old: jnp.where(tick, new, old), new_gns, state.gns
        )
        noise = jnp.where(tick, estimate, state.noise_scale)
        inner_grads = avg_grads if feed_averaged_to_inner else grads
        updates, new_inner = inner.update(inner_grads, state.inner, params)
        return updates, GNSMonitorState(
            step=state.step + 1, gns=gns_state, noise_scale=noise,
            inner=new_inner,
        )

    return optax.GradientTransformation(init, update)


def monitor_gradient_noise_scale(
    inner: optax.GradientTransformation,
    device_batch_size: int,
    axis_name: str = "data",
    alpha: float = 0.6,
    interval: int = 1,
) -> optax.GradientTransformation:
    """S-SGD whose state tracks the gradient noise scale B_noise."""
    return _gns_monitored(inner, device_batch_size, axis_name, alpha,
                          interval, feed_averaged_to_inner=True)


def attach_gradient_noise_scale(
    inner: optax.GradientTransformation,
    device_batch_size: int,
    axis_name: str = "data",
    alpha: float = 0.6,
    interval: int = 1,
) -> optax.GradientTransformation:
    """Attach the GNS monitor to ANY transform without altering it.

    Unlike :func:`monitor_gradient_noise_scale` (which is S-SGD plus the
    statistic), this passes the RAW local gradients through to ``inner``,
    so model-averaging optimizers (SMA, pair averaging) keep their exact
    semantics — the configuration the reference's BERT benchmark runs
    (SynchronousAveragingOptimizer + noise-scale monitor, reference:
    srcs/python/kungfu/tensorflow/optimizers/grad_noise_scale.py:37-69
    wrapping any optimizer passed in). Costs one extra all-reduce to form
    the large-batch gradient the estimator compares against.
    """
    return _gns_monitored(inner, device_batch_size, axis_name, alpha,
                          interval, feed_averaged_to_inner=False)


class VarianceMonitorState(NamedTuple):
    step: jnp.ndarray
    variance: jnp.ndarray  # latest summed gradient variance
    inner: optax.OptState


def monitor_gradient_variance(
    inner: optax.GradientTransformation,
    axis_name: str = "data",
    interval: int = 1,
) -> optax.GradientTransformation:
    """S-SGD whose state tracks summed cross-worker gradient variance."""

    def init(params):
        return VarianceMonitorState(
            step=jnp.zeros((), dtype=jnp.int32),
            variance=jnp.zeros((), dtype=jnp.float32),
            inner=inner.init(params),
        )

    def update(grads, state, params=None):
        avg_grads = all_reduce_mean(grads, axis_name)
        var = gradient_variance(grads, axis_name)
        tick = (state.step % interval) == 0
        variance = jnp.where(tick, var, state.variance)
        updates, new_inner = inner.update(avg_grads, state.inner, params)
        return updates, VarianceMonitorState(
            step=state.step + 1, variance=variance, inner=new_inner
        )

    return optax.GradientTransformation(init, update)
