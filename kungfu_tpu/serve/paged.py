"""Paged-attention GPT forward: decode over a block-table KV pool.

The dense decode path (`models.gpt.CausalSelfAttention`, decode=True)
holds one [B, max_position, H, D] cache per layer with a SINGLE
scalar cursor — every row of the batch must be at the same position,
which is exactly what continuous batching breaks (each sequence in
the batch is at its own length). This module is the paged replacement:

- the KV cache is the `serve.kv_cache.PagedKVPool`'s tensors
  ([layers, blocks, block_tokens, heads, head_dim]);
- each decode step takes per-row block tables + lengths, scatters the
  new token's k/v at each row's own (block, offset), and attends over
  the row's own visible prefix — vLLM's PagedAttention decode shape.
  Two attention paths behind the same signature (``kernel=``): the
  stock-JAX gather ("functional", the default and the parity oracle)
  and the fused Pallas kernel (`ops.paged_attn`), which chases the
  block table with scalar-prefetch index maps instead of
  materializing the contiguous [T, h, d] re-gather — the
  `KF_SERVE_KERNEL` knob picks at engine construction;
- **chunked prefill** (`prefill_chunk`): a long prompt fills its pool
  blocks KF_SERVE_PREFILL_CHUNK tokens at a time with the decode
  step's exact numeric recipe, so the engine can interleave admission
  with decode iterations instead of stalling the running batch behind
  one long forward (Orca's iteration-level scheduling applied to
  prefill), and a CoW-shared prefix can skip its chunks entirely;
- **prefill rides the model itself**: one batched causal forward via
  the model's prefill path fills a dense per-layer cache (which on
  TPU runs the flash VMEM-resident scheme when the config says
  ``attention="flash"`` — the same kernel the training rows use), and
  the filled prefix is copied into the sequence's pool blocks. Time
  to first token is one forward, and serve-prefill numerics cannot
  drift from the model's.

Numerics follow the model's decode branch exactly: f32 scores/softmax
with the config dtype everywhere else (the per-sequence-parity test
in tests/test_serve.py pins token agreement against `gpt_generate`,
and batch-composition bitwise parity against itself).

Everything here is FUNCTIONAL: `decode_step` takes and returns the
pool tensors (the engine jits it with the pools donated), and nothing
reads clocks, env or the allocator — the host-side scheduling stays
in `serve.engine` where the trace-purity lint can see it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _supported(cfg) -> None:
    if cfg.num_experts:
        raise NotImplementedError(
            "paged decode serves dense GPT configs; MoE decode routing "
            "is not implemented")


def init_pool_tensors(cfg, num_blocks: int, block_tokens: int):
    """(k, v) pool tensors [L, num_blocks+1, block_tokens, H, D] in
    the config dtype (+1: block 0 is the allocator's scratch block)."""
    _supported(cfg)
    h, d = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    shape = (cfg.num_layers, num_blocks + 1, block_tokens, h, d)
    return (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


# -- explicit-params module applications (gpt_pipeline_forward style) ---------


def _dense(p, x, dtype):
    return (x.astype(dtype) @ p["kernel"].astype(dtype)
            + p["bias"].astype(dtype))


def _qkv(p, x, dtype):
    """DenseGeneral((heads, head_dim)): kernel [H, h, d], bias [h, d]."""
    return (jnp.einsum("bh,hnd->bnd", x.astype(dtype),
                       p["kernel"].astype(dtype))
            + p["bias"].astype(dtype))


def _attn_out(p, x, dtype):
    """DenseGeneral(hidden, axis=(-2, -1)): kernel [h, d, H], bias [H]."""
    return (jnp.einsum("bnd,ndh->bh", x.astype(dtype),
                       p["kernel"].astype(dtype))
            + p["bias"].astype(dtype))


def _layernorm(p, x, dtype, eps: float = 1e-6):
    """flax LayerNorm(dtype=cfg.dtype, param_dtype=f32): f32 stats,
    f32 scale/bias, output in the compute dtype."""
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(dtype)


def decode_step(cfg, params, pool_k, pool_v, tables, lengths, tokens,
                kernel: str = "functional"):
    """One continuous-batching decode iteration.

    - `tables` [B, max_blocks] int32 — each row's block table (unused
      entries point at the scratch block);
    - `lengths` [B] int32 — tokens already in each row's cache; the
      incoming token is written at position `lengths[b]` (inactive pad
      rows carry length 0 and a scratch table — their writes land in
      the scratch block and their outputs are ignored);
    - `tokens` [B] int32 — each row's current input token;
    - `kernel` — "functional" (stock-JAX gather, the parity oracle) or
      a `ops.paged_attn` scheme ("auto"/"resident"/"stream"): the
      fused kernel replaces the contiguous re-gather with table-
      chasing scalar-prefetch DMA. The scatter stays stock JAX either
      way (one token per row — nothing to fuse).

    Returns ``(logits [B, vocab] f32, pool_k, pool_v)``. Rows are
    independent: a row's logits depend only on its own table/length/
    token, which is what makes batch composition a scheduling choice
    instead of a numerics choice (pinned bitwise by tests).
    """
    _supported(cfg)
    dtype = cfg.dtype
    bsz = tokens.shape[0]
    max_blocks = tables.shape[1]
    bt = pool_k.shape[2]
    d = cfg.hidden_size // cfg.num_heads
    rows = jnp.arange(bsz)
    blk = tables[rows, lengths // bt]       # [B] destination block id
    off = lengths % bt                      # [B] offset inside it
    visible = (jnp.arange(max_blocks * bt)[None, :]
               <= lengths[:, None])         # positions 0..length incl.
    if kernel != "functional":
        from ..ops import paged_attn
    nbp1 = pool_k.shape[1]                  # pool blocks + scratch

    wte = params["wte"]["embedding"].astype(dtype)
    wpe = params["wpe"]["embedding"].astype(dtype)
    x = wte[tokens] + wpe[lengths]          # [B, H]
    for layer in range(cfg.num_layers):
        p = params[f"Block_{layer}"]
        y = _layernorm(p["LayerNorm_0"], x, dtype)
        a = p["CausalSelfAttention_0"]
        q = _qkv(a["query"], y, dtype)      # [B, h, d]
        k = _qkv(a["key"], y, dtype)
        v = _qkv(a["value"], y, dtype)
        pool_k = pool_k.at[layer, blk, off].set(k)
        pool_v = pool_v.at[layer, blk, off].set(v)
        if kernel != "functional":
            # the per-layer pool slice rides in as a RESHAPE of the
            # whole pool (free) + a block_base offset in the index
            # map — slicing pool_k[layer] would copy the layer's
            # entire pool into a pallas operand every step
            kp = pool_k.reshape((cfg.num_layers * nbp1,)
                                + pool_k.shape[2:])
            vp = pool_v.reshape((cfg.num_layers * nbp1,)
                                + pool_v.shape[2:])
            o = paged_attn.paged_attention(
                q, kp, vp, tables, lengths,
                block_base=layer * nbp1,
                scheme=None if kernel == "auto" else kernel)
            o = o.astype(dtype)
        else:
            # gather each row's blocks into a contiguous [T, h, d] view
            kk = pool_k[layer][tables].reshape(bsz, max_blocks * bt,
                                               cfg.num_heads, d)
            vv = pool_v[layer][tables].reshape(bsz, max_blocks * bt,
                                               cfg.num_heads, d)
            # f32 scores/softmax — the model's decode-branch numerics
            s = jnp.einsum("bnd,btnd->bnt", q.astype(jnp.float32),
                           kk.astype(jnp.float32)) * (d ** -0.5)
            s = jnp.where(visible[:, None, :], s,
                          jnp.finfo(jnp.float32).min)
            w = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bnt,btnd->bnd", w,
                           vv.astype(jnp.float32)).astype(dtype)
        x = x + _attn_out(a["out"], o, dtype)
        y = _layernorm(p["LayerNorm_1"], x, dtype)
        y = _dense(p["Dense_0"], y, dtype)
        y = jax.nn.gelu(y)
        y = _dense(p["Dense_1"], y, dtype)
        x = x + y
    x = _layernorm(params["LayerNorm_0"], x, dtype)
    logits = _dense(params["lm_head"], x, jnp.float32)
    return logits, pool_k, pool_v


def make_decode_fn(cfg, kernel: str = "functional"):
    """The jitted decode step for one engine: pools donated (the pool
    is updated in place across iterations, never copied). The engine
    always calls it at its full (max_batch, max_blocks) shapes, so
    every iteration of the serving loop is ONE compiled program
    regardless of which slots are live. `kernel` is baked in at trace
    time (the engine resolves the KF_SERVE_KERNEL knob + plan ONCE at
    construction — see DecodeEngine)."""

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def fn(params, pool_k, pool_v, tables, lengths, tokens):
        return decode_step(cfg, params, pool_k, pool_v, tables,
                           lengths, tokens, kernel=kernel)

    return fn


def prefill_chunk(cfg, params, pool_k, pool_v, table, start, tokens,
                  true_len):
    """Incremental prefill: run `tokens` [C] (positions ``start ..
    start+C-1``) of ONE sequence against its pool blocks, with the
    decode step's exact numeric recipe (f32 scores/softmax, finfo.min
    masking) applied causally WITHIN the chunk — query i sees pool
    positions 0..start+i inclusive, its own freshly scattered k/v
    included. The engine calls this repeatedly to prefill
    KF_SERVE_PREFILL_CHUNK tokens per iteration, and to prefill only
    the non-shared remainder of a CoW-shared prefix.

    - `table` [max_blocks] int32 — the sequence's padded block-table
      row (unused entries point at scratch);
    - `start` scalar int32 — first position of this chunk (everything
      before it is already in the pool: earlier chunks or shared
      blocks);
    - `true_len` scalar int32 — ``start + real_tokens``; padded tail
      positions (>= true_len) scatter into the scratch block and mask
      themselves out of every real query's visibility.

    Returns ``(logits [C, vocab] f32, pool_k, pool_v)`` — the caller
    reads the last REAL row's argmax when the prompt completes.
    """
    _supported(cfg)
    dtype = cfg.dtype
    c = tokens.shape[0]
    max_blocks = table.shape[0]
    bt = pool_k.shape[2]
    d = cfg.hidden_size // cfg.num_heads
    pos = start + jnp.arange(c, dtype=jnp.int32)      # [C]
    real = pos < true_len
    blk = jnp.where(real, table[pos // bt], 0)        # pad -> scratch
    off = pos % bt
    t = max_blocks * bt
    # query i sees pool positions 0..pos[i] inclusive
    visible = (jnp.arange(t)[None, :] <= pos[:, None]) \
        & real[:, None]

    wte = params["wte"]["embedding"].astype(dtype)
    wpe = params["wpe"]["embedding"].astype(dtype)
    x = wte[tokens] + wpe[pos]                        # [C, H]
    for layer in range(cfg.num_layers):
        p = params[f"Block_{layer}"]
        y = _layernorm(p["LayerNorm_0"], x, dtype)
        a = p["CausalSelfAttention_0"]
        q = _qkv(a["query"], y, dtype)                # [C, h, d]
        k = _qkv(a["key"], y, dtype)
        v = _qkv(a["value"], y, dtype)
        pool_k = pool_k.at[layer, blk, off].set(k)
        pool_v = pool_v.at[layer, blk, off].set(v)
        kk = pool_k[layer][table].reshape(t, cfg.num_heads, d)
        vv = pool_v[layer][table].reshape(t, cfg.num_heads, d)
        s = jnp.einsum("cnd,tnd->cnt", q.astype(jnp.float32),
                       kk.astype(jnp.float32)) * (d ** -0.5)
        s = jnp.where(visible[:, None, :], s,
                      jnp.finfo(jnp.float32).min)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("cnt,tnd->cnd", w,
                       vv.astype(jnp.float32)).astype(dtype)
        x = x + _attn_out(a["out"], o, dtype)
        y = _layernorm(p["LayerNorm_1"], x, dtype)
        y = _dense(p["Dense_0"], y, dtype)
        y = jax.nn.gelu(y)
        y = _dense(p["Dense_1"], y, dtype)
        x = x + y
    x = _layernorm(params["LayerNorm_0"], x, dtype)
    logits = _dense(params["lm_head"], x, jnp.float32)
    return logits, pool_k, pool_v


def make_prefill_chunk_fn(cfg):
    """Jitted `prefill_chunk` with the pools donated; the engine caches
    one per chunk length (chunks are padded to block-sized buckets, so
    the compile count is bounded like the whole-prefill path's)."""

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def fn(params, pool_k, pool_v, table, start, tokens, true_len):
        return prefill_chunk(cfg, params, pool_k, pool_v, table,
                             start, tokens, true_len)

    return fn


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _copy_blocks(pool_k, pool_v, src, dst):
    pool_k = pool_k.at[:, dst].set(pool_k[:, src])
    pool_v = pool_v.at[:, dst].set(pool_v[:, src])
    return pool_k, pool_v


def copy_blocks(pool_k, pool_v, copies):
    """Apply the allocator's copy-on-write list: ONE donated gather/
    scatter for all (src, dst) pairs of this iteration, all layers at
    once — not a Python loop of whole-pool copies."""
    src = np.asarray([c[0] for c in copies], np.int32)
    dst = np.asarray([c[1] for c in copies], np.int32)
    return _copy_blocks(pool_k, pool_v, src, dst)


#: per-engine-model jitted whole-prefill (id-keyed: serving owns ONE
#: long-lived model; jit itself caches per prompt-bucket shape). The
#: eager model.apply this replaces cost ~50x the compiled forward in
#: per-op dispatch — it was the prefill_ms dominator of every
#: BENCH_r16 cell, not the forward's FLOPs.
_PREFILL_JIT: dict = {}


def _make_prefill_fn(model):
    cfg = model.config

    @jax.jit
    def fn(params, prompt):
        abstract = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0), prompt[:, :1],
                               decode=True))
        cache = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), abstract["cache"])
        logits, mut = model.apply(
            {"params": params, "cache": cache}, prompt, prefill=True,
            mutable=["cache"])
        t = prompt.shape[1]
        ks = jnp.stack([
            mut["cache"][f"Block_{i}"]["CausalSelfAttention_0"]
            ["k"][:, :t] for i in range(cfg.num_layers)])
        vs = jnp.stack([
            mut["cache"][f"Block_{i}"]["CausalSelfAttention_0"]
            ["v"][:, :t] for i in range(cfg.num_layers)])
        return logits.astype(jnp.float32), ks, vs

    return fn


def prefill(model, params, prompt):
    """Batched causal prefill through the MODEL's own prefill path.

    `prompt` [B, T] int32. Returns ``(logits [B, T, vocab] f32, ks,
    vs)`` with ks/vs [L, B, T, h, d] — the filled cache prefix, ready
    for `write_prefill` to scatter into pool blocks. One jitted
    forward per prompt-bucket shape, same numerics as
    `gpt_generate`'s prefill (it IS the same code path). Callers that
    pad the prompt to a length bucket (the engine does, to bound
    compile count) read the logits at the last REAL position —
    causal masking keeps positions < T independent of the padding
    behind them.
    """
    _supported(model.config)
    fn = _PREFILL_JIT.get(id(model))
    if fn is None:
        fn = _PREFILL_JIT[id(model)] = _make_prefill_fn(model)
    return fn(params, prompt)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _scatter_blocks(pool_k, pool_v, ks, vs, blocks):
    """One donated scatter of block-aligned K/V ([L, n*bt, h, d])
    into pool blocks `blocks` [n] — NOT a Python loop of un-jitted
    `.at[].set()` calls, each of which would copy the entire tier's
    KV memory per block on the hot admission path."""
    n = blocks.shape[0]
    bt = pool_k.shape[2]
    shape = (ks.shape[0], n, bt) + ks.shape[2:]
    pool_k = pool_k.at[:, blocks].set(ks.reshape(shape))
    pool_v = pool_v.at[:, blocks].set(vs.reshape(shape))
    return pool_k, pool_v


def write_prefill(pool_k, pool_v, table, ks, vs, block_tokens: int):
    """Scatter one sequence's prefill K/V ([L, T_padded, h, d], padded
    to the block-sized bucket so T_padded == len(table)*block_tokens)
    into its block table (a host-side list of block ids). The padded
    tail lands in owned blocks past the sequence's length — never
    visible (attention masks by length), and it keeps the scatter one
    jitted donated call per admission. Returns the updated pools."""
    t = ks.shape[1]
    if t != len(table) * block_tokens:
        raise ValueError(
            f"prefill K/V length {t} != {len(table)} blocks x "
            f"{block_tokens} tokens — pad the prompt to its bucket")
    blocks = np.asarray(table, np.int32)
    return _scatter_blocks(pool_k, pool_v, ks, vs, blocks)


def max_blocks_for(max_len: int, block_tokens: int) -> int:
    """Block-table width covering `max_len` tokens."""
    return int(np.ceil(max_len / block_tokens))
