"""Paged-attention GPT forward: decode over a block-table KV pool.

The dense decode path (`models.gpt.CausalSelfAttention`, decode=True)
holds one [B, max_position, H, D] cache per layer with a SINGLE
scalar cursor — every row of the batch must be at the same position,
which is exactly what continuous batching breaks (each sequence in
the batch is at its own length). This module is the paged replacement:

- the KV cache is the `serve.kv_cache.PagedKVPool`'s tensors
  ([layers, blocks, block_tokens, heads, head_dim]);
- each decode step takes per-row block tables + lengths, scatters the
  new token's k/v at each row's own (block, offset), gathers each
  row's blocks back into a contiguous view, and masks attention to
  the row's own visible prefix — vLLM's PagedAttention decode shape,
  expressed in stock JAX gather/scatter (a Pallas kernel drops in
  behind the same signature when a TPU session warrants it);
- **prefill rides the model itself**: one batched causal forward via
  the model's prefill path fills a dense per-layer cache (which on
  TPU runs the flash VMEM-resident scheme when the config says
  ``attention="flash"`` — the same kernel the training rows use), and
  the filled prefix is copied into the sequence's pool blocks. Time
  to first token is one forward, and serve-prefill numerics cannot
  drift from the model's.

Numerics follow the model's decode branch exactly: f32 scores/softmax
with the config dtype everywhere else (the per-sequence-parity test
in tests/test_serve.py pins token agreement against `gpt_generate`,
and batch-composition bitwise parity against itself).

Everything here is FUNCTIONAL: `decode_step` takes and returns the
pool tensors (the engine jits it with the pools donated), and nothing
reads clocks, env or the allocator — the host-side scheduling stays
in `serve.engine` where the trace-purity lint can see it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _supported(cfg) -> None:
    if cfg.num_experts:
        raise NotImplementedError(
            "paged decode serves dense GPT configs; MoE decode routing "
            "is not implemented")


def init_pool_tensors(cfg, num_blocks: int, block_tokens: int):
    """(k, v) pool tensors [L, num_blocks+1, block_tokens, H, D] in
    the config dtype (+1: block 0 is the allocator's scratch block)."""
    _supported(cfg)
    h, d = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    shape = (cfg.num_layers, num_blocks + 1, block_tokens, h, d)
    return (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


# -- explicit-params module applications (gpt_pipeline_forward style) ---------


def _dense(p, x, dtype):
    return (x.astype(dtype) @ p["kernel"].astype(dtype)
            + p["bias"].astype(dtype))


def _qkv(p, x, dtype):
    """DenseGeneral((heads, head_dim)): kernel [H, h, d], bias [h, d]."""
    return (jnp.einsum("bh,hnd->bnd", x.astype(dtype),
                       p["kernel"].astype(dtype))
            + p["bias"].astype(dtype))


def _attn_out(p, x, dtype):
    """DenseGeneral(hidden, axis=(-2, -1)): kernel [h, d, H], bias [H]."""
    return (jnp.einsum("bnd,ndh->bh", x.astype(dtype),
                       p["kernel"].astype(dtype))
            + p["bias"].astype(dtype))


def _layernorm(p, x, dtype, eps: float = 1e-6):
    """flax LayerNorm(dtype=cfg.dtype, param_dtype=f32): f32 stats,
    f32 scale/bias, output in the compute dtype."""
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(dtype)


def decode_step(cfg, params, pool_k, pool_v, tables, lengths, tokens):
    """One continuous-batching decode iteration.

    - `tables` [B, max_blocks] int32 — each row's block table (unused
      entries point at the scratch block);
    - `lengths` [B] int32 — tokens already in each row's cache; the
      incoming token is written at position `lengths[b]` (inactive pad
      rows carry length 0 and a scratch table — their writes land in
      the scratch block and their outputs are ignored);
    - `tokens` [B] int32 — each row's current input token.

    Returns ``(logits [B, vocab] f32, pool_k, pool_v)``. Rows are
    independent: a row's logits depend only on its own table/length/
    token, which is what makes batch composition a scheduling choice
    instead of a numerics choice (pinned bitwise by tests).
    """
    _supported(cfg)
    dtype = cfg.dtype
    bsz = tokens.shape[0]
    max_blocks = tables.shape[1]
    bt = pool_k.shape[2]
    d = cfg.hidden_size // cfg.num_heads
    rows = jnp.arange(bsz)
    blk = tables[rows, lengths // bt]       # [B] destination block id
    off = lengths % bt                      # [B] offset inside it
    visible = (jnp.arange(max_blocks * bt)[None, :]
               <= lengths[:, None])         # positions 0..length incl.

    wte = params["wte"]["embedding"].astype(dtype)
    wpe = params["wpe"]["embedding"].astype(dtype)
    x = wte[tokens] + wpe[lengths]          # [B, H]
    for layer in range(cfg.num_layers):
        p = params[f"Block_{layer}"]
        y = _layernorm(p["LayerNorm_0"], x, dtype)
        a = p["CausalSelfAttention_0"]
        q = _qkv(a["query"], y, dtype)      # [B, h, d]
        k = _qkv(a["key"], y, dtype)
        v = _qkv(a["value"], y, dtype)
        pool_k = pool_k.at[layer, blk, off].set(k)
        pool_v = pool_v.at[layer, blk, off].set(v)
        # gather each row's blocks into its contiguous [T, h, d] view
        kk = pool_k[layer][tables].reshape(bsz, max_blocks * bt,
                                           cfg.num_heads, d)
        vv = pool_v[layer][tables].reshape(bsz, max_blocks * bt,
                                           cfg.num_heads, d)
        # f32 scores/softmax — the model's decode-branch numerics
        s = jnp.einsum("bnd,btnd->bnt", q.astype(jnp.float32),
                       kk.astype(jnp.float32)) * (d ** -0.5)
        s = jnp.where(visible[:, None, :], s,
                      jnp.finfo(jnp.float32).min)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bnt,btnd->bnd", w,
                       vv.astype(jnp.float32)).astype(dtype)
        x = x + _attn_out(a["out"], o, dtype)
        y = _layernorm(p["LayerNorm_1"], x, dtype)
        y = _dense(p["Dense_0"], y, dtype)
        y = jax.nn.gelu(y)
        y = _dense(p["Dense_1"], y, dtype)
        x = x + y
    x = _layernorm(params["LayerNorm_0"], x, dtype)
    logits = _dense(params["lm_head"], x, jnp.float32)
    return logits, pool_k, pool_v


def make_decode_fn(cfg):
    """The jitted decode step for one engine: pools donated (the pool
    is updated in place across iterations, never copied). The engine
    always calls it at its full (max_batch, max_blocks) shapes, so
    every iteration of the serving loop is ONE compiled program
    regardless of which slots are live."""

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def fn(params, pool_k, pool_v, tables, lengths, tokens):
        return decode_step(cfg, params, pool_k, pool_v, tables,
                           lengths, tokens)

    return fn


def prefill(model, params, prompt):
    """Batched causal prefill through the MODEL's own prefill path.

    `prompt` [B, T] int32. Returns ``(logits [B, T, vocab] f32, ks,
    vs)`` with ks/vs [L, B, T, h, d] — the filled cache prefix, ready
    for `write_prefill` to scatter into pool blocks. One forward,
    same numerics as `gpt_generate`'s prefill (it IS the same code
    path). Callers that pad the prompt to a length bucket (the
    engine does, to bound compile count) read the logits at the last
    REAL position — causal masking keeps positions < T independent
    of the padding behind them.
    """
    _supported(model.config)
    cfg = model.config
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), prompt[:, :1],
                           decode=True))
    cache = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), abstract["cache"])
    logits, mut = model.apply(
        {"params": params, "cache": cache}, prompt, prefill=True,
        mutable=["cache"])
    t = prompt.shape[1]
    ks = jnp.stack([
        mut["cache"][f"Block_{i}"]["CausalSelfAttention_0"]["k"][:, :t]
        for i in range(cfg.num_layers)])
    vs = jnp.stack([
        mut["cache"][f"Block_{i}"]["CausalSelfAttention_0"]["v"][:, :t]
        for i in range(cfg.num_layers)])
    return logits.astype(jnp.float32), ks, vs


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _scatter_blocks(pool_k, pool_v, ks, vs, blocks):
    """One donated scatter of block-aligned K/V ([L, n*bt, h, d])
    into pool blocks `blocks` [n] — NOT a Python loop of un-jitted
    `.at[].set()` calls, each of which would copy the entire tier's
    KV memory per block on the hot admission path."""
    n = blocks.shape[0]
    bt = pool_k.shape[2]
    shape = (ks.shape[0], n, bt) + ks.shape[2:]
    pool_k = pool_k.at[:, blocks].set(ks.reshape(shape))
    pool_v = pool_v.at[:, blocks].set(vs.reshape(shape))
    return pool_k, pool_v


def write_prefill(pool_k, pool_v, table, ks, vs, block_tokens: int):
    """Scatter one sequence's prefill K/V ([L, T_padded, h, d], padded
    to the block-sized bucket so T_padded == len(table)*block_tokens)
    into its block table (a host-side list of block ids). The padded
    tail lands in owned blocks past the sequence's length — never
    visible (attention masks by length), and it keeps the scatter one
    jitted donated call per admission. Returns the updated pools."""
    t = ks.shape[1]
    if t != len(table) * block_tokens:
        raise ValueError(
            f"prefill K/V length {t} != {len(table)} blocks x "
            f"{block_tokens} tokens — pad the prompt to its bucket")
    blocks = np.asarray(table, np.int32)
    return _scatter_blocks(pool_k, pool_v, ks, vs, blocks)


def max_blocks_for(max_len: int, block_tokens: int) -> int:
    """Block-table width covering `max_len` tokens."""
    return int(np.ceil(max_len / block_tokens))
