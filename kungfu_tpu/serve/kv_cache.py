"""Block-table paged KV cache: fixed-size blocks in a preallocated pool.

The vLLM PagedAttention idea (PAPERS.md), sized for this runtime: the
KV cache is ONE preallocated pool of fixed-size blocks
(`KF_KV_BLOCK_TOKENS` tokens each) shared by every sequence in the
decode batch, so sequences of wildly different lengths batch together
without reserving max_position tokens each — the reservation that
makes dense [B, max_position] caches cap batch size at the longest
request. A sequence owns an ordered list of block ids (its *block
table*); allocation appends a block when the sequence crosses a block
boundary, retirement returns every block to the free list for the
next admission to reuse.

Two halves, split on purpose:

- the **allocator** (this module) is host-side, pure-Python, and
  schedule-only — no tensor reads — so its invariants (every block
  owned by at most one sequence, free+owned == capacity, reuse is
  LIFO) are testable without JAX and auditable by eye;
- the **pool tensors** (`k`/`v`, [layers, blocks, block_tokens,
  heads, head_dim]) live wherever JAX puts them and are only touched
  by `serve.paged`'s gather/scatter decode step.

Block 0 is a reserved SCRATCH block, never allocated: inactive batch
rows point their table at it so the (always-batched) scatter of the
current token's k/v has somewhere harmless to land — no real
sequence ever reads it (visibility is masked by length).

Cross-request isolation does not depend on zeroing freed blocks:
attention masks every position >= the sequence's own length, so a
reused block's stale bytes are never visible. The
`test_no_cross_request_leakage` fixture in tests/test_serve.py pins
exactly that (reused-pool logits bitwise == fresh-pool logits).

`kf_kv_blocks_in_use` (gauge, docs/observability.md) tracks pool
pressure — the admission-control signal `SLOPolicy` and operators
watch.
"""

from __future__ import annotations

from typing import Dict, List

from ..trace import metrics

#: reserved scratch block id (see module docstring)
SCRATCH_BLOCK = 0


class KVPoolExhausted(RuntimeError):
    """No free KV blocks: the admission signal — the scheduler must
    stop admitting (or evict) instead of corrupting a live block."""


class PagedKVPool:
    """Fixed-size-block KV pool + per-sequence block tables.

    `num_blocks` counts usable blocks EXCLUDING the scratch block;
    capacity in tokens is ``num_blocks * block_tokens``. Pool tensors
    are created lazily by `serve.paged.init_pool_tensors` (the
    allocator stays importable without JAX).
    """

    def __init__(self, num_blocks: int, block_tokens: int):
        if num_blocks <= 0 or block_tokens <= 0:
            raise ValueError(
                f"need positive num_blocks/block_tokens, got "
                f"{num_blocks}/{block_tokens}")
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        # LIFO free list (ids 1..num_blocks; 0 is scratch): reuse the
        # most-recently-freed block first, so leakage-after-eviction
        # bugs surface on the very next admission instead of hiding
        # behind a cold tail of never-touched blocks
        self._free: List[int] = list(range(self.num_blocks, 0, -1))
        self._tables: Dict[object, List[int]] = {}
        self._lengths: Dict[object, int] = {}
        self._publish()

    # -- allocator ----------------------------------------------------------

    def _publish(self) -> None:
        metrics.REGISTRY.set("kf_kv_blocks_in_use",
                             self.num_blocks - len(self._free))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - len(self._free)

    def blocks_for(self, tokens: int) -> int:
        """Blocks needed to hold `tokens` positions."""
        return -(-max(tokens, 0) // self.block_tokens)

    def can_admit(self, tokens: int) -> bool:
        return self.blocks_for(tokens) <= len(self._free)

    def admit(self, seq, tokens: int) -> List[int]:
        """Register sequence `seq` at length `tokens`, allocating its
        initial block table. Raises KVPoolExhausted (allocating
        nothing) when the pool cannot hold it."""
        if seq in self._tables:
            raise ValueError(f"sequence {seq!r} already admitted")
        need = self.blocks_for(max(tokens, 1))
        if need > len(self._free):
            raise KVPoolExhausted(
                f"seq {seq!r} needs {need} blocks, {len(self._free)} "
                f"free of {self.num_blocks}")
        self._tables[seq] = [self._free.pop() for _ in range(need)]
        self._lengths[seq] = int(tokens)
        self._publish()
        return list(self._tables[seq])

    def grow(self, seq, new_length: int) -> None:
        """Grow `seq`'s table to cover `new_length` tokens (decode
        appends one token per step; the table grows only at block
        boundaries). Raises KVPoolExhausted with the table unchanged
        when the pool is dry — the caller decides eviction policy."""
        table = self._tables[seq]
        need = self.blocks_for(new_length) - len(table)
        if need > len(self._free):
            raise KVPoolExhausted(
                f"seq {seq!r} needs {need} more block(s), "
                f"{len(self._free)} free")
        for _ in range(max(need, 0)):
            table.append(self._free.pop())
        self._lengths[seq] = int(new_length)
        self._publish()

    def release(self, seq) -> None:
        """Retire `seq`: every owned block returns to the free list."""
        for b in reversed(self._tables.pop(seq)):
            self._free.append(b)
        del self._lengths[seq]
        self._publish()

    def length(self, seq) -> int:
        return self._lengths[seq]

    def table(self, seq) -> List[int]:
        return list(self._tables[seq])

    def sequences(self):
        return list(self._tables)

    def check_invariants(self) -> List[str]:
        """Allocator health: disjoint ownership, conservation, table
        sizes consistent with lengths. Empty list == healthy (the
        serve smoke and tests gate on it)."""
        out: List[str] = []
        owned = [b for t in self._tables.values() for b in t]
        if len(owned) != len(set(owned)):
            out.append("a block is owned by two sequences")
        if SCRATCH_BLOCK in owned or SCRATCH_BLOCK in self._free:
            out.append("scratch block 0 entered circulation")
        if sorted(owned + self._free) != list(
                range(1, self.num_blocks + 1)):
            out.append(
                f"conservation violated: {len(owned)} owned + "
                f"{len(self._free)} free != {self.num_blocks}")
        for seq, t in self._tables.items():
            if len(t) != self.blocks_for(max(self._lengths[seq], 1)):
                out.append(f"seq {seq!r}: table {len(t)} blocks vs "
                           f"length {self._lengths[seq]}")
        return out

    # -- batch views (consumed by serve.paged) ------------------------------

    def batch_tables(self, seqs, max_blocks: int,
                     pad_rows: int = 0):
        """[len(seqs)+pad_rows, max_blocks] int32 block-table matrix;
        unused entries (and every entry of a pad row) point at the
        scratch block. `max_blocks` must cover the longest table."""
        import numpy as np

        rows = len(seqs) + pad_rows
        out = np.full((rows, max_blocks), SCRATCH_BLOCK, np.int32)
        for i, seq in enumerate(seqs):
            t = self._tables[seq]
            if len(t) > max_blocks:
                raise ValueError(
                    f"seq {seq!r} table {len(t)} > max_blocks "
                    f"{max_blocks}")
            out[i, :len(t)] = t
        return out

    def batch_lengths(self, seqs, pad_rows: int = 0):
        """[len(seqs)+pad_rows] int32 lengths; pad rows are 0."""
        import numpy as np

        out = np.zeros(len(seqs) + pad_rows, np.int32)
        for i, seq in enumerate(seqs):
            out[i] = self._lengths[seq]
        return out


def pool_capacity_blocks(max_batch: int, max_len: int,
                         block_tokens: int,
                         headroom_blocks: int = 0) -> int:
    """Blocks needed for `max_batch` concurrent sequences of up to
    `max_len` tokens — the engine's default preallocation sizing
    (callers shrink it to create admission pressure in tests)."""
    per_seq = -(-max_len // block_tokens)
    return max_batch * per_seq + headroom_blocks
