"""Block-table paged KV cache: fixed-size blocks in a preallocated pool.

The vLLM PagedAttention idea (PAPERS.md), sized for this runtime: the
KV cache is ONE preallocated pool of fixed-size blocks
(`KF_KV_BLOCK_TOKENS` tokens each) shared by every sequence in the
decode batch, so sequences of wildly different lengths batch together
without reserving max_position tokens each — the reservation that
makes dense [B, max_position] caches cap batch size at the longest
request. A sequence owns an ordered list of block ids (its *block
table*); allocation appends a block when the sequence crosses a block
boundary, retirement returns every block to the free list for the
next admission to reuse.

Two halves, split on purpose:

- the **allocator** (this module) is host-side, pure-Python, and
  schedule-only — no tensor reads — so its invariants (every block
  owned by at most one sequence, free+owned == capacity, reuse is
  LIFO) are testable without JAX and auditable by eye;
- the **pool tensors** (`k`/`v`, [layers, blocks, block_tokens,
  heads, head_dim]) live wherever JAX puts them and are only touched
  by `serve.paged`'s gather/scatter decode step.

Block 0 is a reserved SCRATCH block, never allocated: inactive batch
rows point their table at it so the (always-batched) scatter of the
current token's k/v has somewhere harmless to land — no real
sequence ever reads it (visibility is masked by length).

Cross-request isolation does not depend on zeroing freed blocks:
attention masks every position >= the sequence's own length, so a
reused block's stale bytes are never visible. The
`test_no_cross_request_leakage` fixture in tests/test_serve.py pins
exactly that (reused-pool logits bitwise == fresh-pool logits).

**Copy-on-write prefix sharing** (vLLM, PAPERS.md): blocks are
refcounted, and a *prefix index* maps the token tuple of every
committed full prompt block to its block id. `admit` walks the index
over the new prompt's block-aligned prefixes and maps every hit into
the new table instead of re-prefilling it (a final *partial* block is
shared too when its first `r` tokens extend the prompt — positions
past the sequence's length are masked, so the donor's extra tokens
are invisible). Committed blocks are immutable: any write that would
land in a shared or committed block — decode's append, or a chunked
prefill resuming at the divergence point — first goes through
`grow`/`cow_for_write`, which swap in a fresh private block and hand
the caller the (src, dst) pool-tensor copies to execute. `release`
decrements; a block leaves circulation (and the index) only at
refcount zero. K/V at position p depends only on tokens[0..p], so
token-prefix equality is exactly K/V-prefix equality and sharing is
bitwise-lossless.

`kf_kv_blocks_in_use` (gauge, docs/observability.md) tracks pool
pressure — the admission-control signal `SLOPolicy` and operators
watch.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..trace import metrics

#: reserved scratch block id (see module docstring)
SCRATCH_BLOCK = 0


class KVPoolExhausted(RuntimeError):
    """No free KV blocks: the admission signal — the scheduler must
    stop admitting (or evict) instead of corrupting a live block."""


class PagedKVPool:
    """Fixed-size-block KV pool + per-sequence block tables.

    `num_blocks` counts usable blocks EXCLUDING the scratch block;
    capacity in tokens is ``num_blocks * block_tokens``. Pool tensors
    are created lazily by `serve.paged.init_pool_tensors` (the
    allocator stays importable without JAX).
    """

    def __init__(self, num_blocks: int, block_tokens: int):
        if num_blocks <= 0 or block_tokens <= 0:
            raise ValueError(
                f"need positive num_blocks/block_tokens, got "
                f"{num_blocks}/{block_tokens}")
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        # LIFO free list (ids 1..num_blocks; 0 is scratch): reuse the
        # most-recently-freed block first, so leakage-after-eviction
        # bugs surface on the very next admission instead of hiding
        # behind a cold tail of never-touched blocks
        self._free: List[int] = list(range(self.num_blocks, 0, -1))
        self._tables: Dict[object, List[int]] = {}
        self._lengths: Dict[object, int] = {}
        #: block id -> number of owning sequences (blocks in circulation)
        self._refs: Dict[int, int] = {}
        #: full-prefix token tuple (block-aligned) -> committed block id
        self._index: Dict[tuple, int] = {}
        #: reverse of _index — committed block id -> its prefix key
        self._block_key: Dict[int, tuple] = {}
        #: seq -> tokens mapped from the index at admit time
        self._shared: Dict[object, int] = {}
        self._publish()

    # -- refcounting --------------------------------------------------------

    def _alloc(self) -> int:
        b = self._free.pop()
        self._refs[b] = 1
        return b

    def _incref(self, b: int) -> None:
        self._refs[b] += 1

    def _decref(self, b: int) -> None:
        n = self._refs[b] - 1
        if n:
            self._refs[b] = n
            return
        del self._refs[b]
        key = self._block_key.pop(b, None)
        if key is not None:
            del self._index[key]  # evict-on-free: no dangling donors
        self._free.append(b)

    def _is_private(self, b: int) -> bool:
        """Writable in place: sole owner AND not published as a prefix
        donor (committed blocks stay immutable even at refcount 1 —
        a later admission may map them at any moment)."""
        return self._refs.get(b, 0) == 1 and b not in self._block_key

    # -- allocator ----------------------------------------------------------

    def _publish(self) -> None:
        metrics.REGISTRY.set("kf_kv_blocks_in_use",
                             self.num_blocks - len(self._free))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - len(self._free)

    def blocks_for(self, tokens: int) -> int:
        """Blocks needed to hold `tokens` positions."""
        return -(-max(tokens, 0) // self.block_tokens)

    def can_admit(self, tokens: int) -> bool:
        return self.blocks_for(tokens) <= len(self._free)

    def match_prefix(self, prompt: Sequence[int]) -> Tuple[List[int], int]:
        """Longest committed prefix of `prompt`: returns (block ids to
        map shared, tokens they cover). Walks the index over
        block-aligned prefixes; when every full block matched and a
        committed block's first `r` tokens extend the remainder, that
        block is shared partially (the donor's tail past the new
        sequence's length is masked, hence invisible). Read-only."""
        prompt = list(prompt)
        t = len(prompt)
        bt = self.block_tokens
        blocks: List[int] = []
        while (len(blocks) + 1) * bt <= t:
            b = self._index.get(tuple(prompt[: (len(blocks) + 1) * bt]))
            if b is None:
                break
            blocks.append(b)
        shared = len(blocks) * bt
        r = t - shared
        if 0 < r < bt and len(blocks) == self.blocks_for(t) - 1:
            for key, b in self._index.items():
                if len(key) == shared + bt and key[:t] == tuple(prompt):
                    blocks.append(b)
                    shared = t
                    break
        return blocks, shared

    def admit(self, seq, tokens: int,
              prompt: Optional[Sequence[int]] = None) -> List[int]:
        """Register sequence `seq` at length `tokens`, allocating its
        initial block table. With `prompt` (the token ids), committed
        prefix blocks are mapped shared instead of freshly allocated —
        `shared_tokens(seq)` reports how many positions need no
        prefill. Raises KVPoolExhausted (allocating nothing) when the
        pool cannot hold the non-shared remainder."""
        if seq in self._tables:
            raise ValueError(f"sequence {seq!r} already admitted")
        shared_blocks: List[int] = []
        shared = 0
        if prompt is not None:
            if len(prompt) != tokens:
                raise ValueError(
                    f"prompt length {len(prompt)} != tokens {tokens}")
            shared_blocks, shared = self.match_prefix(prompt)
        need = self.blocks_for(max(tokens, 1)) - len(shared_blocks)
        if need > len(self._free):
            raise KVPoolExhausted(
                f"seq {seq!r} needs {need} blocks, {len(self._free)} "
                f"free of {self.num_blocks}")
        for b in shared_blocks:
            self._incref(b)
        self._tables[seq] = list(shared_blocks) + [
            self._alloc() for _ in range(need)]
        self._lengths[seq] = int(tokens)
        self._shared[seq] = int(shared)
        self._publish()
        return list(self._tables[seq])

    def shared_tokens(self, seq) -> int:
        """Tokens `seq` mapped from the prefix index at admit time."""
        return self._shared.get(seq, 0)

    def grow(self, seq, new_length: int) -> List[Tuple[int, int]]:
        """Grow `seq`'s table to cover `new_length` tokens (decode
        appends one token per step; the table grows only at block
        boundaries). The block receiving position ``new_length - 1``
        is made privately writable — when it is shared or committed,
        a fresh block is swapped in and the returned (src, dst) list
        tells the caller which pool-tensor copies to execute BEFORE
        the append. Raises KVPoolExhausted with the table unchanged
        when the pool is dry — the caller decides eviction policy."""
        table = self._tables[seq]
        new_length = int(new_length)
        need = self.blocks_for(new_length) - len(table)
        wi = (new_length - 1) // self.block_tokens
        cow = (need <= 0 and wi < len(table)
               and not self._is_private(table[wi]))
        if max(need, 0) + (1 if cow else 0) > len(self._free):
            raise KVPoolExhausted(
                f"seq {seq!r} needs {max(need, 0) + (1 if cow else 0)} "
                f"more block(s), {len(self._free)} free")
        for _ in range(max(need, 0)):
            table.append(self._alloc())
        copies: List[Tuple[int, int]] = []
        if cow:
            src = table[wi]
            dst = self._alloc()
            table[wi] = dst
            self._decref(src)
            copies.append((src, dst))
        self._lengths[seq] = new_length
        self._publish()
        return copies

    def cow_for_write(self, seq, lo: int, hi: int) -> List[Tuple[int, int]]:
        """Make every block covering positions [lo, hi) privately
        writable (chunked prefill resuming at a divergence point
        writes a whole range at once). Returns the (src, dst)
        pool-tensor copies to execute BEFORE the write; raises
        KVPoolExhausted with the tables unchanged when dry."""
        if hi <= lo:
            return []
        table = self._tables[seq]
        bt = self.block_tokens
        idxs = [i for i in range(lo // bt, (hi - 1) // bt + 1)
                if not self._is_private(table[i])]
        if len(idxs) > len(self._free):
            raise KVPoolExhausted(
                f"seq {seq!r} needs {len(idxs)} copy-on-write "
                f"block(s), {len(self._free)} free")
        copies: List[Tuple[int, int]] = []
        for i in idxs:
            src = table[i]
            dst = self._alloc()
            table[i] = dst
            self._decref(src)
            copies.append((src, dst))
        if copies:
            self._publish()
        return copies

    def commit_prefix(self, seq, prompt: Sequence[int]) -> None:
        """Publish `seq`'s fully-prefilled prompt blocks into the
        prefix index so later admissions can share them. Only full
        blocks commit — the partial tail keeps receiving decode
        appends. Idempotent; on a key collision (identical prompt
        prefilled concurrently) the first writer wins."""
        table = self._tables[seq]
        prompt = list(prompt)
        for i in range(len(prompt) // self.block_tokens):
            b = table[i]
            key = tuple(prompt[: (i + 1) * self.block_tokens])
            if key in self._index or b in self._block_key:
                continue
            self._index[key] = b
            self._block_key[b] = key

    def release(self, seq) -> None:
        """Retire `seq`: drop one reference per owned block; blocks
        reaching refcount zero return to the free list (and leave the
        prefix index)."""
        for b in reversed(self._tables.pop(seq)):
            self._decref(b)
        del self._lengths[seq]
        self._shared.pop(seq, None)
        self._publish()

    def length(self, seq) -> int:
        return self._lengths[seq]

    def table(self, seq) -> List[int]:
        return list(self._tables[seq])

    def sequences(self):
        return list(self._tables)

    def check_invariants(self) -> List[str]:
        """Allocator health: refcount conservation (shared blocks
        counted once in blocks_in_use), no freed block with refs,
        prefix-index consistency, table sizes consistent with
        lengths. Empty list == healthy (the serve smoke and tests
        gate on it)."""
        out: List[str] = []
        owned: Dict[int, int] = {}
        for t in self._tables.values():
            for b in t:
                owned[b] = owned.get(b, 0) + 1
        if owned != self._refs:
            for b in sorted(set(owned) | set(self._refs)):
                if owned.get(b, 0) != self._refs.get(b, 0):
                    out.append(
                        f"block {b}: {owned.get(b, 0)} owner(s) vs "
                        f"refcount {self._refs.get(b, 0)}")
        if len(self._free) != len(set(self._free)):
            out.append("free list holds a duplicate (double free)")
        circ = set(self._refs)
        if circ & set(self._free):
            out.append("a freed block still has references")
        if SCRATCH_BLOCK in circ or SCRATCH_BLOCK in self._free:
            out.append("scratch block 0 entered circulation")
        if sorted(list(circ) + self._free) != list(
                range(1, self.num_blocks + 1)):
            out.append(
                f"conservation violated: {len(circ)} in use + "
                f"{len(self._free)} free != {self.num_blocks}")
        for key, b in self._index.items():
            if self._block_key.get(b) != key:
                out.append(f"committed block {b}: reverse key mismatch")
            if b not in circ:
                out.append(f"committed block {b} not in circulation")
            if not key or len(key) % self.block_tokens:
                out.append(f"committed key of {len(key)} tokens is not "
                           f"block-aligned")
        for b in self._block_key:
            if self._index.get(self._block_key[b]) != b:
                out.append(f"block {b} committed but index disagrees")
        for seq, t in self._tables.items():
            if len(t) != self.blocks_for(max(self._lengths[seq], 1)):
                out.append(f"seq {seq!r}: table {len(t)} blocks vs "
                           f"length {self._lengths[seq]}")
        return out

    # -- batch views (consumed by serve.paged) ------------------------------

    def batch_tables(self, seqs, max_blocks: int,
                     pad_rows: int = 0):
        """[len(seqs)+pad_rows, max_blocks] int32 block-table matrix;
        unused entries (and every entry of a pad row) point at the
        scratch block. `max_blocks` must cover the longest table."""
        import numpy as np

        rows = len(seqs) + pad_rows
        out = np.full((rows, max_blocks), SCRATCH_BLOCK, np.int32)
        for i, seq in enumerate(seqs):
            t = self._tables[seq]
            if len(t) > max_blocks:
                raise ValueError(
                    f"seq {seq!r} table {len(t)} > max_blocks "
                    f"{max_blocks}")
            out[i, :len(t)] = t
        return out

    def batch_lengths(self, seqs, pad_rows: int = 0):
        """[len(seqs)+pad_rows] int32 lengths; pad rows are 0."""
        import numpy as np

        out = np.zeros(len(seqs) + pad_rows, np.int32)
        for i, seq in enumerate(seqs):
            out[i] = self._lengths[seq]
        return out


def pool_capacity_blocks(max_batch: int, max_len: int,
                         block_tokens: int,
                         headroom_blocks: int = 0) -> int:
    """Blocks needed for `max_batch` concurrent sequences of up to
    `max_len` tokens — the engine's default preallocation sizing
    (callers shrink it to create admission pressure in tests)."""
    per_seq = -(-max_len // block_tokens)
    return max_batch * per_seq + headroom_blocks
