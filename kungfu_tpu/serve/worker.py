"""The elastic decode worker: `python -m kungfu_tpu.serve.worker`.

Run under kfrun like any trainer. Each worker is a data-parallel
serving replica: it leases requests from the config server's ledger
(`serve.frontend`), runs them through its own `DecodeEngine`
(continuous batching over the paged KV pool), and streams tokens
back — so the tier scales request throughput with worker count and
NO request state lives in any worker longer than one lease.

The elastic story is the training runtime's, unchanged
(docs/serving.md "Elastic serving"):

- **membership** rides `ElasticCallback.after_step` once per decode
  iteration: planned resizes (TEST_SCHEDULE) and policy-driven ones
  (KF_POLICY=slo -> `SLOPolicy` reading /serve/stats) both go through
  the consensus-resize path; survivors keep their engines — their
  in-flight requests decode straight through the epoch switch, which
  is why the benchmark can report p99 *through* a resize instead of
  around one;
- **params** prove the same continuity training proves: a joiner
  (launch version > 0) adopts survivors' weights via the boot-time
  broadcast, survivors answer from their `changed` branch; a COLD
  boot with KF_CKPT_DIR restores the sharded checkpoint tier
  re-sharded to this np (`restore_sharded`) — the serving replica's
  weights come from the training tier's durable rung, not from a
  side channel;
- **failure**: a peer death surfaces as KfError in the membership
  collectives; with KF_RECOVER=1 the worker rides
  `ElasticCallback.recover` and keeps serving. The dead worker's
  leases expire on the ledger and its requests resume elsewhere —
  completion-after-recovery, asserted by the chaos e2e
  (tests/test_serve_elastic.py) and the `spot_serve_kill` scenario.

Markers (parsed by `serve.harness`): KF_SERVE_READY / KF_SERVE_RESTORED
/ KF_SERVE_JOINER / KF_SERVE_RESIZED / KF_SERVE_RECOVERED /
KF_SERVE_EVICTED / KF_SERVE_DONE.
"""

import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

import kungfu_tpu
from kungfu_tpu import trace
from kungfu_tpu.elastic import ElasticCallback
from kungfu_tpu.env import env_choice, env_flag, env_float, env_int
from kungfu_tpu.ffi import KfError
from kungfu_tpu.initializer import broadcast_variables
from kungfu_tpu.serve import frontend
from kungfu_tpu.serve.engine import DecodeEngine, build_lm
from kungfu_tpu.trace import metrics

MAX_BATCH = env_int("KF_SERVE_MAX_BATCH", 8, minimum=1)
BLOCK_TOKENS = env_int("KF_KV_BLOCK_TOKENS", 16, minimum=1)
SLO_P99_MS = env_float("KF_SLO_P99_MS", 0.0, minimum=0.0)
MODEL_SIZE = os.environ.get("KF_SERVE_MODEL", "tiny")
MAX_LEN = env_int("KF_SERVE_MAX_LEN", 64, minimum=2)
#: pool sizing override: 0 = worst-case (max_batch full-length seqs);
#: tests shrink it to drive the preemption path
NUM_BLOCKS = env_int("KF_SERVE_BLOCKS", 0, minimum=0)
#: exit once the ledger reports this many finished requests (0 = run
#: until the iteration cap — the benchmark/harness always sets it)
EXPECT = env_int("KF_SERVE_EXPECT", 0, minimum=0)
MAX_ITERS = env_int("KF_SERVE_MAX_ITERS", 20_000, minimum=1)
#: fast-path knobs (docs/serving.md "The fast path"): decode kernel
#: selection, chunked-prefill size (0 = whole-prompt), CoW prefix
#: sharing across requests
KERNEL = env_choice("KF_SERVE_KERNEL", "auto",
                    ("auto", "kernel", "functional"))
PREFILL_CHUNK = env_int("KF_SERVE_PREFILL_CHUNK", 0, minimum=0)
SHARE_PREFIX = env_flag("KF_SERVE_SHARE_PREFIX", True)
SCHEDULE = os.environ.get("TEST_SCHEDULE", "")
POLICY = os.environ.get("KF_POLICY", "")
RECOVER = os.environ.get("KF_RECOVER", "0") == "1"
RECOVERY_DEADLINE_S = float(
    os.environ.get("KF_RECOVERY_DEADLINE_MS", "30000")) / 1e3
CKPT_DIR = os.environ.get("KF_CKPT_DIR", "")

peer = kungfu_tpu.init()
url = peer.config.config_server
if not url:
    raise SystemExit("serve.worker needs a config server "
                     "(kfrun -w -config-server ...)")
#: stable worker identity for lease fencing: rank changes across
#: epochs, the bound self address does not
WID = str(peer.config.self_id)

model, params, _mesh = build_lm(
    MODEL_SIZE, max_position=MAX_LEN,
    dtype=jnp.float32 if jax.devices()[0].platform == "cpu" else None)

policy = None
if POLICY == "slo":
    from kungfu_tpu.elastic.policy import SLOPolicy

    policy = SLOPolicy(p99_target_ms=SLO_P99_MS,
                       capacity_per_worker=MAX_BATCH)
elif POLICY:
    raise SystemExit(f"unknown KF_POLICY {POLICY!r} for serving "
                     "(known: slo)")
elastic = ElasticCallback(peer, schedule="" if policy else SCHEDULE,
                          policy=policy)

def tier_drained() -> bool:
    """True once the ledger reports every expected request finished.

    The end-of-run escape hatch for membership collectives: near the
    drain, a policy/schedule proposal can still be in flight while
    peers exit on EXPECT — a joiner booting into (or a survivor
    consenting with) an already-exited peer sees KfError. When the
    tier is drained that is a clean shutdown, not a failure."""
    if EXPECT <= 0:
        return False
    try:
        st = frontend.stats(url)
    except (OSError, ValueError, KeyError):
        return False
    return st["done"] + st["failed"] >= EXPECT


if peer.config.version > 0:
    # joiner: adopt the cluster-agreed iteration count FIRST (a
    # replacement replica restarting at step 0 would replay the chaos
    # schedule's already-fired step coordinates — the same
    # lesson PR 5 learned about wire names), then the survivors'
    # weights (they may be restored/trained state, not this process's
    # seed init). Rank-divergent by protocol — the survivor half
    # answers from its `changed` branch.
    try:
        elastic.sync_position()
        params = broadcast_variables(params, peer=peer)
    except KfError:
        if tier_drained():
            # spawned just as the tier finished: nothing to join
            print(f"KF_SERVE_DRAINED rank={peer.rank} (joiner)",
                  flush=True)
            raise SystemExit(0) from None
        raise
    print(f"KF_SERVE_JOINER rank={peer.rank} size={peer.size} "
          f"step={elastic.state.step}", flush=True)
elif CKPT_DIR:
    # cold boot: restore the sharded checkpoint tier re-sharded to
    # THIS np (the whole point of serving off the training tier's
    # durable rung). Entered unconditionally on every version-0 rank;
    # rank 0's pick broadcast agrees on the candidate (or on "none":
    # every rank falls through together).
    from kungfu_tpu.checkpoint_async import (CheckpointError,
                                             restore_sharded)
    try:
        out, step0, _meta, _res = restore_sharded(CKPT_DIR, params,
                                                  peer=peer)
        params = out
        print(f"KF_SERVE_RESTORED rank={peer.rank} step={step0}",
              flush=True)
    except CheckpointError as e:
        print(f"KF_SERVE_RESTORE_NONE rank={peer.rank}: {e}",
              flush=True)

engine = DecodeEngine(model, params, max_batch=MAX_BATCH,
                      block_tokens=BLOCK_TOKENS, max_len=MAX_LEN,
                      num_blocks=NUM_BLOCKS, kernel=KERNEL,
                      prefill_chunk=PREFILL_CHUNK,
                      share_prefix=SHARE_PREFIX)
# compile before READY: a replica that jits on its first lease stalls
# that request for seconds and contends every peer on a shared host
_t0 = time.perf_counter()
engine.warm()
warm_s = time.perf_counter() - _t0
#: ledger position each live sequence appends at next
positions = {}
served = 0
#: wall seconds spent in control-plane round trips (lease/append/
#: stats) — the KF_SERVE_TIMING breakdown the benchmark parses
control_s = 0.0
#: high-water mark of KV blocks in use — the prefix-sharing
#: benchmark cell's collapse observable
peak_blocks = 0


def timed(fn, *args, **kwargs):
    global control_s
    t0 = time.perf_counter()
    try:
        return fn(*args, **kwargs)
    finally:
        control_s += time.perf_counter() - t0


print(f"KF_SERVE_READY rank={peer.rank} size={peer.size} "
      f"max_batch={MAX_BATCH} block_tokens={BLOCK_TOKENS} "
      f"kernel={engine.kernel} chunk={PREFILL_CHUNK} "
      f"share={int(SHARE_PREFIX)}", flush=True)


def release_all(note: str) -> None:
    """Return every live sequence to the ledger (their tokens are
    already recorded; a later lease resumes them elsewhere)."""
    for s in engine.live():
        engine.drain(s)
        try:
            frontend.release(url, int(s), WID)
        except (OSError, ValueError, KeyError) as e:
            # control plane unreachable: the lease expiry reclaims it
            print(f"[kf-serve] release({s}) after {note}: {e}",
                  flush=True)
        positions.pop(s, None)


def survivor_recover() -> None:
    """Adopt the runner's shrunken stage and keep serving; the engine
    (and every in-flight request on THIS worker) survives untouched."""
    out = elastic.recover(params=params,
                          deadline_s=RECOVERY_DEADLINE_S)
    if out is None:
        if not elastic.state.keep:
            release_all("eviction")
            print(f"KF_SERVE_EVICTED rank={peer.rank}", flush=True)
            raise SystemExit(0)
        if tier_drained():
            # no recovery stage will come: the "dead" peer exited
            # cleanly on EXPECT and the runner has nothing to reap
            print(f"KF_SERVE_DRAINED rank={peer.rank} (recovery)",
                  flush=True)
            raise SystemExit(0)
        raise SystemExit(43)
    print(f"KF_SERVE_RECOVERED rank={peer.rank} size={peer.size} "
          f"epoch={peer.version}", flush=True)


for _ in range(MAX_ITERS):
    # rows for THIS iteration's single /serve/append_batch round trip
    # (one POST per iteration instead of one per sequence — the
    # per-sequence append storm was BENCH_r15's inverse np scaling)
    rows = []
    # -- admit: fill free slots from the ledger -----------------------------
    if engine.free_slots() > 0:
        try:
            leased = timed(frontend.lease, url, engine.free_slots(),
                           WID)
        except (OSError, ValueError, KeyError) as e:
            print(f"[kf-serve] lease failed after bounded retries: "
                  f"{e}", flush=True)
            leased = []
        for r in leased:
            rid = int(r["id"])
            if engine.is_live(rid):
                # our OWN expired lease came back (a stalled iteration
                # outlived KF_SERVE_LEASE_MS): we now hold the fresh
                # lease and the sequence is still decoding — keep it,
                # do not double-admit (engine.admit would raise)
                continue
            prompt = [int(t) for t in r["prompt"]] + \
                [int(t) for t in r["tokens"]]
            remaining = int(r["max_new"]) - int(r["pos"])
            if remaining <= 0 or not engine.can_admit(len(prompt)):
                timed(frontend.release, url, rid, WID)
                continue
            tok, done = engine.admit(rid, prompt, remaining)
            if tok is None:
                # deferred (chunked/shared) prefill: step() emits the
                # first token through its `emitted` map at this pos
                positions[rid] = int(r["pos"])
                continue
            positions[rid] = int(r["pos"]) + 1
            # the one append that stays un-batched: it renews this
            # request's lease BEFORE the iteration's decode/compile
            # work (a boot-time compile can outlive the lease, and a
            # first-iteration "stale" would bounce the whole batch
            # back to the queue)
            status = timed(frontend.append, url, rid, int(r["pos"]),
                           [tok], done, WID)
            if status != "ok":
                engine.drain(rid)
                positions.pop(rid, None)
            elif done:
                served += 1
                positions.pop(rid, None)

    # -- one continuous-batching decode iteration ---------------------------
    emitted, preempted = engine.step()
    for s in preempted:
        timed(frontend.release, url, int(s), WID)
        positions.pop(s, None)
    for s, (tok, done) in emitted.items():
        rows.append({"id": int(s), "pos": positions[s],
                     "tokens": [tok], "done": done})
        positions[s] = positions[s] + 1
    for s in engine.prefilling():
        if s not in emitted:
            # heartbeat: an empty in-place append renews the lease of
            # a sequence that spends several iterations in chunked
            # prefill without emitting anything
            rows.append({"id": int(s), "pos": positions[s],
                         "tokens": [], "done": False})
    stats = None
    if rows:
        statuses, stats = timed(frontend.append_batch, url, rows, WID)
        for row, status in zip(rows, statuses):
            rid = row["id"]
            if status != "ok":
                # "stale": our lease was reclaimed; "done": a resumed
                # lease finished the request elsewhere while we
                # stalled (e.g. through a recovery window) — keeping
                # the dead sequence would burn a batch slot for up to
                # max_new more iterations
                engine.drain(rid)
                positions.pop(rid, None)
            elif row["done"]:
                served += 1
                positions.pop(rid, None)
    metrics.REGISTRY.set("kf_serve_active", engine.active)
    peak_blocks = max(peak_blocks, engine.pool.blocks_in_use)

    # -- elastic membership (the training runtime's path, unchanged) --------
    try:
        if policy is not None:
            if stats is None:
                stats = timed(frontend.stats, url)
            policy.observe(stats["queue_depth"], stats["running"],
                           stats["p99_ms"])
        with trace.span("step.hook", cat="serve"):
            changed = elastic.after_step()
    except KfError:
        if not RECOVER:
            if tier_drained():
                break  # a peer exited on EXPECT mid-consensus
            raise
        survivor_recover()
        continue
    if changed:
        if not elastic.state.keep:
            release_all("eviction")
            print(f"KF_SERVE_EVICTED rank={peer.rank}", flush=True)
            raise SystemExit(0)
        # survivor half of the joiner's boot-time resync (position,
        # then weights); the engine's KV pool is per-process state
        # and rides through
        try:
            elastic.sync_position()
            params = broadcast_variables(params, peer=peer)
        except KfError:
            if not RECOVER:
                if tier_drained():
                    break  # resync raced the drain; work is done
                raise
            survivor_recover()
            continue
        print(f"KF_SERVE_RESIZED rank={peer.rank} size={peer.size} "
              f"epoch={peer.version} step={elastic.state.step}",
              flush=True)

    # -- drain / idle -------------------------------------------------------
    if EXPECT > 0:
        try:
            stats = stats or timed(frontend.stats, url)
        except (OSError, ValueError, KeyError):
            stats = None
        if stats and stats["done"] + stats["failed"] >= EXPECT:
            break
    if engine.active == 0:
        time.sleep(0.01)

release_all("shutdown")  # no-op on a drained ledger (EXPECT reached);
#                          an iteration-cap exit returns its leases
print(f"KF_SERVE_TIMING rank={peer.rank} steps={engine.steps} "
      f"decode_ms={engine.decode_s * 1e3:.1f} "
      f"prefill_ms={engine.prefill_s * 1e3:.1f} "
      f"prefill_chunks={engine.prefill_chunks} "
      f"control_ms={control_s * 1e3:.1f} "
      f"warm_ms={warm_s * 1e3:.1f} "
      f"peak_blocks={peak_blocks}", flush=True)
print(f"KF_SERVE_DONE rank={peer.rank} size={peer.size} "
      f"served={served} iters={elastic.state.step}", flush=True)
