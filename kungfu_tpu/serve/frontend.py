"""HTTP front-end for the decode tier: /serve/* on the config server.

Ingest rides the control plane the cluster already runs
(`elastic.config_server.ConfigServer` mounts these routes next to
/get /put /trace): the config server is the one address that
survives worker churn, so the request ledger living behind it is
what makes serving elastic at all — resizes and worker deaths move
the COMPUTE, never the requests.

Routes (all JSON):

- ``POST /serve/submit``  {"prompt": [ids], "max_new_tokens": n}
  -> {"id": k} | 429 when the bounded admission queue is full
  (transient in the retrying.py taxonomy: clients back off and
  retry) | 400 on malformed input (permanent: never retried).
- ``POST /serve/submit_batch`` {"rows": [{"prompt", "max_new_tokens"},
  ...]} -> {"results": [{"id": k} | {"error", "code"}, ...]} — the
  admission router's coalescing verb: one ledger write (and one
  replication op) admits a whole flush window; rejection is per-row.
- ``GET  /serve/result?id=k`` -> request record (state/tokens/
  latency) | 404.
- ``GET  /serve/stats`` -> ledger stats (queue depth, in-flight,
  p50/p99 completed latency) — the `SLOPolicy` signal and the
  benchmark's measurement plane.
- ``GET  /serve/invariants`` -> {"violations": [...]} — the request-
  plane health gate (empty == healthy).
- worker verbs: ``POST /serve/lease`` {"max": n, "worker": w},
  ``POST /serve/append`` {"id", "pos", "tokens", "done", "worker"},
  ``POST /serve/append_batch`` {"rows": [{"id", "pos", "tokens",
  "done"}, ...], "worker": w} -> {"statuses": [...], "stats": {...}}
  — ONE round trip per decode iteration, ledger stats piggybacked so
  the worker skips its separate /serve/stats poll (the per-sequence
  append storm behind BENCH_r15's inverse np scaling),
  ``POST /serve/release`` {"id", "worker"}.

Like ``/trace``, the ``/serve`` plane is EXEMPT from the chaos HTTP
hooks: fault schedules must perturb the membership control plane at
deterministic request indices, and serve traffic volume is workload-
dependent — killing a decode worker is a *worker-side* fault
(``crash_worker``), which is exactly what the ``spot_serve_kill``
scenario schedules.

The client half (`submit`/`result`/`lease`/`append`/`release`/
`stats`) rides `peer.post_url`/`peer.fetch_url`, i.e. the shared
control-plane retry policy.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..peer import fetch_url, post_url
from .ledger import AdmissionFull, RequestLedger

__all__ = [
    "handle_serve", "serve_url", "submit", "submit_batch", "result",
    "results", "stats", "invariants", "lease", "append",
    "append_batch", "release", "RequestLedger",
]


def handle_serve(ledger: RequestLedger, method: str, path: str,
                 body: str) -> Optional[Tuple[int, str]]:
    """Dispatch one /serve/* request against `ledger`; returns
    ``(status, json_body)`` or None when `path` is not a serve route
    (the config server falls through to its own routes)."""
    parsed = urlparse(path)
    route = parsed.path
    if not route.startswith("/serve"):
        return None
    try:
        doc = json.loads(body) if body else {}
        if not isinstance(doc, dict):
            raise ValueError("body must be a JSON object")
        if method == "POST" and route == "/serve/submit":
            rid = ledger.submit(list(doc.get("prompt", [])),
                                int(doc.get("max_new_tokens", 0)))
            return 200, json.dumps({"id": rid})
        if method == "POST" and route == "/serve/submit_batch":
            results_ = ledger.submit_batch(list(doc.get("rows", [])))
            return 200, json.dumps({"results": results_})
        if method == "POST" and route == "/serve/lease":
            out = ledger.lease(int(doc.get("max", 1)),
                               str(doc.get("worker", "")))
            return 200, json.dumps({"requests": out})
        if method == "POST" and route == "/serve/append":
            status = ledger.append_tokens(
                int(doc["id"]), int(doc["pos"]),
                [int(t) for t in doc.get("tokens", [])],
                done=bool(doc.get("done", False)),
                worker=str(doc.get("worker", "")))
            return 200, json.dumps({"status": status})
        if method == "POST" and route == "/serve/append_batch":
            statuses = ledger.append_batch(
                list(doc.get("rows", [])),
                worker=str(doc.get("worker", "")))
            return 200, json.dumps({"statuses": statuses,
                                    "stats": ledger.stats()})
        if method == "POST" and route == "/serve/release":
            ledger.release(int(doc["id"]),
                           worker=str(doc.get("worker", "")))
            return 200, "{}"
        if method == "GET" and route == "/serve/result":
            rid = int(parse_qs(parsed.query).get("id", ["0"])[0])
            return 200, json.dumps(ledger.result(rid))
        if method == "GET" and route == "/serve/stats":
            return 200, json.dumps(ledger.stats())
        if method == "GET" and route == "/serve/results":
            return 200, json.dumps({"results": ledger.results()})
        if method == "GET" and route == "/serve/invariants":
            return 200, json.dumps(
                {"violations": ledger.check_invariants()})
    except AdmissionFull as e:
        return 429, json.dumps({"error": str(e)})
    except KeyError as e:
        return 404, json.dumps({"error": str(e)})
    except (ValueError, TypeError) as e:
        return 400, json.dumps({"error": str(e)})
    return 404, json.dumps({"error": f"unknown serve route {route}"})


# -- client half --------------------------------------------------------------


def serve_url(url: str, route: str = "") -> str:
    """Map a config-server URL (usually its .../get form) onto the
    /serve endpoint family — the trace_url idiom."""
    base = url[:-len("/get")] if url.endswith("/get") else url.rstrip("/")
    return base + "/serve" + route


def submit(url: str, prompt: List[int], max_new_tokens: int,
           retry=None) -> int:
    out = post_url(serve_url(url, "/submit"),
                   json.dumps({"prompt": prompt,
                               "max_new_tokens": max_new_tokens}),
                   retry=retry)
    return int(json.loads(out)["id"])


def submit_batch(url: str, rows: List[Dict], retry=None) -> List[Dict]:
    """Coalesced admission (the router's ledger-side verb): one POST
    admits many prompts; per-row outcome dicts ({"id": k} or
    {"error": ..., "code": 429|400}) come back in row order, so one
    full queue rejects only the rows that didn't fit, not the whole
    batch."""
    out = post_url(serve_url(url, "/submit_batch"),
                   json.dumps({"rows": rows}), retry=retry)
    return list(json.loads(out)["results"])


def result(url: str, rid: int, retry=None) -> Dict:
    return json.loads(fetch_url(serve_url(url, f"/result?id={rid}"),
                                retry=retry))


def stats(url: str, retry=None) -> Dict:
    return json.loads(fetch_url(serve_url(url, "/stats"), retry=retry))


def invariants(url: str, retry=None) -> List[str]:
    return json.loads(fetch_url(serve_url(url, "/invariants"),
                                retry=retry))["violations"]


def results(url: str, retry=None) -> List[Dict]:
    return json.loads(fetch_url(serve_url(url, "/results"),
                                retry=retry))["results"]


def lease(url: str, n: int, worker: str, retry=None) -> List[Dict]:
    out = post_url(serve_url(url, "/lease"),
                   json.dumps({"max": n, "worker": worker}),
                   retry=retry)
    return json.loads(out)["requests"]


def append(url: str, rid: int, pos: int, tokens: List[int],
           done: bool, worker: str, retry=None) -> str:
    out = post_url(serve_url(url, "/append"),
                   json.dumps({"id": rid, "pos": pos,
                               "tokens": tokens, "done": done,
                               "worker": worker}),
                   retry=retry)
    return json.loads(out)["status"]


def append_batch(url: str, rows: List[Dict], worker: str,
                 retry=None) -> Tuple[List[str], Dict]:
    """One POST per decode iteration: per-row append statuses plus
    the piggybacked ledger stats (saves the separate /serve/stats
    poll). Rows are overlap-idempotent on the ledger, so the shared
    retry policy is safe here like everywhere else."""
    out = post_url(serve_url(url, "/append_batch"),
                   json.dumps({"rows": rows, "worker": worker}),
                   retry=retry)
    doc = json.loads(out)
    return list(doc["statuses"]), dict(doc["stats"])


def release(url: str, rid: int, worker: str, retry=None) -> None:
    post_url(serve_url(url, "/release"),
             json.dumps({"id": rid, "worker": worker}), retry=retry)
