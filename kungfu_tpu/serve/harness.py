"""Drive a real elastic serving cluster end to end.

One shared entry point for everything that wants the full decode tier
exercised for real — config server with the /serve ledger, kfrun
watcher, `serve.worker` replicas, live requests — with the
request-plane invariant gate applied at the end:
tests/test_serve_elastic.py, `benchmarks/serve.py`, the run-all.sh
serving smoke (stage 4h) and the `spot_serve_kill` scenario replay
all call `run_serve_cluster`.

The harness submits every request BEFORE launching the workers (the
ledger lives on the config server, which boots first), sizes the
token budget so traffic is still in flight when the schedule's
mid-run resize (or the chaos schedule's worker kill) lands, and
asserts afterwards that EVERY submitted request completed and
`RequestLedger.check_invariants()` is empty — the serving analog of
the goodput plane's phases-sum-to-wall gate.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..elastic.harness import ensure_libkf

SERVE_MARKERS = (
    ("KF_SERVE_READY", "no decode worker came up"),
    ("KF_SERVE_DONE", "no worker drained the request ledger"),
)

RESIZE_MARKERS = SERVE_MARKERS + (
    ("KF_SERVE_JOINER", "the joining replica never adopted weights"),
    ("KF_SERVE_RESIZED", "no survivor rode the epoch switch"),
)

RECOVERY_MARKERS = SERVE_MARKERS + (
    ("KF_CHAOS_FIRE", "the scheduled worker kill never fired"),
    ("KF_SERVE_RECOVERED", "no survivor recovered the decode tier"),
)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def default_requests(n: int, gen_len: int = 12,
                     vocab: int = 50257, seed: int = 17
                     ) -> List[Tuple[List[int], int]]:
    """Deterministic request mix: varied prompt lengths (so the paged
    batch is genuinely ragged), seeded token values."""
    import numpy as np

    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        plen = 2 + int(rng.integers(0, 9))
        prompt = rng.integers(0, vocab, size=plen)
        out.append(([int(t) for t in prompt], gen_len))
    return out


def prefix_requests(n: int, prefix_len: int = 48, gen_len: int = 12,
                    vocab: int = 50257, seed: int = 23
                    ) -> List[Tuple[List[int], int]]:
    """Prefix-heavy request mix (system prompt + short user tails):
    every request shares one `prefix_len`-token common prefix and
    diverges only in a 2-4 token tail — the workload CoW prefix
    sharing collapses (`KF_SERVE_SHARE_PREFIX`, docs/serving.md)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    common = [int(t) for t in rng.integers(0, vocab, size=prefix_len)]
    out = []
    for _ in range(n):
        tail = rng.integers(0, vocab, size=2 + int(rng.integers(0, 3)))
        out.append((common + [int(t) for t in tail], gen_len))
    return out


def run_serve_cluster(
        requests: Sequence[Tuple[List[int], int]],
        schedule: str = "",
        start_np: int = 2,
        slots: int = 4,
        port_range: str = "27100-27999",
        timeout: int = 420,
        logdir: Optional[str] = None,
        markers=SERVE_MARKERS,
        extra_env: Optional[Dict[str, str]] = None,
        recover: bool = False,
        policy: str = "",
        warmup: int = 0,
        grow_when_done: Optional[int] = None,
        server=None) -> Dict:
    """Boot config server + kfrun -w + serve workers, submit
    `requests` ([(prompt, max_new), ...]), wait for the tier to drain
    the ledger, and gate on completion + ledger invariants.

    `warmup` > 0 front-loads that many tiny throwaway requests and
    defers the MEASURED batch until they complete — so the reported
    per-request latencies are warm-tier numbers (worker boot + jit
    compile excluded), the way an operator would measure a running
    service. `grow_when_done` (an absolute completed-request count,
    warmup included) POSTs the config server's /addworker once that
    many requests finished — the operator-driven mid-traffic grow the
    resize benchmark cell measures p99 *through*.

    Returns {"logs", "results", "stats", "wall_s", "measured_wall_s"}
    — `results` covers the measured batch in submission order, each
    with per-request latency_ms. Raises AssertionError (with logs) on
    worker failure, missing markers, an incomplete request, or any
    ledger-invariant violation."""
    import threading

    ensure_libkf()
    from ..elastic.config_server import ConfigServer

    own_server = server is None
    if own_server:
        from ..env import env_int

        server = ConfigServer(
            port=env_int("KF_SERVE_PORT", 0, minimum=0)).start()
    own_logdir = logdir is None
    tmp = tempfile.TemporaryDirectory() if own_logdir else None
    logdir = tmp.name if own_logdir else logdir
    try:
        ledger = server.serve_ledger
        # the ledger lives in THIS process (the config server's), so
        # ledger knobs riding `extra_env` / a scenario's env block
        # must be applied here — merging them only into the worker
        # subprocess env would make them silent no-ops
        if extra_env:
            from ..env import env_float, env_int

            ledger.lease_ms = env_float("KF_SERVE_LEASE_MS",
                                        ledger.lease_ms, extra_env,
                                        minimum=100.0)
            ledger.max_queue = env_int("KF_SERVE_QUEUE",
                                       ledger.max_queue, extra_env,
                                       minimum=1)
        warmup_ids = [ledger.submit([3, 5, 7], 2)
                      for _ in range(warmup)]
        ids: List[int] = []
        measured_t: Dict[str, float] = {}
        stop = threading.Event()

        def _feeder():
            """Submit the measured batch once warmup drains, fire the
            mid-traffic grow at the progress threshold, and stamp the
            drain instant (so throughput excludes teardown). Errors
            land in measured_t["error"] and re-raise on the MAIN
            thread after the run — a daemon-thread traceback on
            stderr must not decay into a misleading
            'threshold never reached' assertion."""
            submitted = warmup == 0
            grown = grow_when_done is None
            total = warmup + len(requests)
            if submitted:
                ids.extend(ledger.submit(p, m) for p, m in requests)
                measured_t["start"] = time.perf_counter()
            while not stop.is_set():
                st = ledger.stats()
                if not submitted and st["done"] >= warmup:
                    ids.extend(ledger.submit(p, m)
                               for p, m in requests)
                    measured_t["start"] = time.perf_counter()
                    submitted = True
                if submitted and not grown \
                        and st["done"] >= grow_when_done:
                    err = server._resize(+1)
                    if err:
                        raise AssertionError(
                            f"mid-traffic grow failed: {err}")
                    measured_t["grow"] = time.perf_counter()
                    grown = True
                if submitted and grown and st["done"] >= total:
                    measured_t["end"] = time.perf_counter()
                    return
                stop.wait(0.05)

        def _feeder_guarded():
            try:
                _feeder()
            # capture-and-re-raise-on-main-thread, not a swallow: the
            # join below raises measured_t["error"] verbatim
            # kflint: disable=retry-discipline — stashed for the main thread
            except BaseException as e:  # noqa: BLE001
                measured_t["error"] = e

        feeder = threading.Thread(target=_feeder_guarded, daemon=True)
        t0 = time.perf_counter()
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get(
            "PYTHONPATH", "")
        env["KF_TIMEOUT_MS"] = env.get("KF_TIMEOUT_MS", "120000")
        env["KF_LOG_LEVEL"] = "warn"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["JAX_PLATFORMS"] = "cpu"
        env["TEST_SCHEDULE"] = schedule
        env["KF_SERVE_EXPECT"] = str(warmup + len(requests))
        env["KF_POLICY"] = policy
        if recover:
            env["KF_RECOVER"] = "1"
            env.setdefault("KF_RECOVERY_DEADLINE_MS", "30000")
        if extra_env:
            env.update(extra_env)
        cmd = [sys.executable, "-m", "kungfu_tpu.run",
               "-np", str(start_np),
               "-H", f"127.0.0.1:{slots}",
               "-port-range", port_range,
               "-w", "-config-server", server.get_url,
               "-logdir", logdir, "-q"]
        if recover:
            cmd.append("-recover")
        cmd += ["--", sys.executable, "-m", "kungfu_tpu.serve.worker"]
        feeder.start()
        try:
            out = subprocess.run(cmd, cwd=_REPO, env=env,
                                 capture_output=True, text=True,
                                 timeout=timeout)
        finally:
            stop.set()
            feeder.join(timeout=10.0)
            if "error" in measured_t:
                raise measured_t["error"]
            # the feeder can be stopped between the last completion
            # and its next poll: stamp the drain instant at join so
            # measured_wall never silently falls back to boot+teardown
            if "start" in measured_t:
                measured_t.setdefault("end", time.perf_counter())
        wall = time.perf_counter() - t0
        logs = ""
        for f in sorted(os.listdir(logdir)):
            if f.endswith(".log"):
                with open(os.path.join(logdir, f)) as fh:
                    logs += f"--- {f} ---\n" + fh.read()
        logs += f"--- runner ---\n{out.stdout}"
        if out.returncode != 0:
            raise AssertionError(
                f"serve cluster failed rc={out.returncode}:\n"
                f"stdout: {out.stdout[-2000:]}\n"
                f"stderr: {out.stderr[-2000:]}\n{logs[-3000:]}")
        for marker, why in markers:
            if marker not in logs:
                raise AssertionError(
                    f"serve cluster: {why} ({marker} missing):\n"
                    f"{logs[-3000:]}")
        if len(ids) != len(requests):
            raise AssertionError(
                f"feeder submitted {len(ids)}/{len(requests)} "
                f"measured requests (warmup never drained?):\n"
                f"{logs[-3000:]}")
        results = [ledger.result(rid) for rid in warmup_ids + ids]
        for r in results:
            if r["state"] != "done":
                raise AssertionError(
                    f"request {r['id']} ended {r['state']!r} "
                    f"(tokens {len(r['tokens'])}/{r['max_new']}):\n"
                    f"{logs[-3000:]}")
        violations = ledger.check_invariants()
        if violations:
            raise AssertionError(
                f"request-ledger invariants violated: {violations}\n"
                f"{logs[-3000:]}")
        if grow_when_done is not None and "grow" not in measured_t:
            raise AssertionError(
                "the mid-traffic grow threshold was never reached "
                f"(grow_when_done={grow_when_done}):\n{logs[-3000:]}")
        measured_wall = (
            measured_t["end"] - measured_t["start"]
            if "end" in measured_t and "start" in measured_t
            else wall)
        return {"logs": logs, "results": results[len(warmup_ids):],
                "stats": ledger.stats(), "wall_s": round(wall, 3),
                "measured_wall_s": round(measured_wall, 3)}
    finally:
        if tmp is not None:
            tmp.cleanup()
        if own_server:
            server.stop()


def seed_checkpoint(ckpt_dir: str, size: str = "tiny",
                    max_len: int = 64) -> None:
    """Write one sharded checkpoint generation of the serve model's
    params (np=1), so a cluster cold-boots its replicas from the
    durable tier re-sharded to ITS np — the serving side of
    reshard-on-restore."""
    import jax.numpy as jnp

    from ..checkpoint_async import save_sharded
    from .engine import build_lm

    _model, params, _ = build_lm(size, max_position=max_len,
                                 dtype=jnp.float32)
    os.makedirs(ckpt_dir, exist_ok=True)
    save_sharded(ckpt_dir, params, step=1, rank=0, nprocs=1)
