"""kfserve: the elastic continuous-batching decode tier.

The "millions of users" half of the north star (ROADMAP item 1):
decode stops being a benchmark row and becomes a serving subsystem —
a request front-end riding the config-server control plane
(`serve.ledger` + the `/serve/*` routes), an iteration-level
continuous-batching scheduler over a block-table paged KV cache
(`serve.engine` / `serve.kv_cache` / `serve.paged` — Orca's
iteration-level admission + vLLM's PagedAttention, PAPERS.md), and —
the piece neither has — *elastic* serving: decode workers ride the
SAME versioned-epoch membership machinery training uses (consensus
resize, survivor recovery, cold boot from the sharded checkpoint
tier), sized by a queue-depth/latency policy
(`elastic.policy.SLOPolicy`). docs/serving.md is the architecture
document.
"""

from .engine import SIZES, DecodeEngine, build_lm
from .kv_cache import KVPoolExhausted, PagedKVPool
from .ledger import AdmissionFull, Request, RequestLedger

__all__ = [
    "AdmissionFull",
    "DecodeEngine",
    "KVPoolExhausted",
    "PagedKVPool",
    "Request",
    "RequestLedger",
    "SIZES",
    "build_lm",
]
