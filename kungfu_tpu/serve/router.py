"""Stateless admission router: the serve tier's front door.

ROADMAP item 3(b): admission throughput must stop being one process's
accept loop. A `Router` terminates client traffic — ``POST
/serve/submit`` and the read verbs — in its own process (or thread),
and is STATELESS: every durable fact lives in the replicated ledger
behind it, so routers scale horizontally and die without losing
anything. Run as many as the ingress needs; clients list them in
``KF_SERVE_ROUTERS`` and peer.py fails over across them exactly like
config replicas (a router death mid-submit surfaces as a connection
failure, the client's next candidate is another router, the resubmit
is admitted there — zero dropped requests).

What a router actually does (docs/serving.md "Front door"):

- **Coalesced admission.** Incoming submits queue for up to
  ``KF_ROUTER_FLUSH_MS`` (or ``_MAX_FLUSH``), then ONE
  ``/serve/submit_batch`` ledger write — and therefore one replication
  op on the tier — admits the whole window. The client's 200 carries
  the ledger-assigned id and is only sent after the batched write
  returned, so admission durability is exactly what the ledger's
  replicate-before-ack gives: a router crash can only lose requests
  that were never acked.
- **Sharded reads.** ``GET /serve/result?id=k`` is served from the
  replica at ``k % n_servers`` (stale-marked follower reads are fine:
  a DONE result is immutable), spreading the result-poll load across
  the tier instead of hammering the leader.
- **No worker verbs, no membership.** Workers keep talking to the
  tier directly (lease/append_batch are already one call per decode
  iteration); /put and friends are the operator's plane. Unknown
  routes 404 here.

Chaos: every incoming request consults ``chaos.on_router_request``
with the router's OWN request counter — ``kill_router`` is the
first-class front-door fault (permanent, like kill_config_replica).

Run standalone:
``python -m kungfu_tpu.serve.router --port 9400 --index 0 \
  --servers http://h:9100,http://h:9101,http://h:9102``
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
import urllib.error
from typing import Dict, List, Optional

from .. import chaos
from ..env import env_float
from ..retrying import NO_RETRY

#: flush-window batch cap — one window's worth of submits becomes one
#: ledger write even under a burst
_MAX_FLUSH = 64


class Router:
    """One stateless admission router in front of a config tier.

    ``servers`` is the index-aligned list of config-server base URLs
    (a tier, or a single server). Construct + ``start()``; ``stop()``
    or a ``kill_router`` chaos fault tears it down."""

    def __init__(self, servers: List[str], host: str = "127.0.0.1",
                 port: int = 0, index: int = 0,
                 flush_ms: Optional[float] = None,
                 standalone: bool = False):
        if not servers:
            raise ValueError("router needs at least one config server")
        self.servers = [s.rstrip("/") for s in servers]
        self.host = host
        self.port = port
        self.index = int(index)
        self.standalone = standalone
        self.flush_ms = float(flush_ms) if flush_ms is not None else \
            env_float("KF_ROUTER_FLUSH_MS", 2.0, minimum=0.0)
        self.dead = False  # kf: guarded_by(_cv)
        self._cv = threading.Condition()
        # submit entries awaiting the coalesced flush
        self._pending: List[Dict] = []  # kf: guarded_by(_cv)
        self._reqs = 0  # kf: guarded_by(_cv) — chaos request counter
        self._upstream = 0  # kf: guarded_by(_cv) — last good server
        self.flushed_batches = 0  # kf: guarded_by(_cv)
        self.submitted = 0        # kf: guarded_by(_cv)
        self._stop_flusher = threading.Event()
        self._lock = threading.Lock()
        # kf: guarded_by(_lock)
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self._flusher: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    @property
    def base(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "Router":
        from ..elastic.config_server import _KeepAliveHTTPServer

        httpd = _KeepAliveHTTPServer((self.host, self.port),
                                     self._handler())
        with self._lock:
            self._httpd = httpd
        self.port = httpd.server_port
        self._thread = threading.Thread(target=httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self._flusher = threading.Thread(
            target=self._flush_loop, name=f"kf-router-{self.index}",
            daemon=True)
        self._flusher.start()
        return self

    def stop(self) -> None:
        self._stop_flusher.set()
        with self._cv:
            self.dead = True
            self._cv.notify_all()
        with self._lock:
            httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.kf_close_connections()
        httpd.server_close()

    def _chaos_kill(self) -> None:
        """kill_router fired: permanent, mid-traffic. Standalone exits
        abruptly; in-process tears the listener down and never
        restarts. Pending (un-acked) submits die with the connection —
        their clients fail over to another router and resubmit."""
        if self.standalone:
            os._exit(29)
        threading.Thread(target=self.stop, daemon=True).start()

    # -- upstream calls -----------------------------------------------------

    def _order(self, start: int) -> List[str]:
        n = len(self.servers)
        return [self.servers[(start + k) % n] for k in range(n)]

    def _call(self, fn, order: List[str], deadline_s: float = 20.0):
        """Lap the tier until one server answers; conn failures and
        election 503s rotate/wait, real errors raise through (the
        handler forwards their status to the client)."""
        last: Optional[BaseException] = None
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            for base in order:
                if self._stop_flusher.is_set():
                    raise TimeoutError("router stopping")
                try:
                    out = fn(base + "/get")
                    with self._cv:
                        self._upstream = self.servers.index(base)
                    return out
                except urllib.error.HTTPError as e:
                    if e.code not in (503, 429):
                        raise
                    last = e  # election / backpressure: next lap
                except (OSError, ValueError) as e:
                    last = e  # dead replica: try a sibling
            time.sleep(0.05)
        raise TimeoutError(
            f"no config server answered within {deadline_s}s: {last}")

    # -- coalesced admission ------------------------------------------------

    def _flush_loop(self) -> None:
        from . import frontend

        while True:
            with self._cv:
                while not self._pending and \
                        not self._stop_flusher.is_set():
                    self._cv.wait(0.25)
                if not self._stop_flusher.is_set() and self.flush_ms > 0:
                    deadline = time.monotonic() + self.flush_ms / 1e3
                    while len(self._pending) < _MAX_FLUSH:
                        rem = deadline - time.monotonic()
                        if rem <= 0:
                            break
                        self._cv.wait(rem)
                batch, self._pending = self._pending, []
                upstream = self._upstream
            if self._stop_flusher.is_set():
                self._fail(batch)
                with self._cv:
                    batch, self._pending = self._pending, []
                self._fail(batch)
                return
            if not batch:
                continue
            try:
                results = self._call(
                    lambda url: frontend.submit_batch(
                        url, [e["row"] for e in batch],
                        retry=NO_RETRY),
                    self._order(upstream))
            # any upstream failure shape fails the batch; each waiting
            # client gets a 503 and ITS retry policy resubmits
            # (possibly through another router) — the router must not
            # guess which shapes heal on the clients' behalf
            # kflint: disable=retry-discipline
            except Exception as e:  # noqa: BLE001
                print(f"[kf-router] r{self.index}: flush failed: {e}",
                      flush=True)
                self._fail(batch)
                continue
            with self._cv:
                self.flushed_batches += 1
                self.submitted += sum(
                    1 for r in results if "id" in r)
            for entry, res in zip(batch, results):
                entry["out"] = res
                entry["ev"].set()

    @staticmethod
    def _fail(batch: List[Dict]) -> None:
        for entry in batch:
            entry["ev"].set()  # entry["out"] stays None => 503

    def _enqueue_submit(self, row: Dict) -> Dict:
        entry = {"row": row, "ev": threading.Event(), "out": None}
        with self._cv:
            self._pending.append(entry)
            self._cv.notify()
        entry["ev"].wait(30.0)
        return entry

    # -- http ---------------------------------------------------------------

    def _handler(self):
        router = self
        from http.server import BaseHTTPRequestHandler

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            timeout = 30.0
            disable_nagle_algorithm = True  # see config_server.py

            def log_message(self, *args):  # quiet
                pass

            def setup(self):
                super().setup()
                track = getattr(self.server, "kf_track", None)
                if track is not None:
                    track(self.connection)

            def finish(self):
                try:
                    super().finish()
                finally:
                    untrack = getattr(self.server, "kf_untrack", None)
                    if untrack is not None:
                        untrack(self.connection)

            def _reply(self, code: int, body: str = ""):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _chaos(self) -> bool:
                with router._cv:
                    router._reqs += 1
                    idx = router._reqs
                action = chaos.on_router_request(
                    self.path, router=router.index, request_idx=idx)
                if action and action.get("kill"):
                    router._chaos_kill()
                    try:
                        self.connection.close()
                    except OSError:
                        pass
                    self.close_connection = True
                    return True
                return False

            def _forward_read(self, fn, order) -> None:
                try:
                    doc = router._call(fn, order)
                except urllib.error.HTTPError as e:
                    try:
                        body = e.read().decode()
                    except (OSError, ValueError):
                        body = json.dumps({"error": str(e)})
                    self._reply(e.code, body or
                                json.dumps({"error": str(e)}))
                    return
                except (TimeoutError, OSError) as e:
                    self._reply(503, json.dumps(
                        {"error": f"no upstream: {e}"}))
                    return
                self._reply(200, json.dumps(doc))

            def _crash_guard(self, fn):
                """Exception firewall — see config_server.Handler:
                keep-alive means an escaped exception hangs the pooled
                client on a dead read. Checked by
                handler-exception-safety."""
                try:
                    fn()
                # top of the handler stack: nothing above can retry,
                # and propagating would hang the keep-alive client
                # kflint: disable=retry-discipline
                except Exception as e:
                    print(f"[kf-router] handler crashed on "
                          f"{getattr(self, 'requestline', '?')}: {e!r}",
                          flush=True)
                    try:
                        self._reply(500, json.dumps(
                            {"error": f"internal error: {e}"}))
                    except OSError:
                        self.close_connection = True

            def do_GET(self):
                self._crash_guard(self._get)

            def do_POST(self):
                self._crash_guard(self._post)

            def _get(self):
                from urllib.parse import parse_qs, urlparse

                from kungfu_tpu.serve import frontend

                if self._chaos():
                    return
                parsed = urlparse(self.path)
                route = parsed.path
                if route == "/healthz":
                    self._reply(200, json.dumps(router.healthz()))
                    return
                if route == "/serve/result":
                    rid = int(parse_qs(parsed.query)
                              .get("id", ["0"])[0])
                    # shard by request id: result polls spread across
                    # the tier (follower reads; DONE is immutable)
                    self._forward_read(
                        lambda url: frontend.result(url, rid,
                                                    retry=NO_RETRY),
                        router._order(rid % len(router.servers)))
                    return
                if route == "/serve/stats":
                    self._forward_read(
                        lambda url: frontend.stats(url, retry=NO_RETRY),
                        router._order(router.index))
                    return
                if route == "/serve/results":
                    self._forward_read(
                        lambda url: {"results": frontend.results(
                            url, retry=NO_RETRY)},
                        router._order(router.index))
                    return
                if route == "/serve/invariants":
                    self._forward_read(
                        lambda url: {"violations": frontend.invariants(
                            url, retry=NO_RETRY)},
                        router._order(router.index))
                    return
                self._reply(404, '{"error": "not a router route"}')

            def _post(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n).decode() if n else ""
                if self._chaos():
                    return
                if self.path != "/serve/submit":
                    self._reply(404, '{"error": "routers only ingest '
                                     '/serve/submit"}')
                    return
                try:
                    doc = json.loads(body) if body else {}
                    if not isinstance(doc, dict):
                        raise ValueError("body must be a JSON object")
                except ValueError as e:
                    self._reply(400, json.dumps({"error": str(e)}))
                    return
                entry = router._enqueue_submit(doc)
                out = entry["out"]
                if out is None:
                    self._reply(503, '{"error": "admission flush '
                                     'failed; retry"}')
                elif "id" in out:
                    self._reply(200, json.dumps({"id": out["id"]}))
                else:
                    self._reply(int(out.get("code", 400)),
                                json.dumps({"error": out.get(
                                    "error", "rejected")}))

        return Handler

    def healthz(self) -> Dict:
        with self._cv:
            pending = len(self._pending)
            reqs = self._reqs
        return {"role": "router", "index": self.index,
                "pending": pending, "requests": reqs,
                "flushed_batches": self.flushed_batches,
                "submitted": self.submitted, "dead": self.dead}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="one stateless admission router")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--index", type=int, default=0)
    ap.add_argument("--servers", required=True,
                    help="comma-separated config-server base URLs")
    ap.add_argument("--flush-ms", type=float, default=None)
    args = ap.parse_args(argv)
    router = Router(
        [b.strip() for b in args.servers.split(",") if b.strip()],
        host=args.host, port=args.port, index=args.index,
        flush_ms=args.flush_ms, standalone=True).start()
    print(f"[kf-router] r{args.index} serving on {router.base}",
          flush=True)
    try:
        router._thread.join()
    except KeyboardInterrupt:
        router.stop()


if __name__ == "__main__":
    main()
