"""The request ledger: serving's source of truth on the control plane.

Requests live HERE — on the config server, the one process the whole
cluster already trusts to survive worker churn — not inside any decode
worker. A worker only ever *leases* work and streams tokens back, so
worker death mid-request loses nothing the ledger did not already
record: the lease expires, the request re-queues with its
generated-so-far tokens intact, and the next lease resumes it by
re-prefilling prompt + generated. That is the whole
completion-after-recovery story (docs/serving.md) — the elastic
machinery moves workers around, the ledger guarantees no request and
no token is lost or duplicated while they move.

Life cycle::

    submit -> QUEUED -> lease -> RUNNING -> append(done) -> DONE
                 ^                   |
                 +--- lease expiry / release / eviction

Admission is BOUNDED (`max_queue`): past the bound, `submit` raises
`AdmissionFull` and the HTTP front-end replies 429 — backpressure at
ingest, per the `retrying.py` taxonomy (429 is transient: a client
retry can heal it; a malformed submit is a 400 and never retried).

Append is POSITION-CHECKED and LEASE-FENCED: tokens carry their
position, overlapping re-deliveries (a resumed request's first step
re-emits what the ledger already has) are ignored if they agree and
are a recorded violation if they do not, a gap is rejected, and only
the current lease holder may append — a zombie worker whose lease was
reclaimed cannot corrupt the resumed stream (its append returns
``stale`` and the worker drops the sequence).

`check_invariants` is the request-plane analog of the goodput plane's
phases-sum-to-wall gate: conservation (every submitted request is in
exactly one state), bounded completion (1 <= tokens <= max_new on
DONE), and zero recorded append violations — the serving smoke and
`benchmarks/serve.py` fail loudly on any entry.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List

from ..trace import metrics

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"


class AdmissionFull(RuntimeError):
    """The bounded admission queue is full (HTTP 429 at the front
    end — transient in the retrying.py taxonomy)."""


@dataclass
class Request:
    """One request's ledger record."""

    id: int
    prompt: List[int]
    max_new: int
    state: str = QUEUED
    tokens: List[int] = field(default_factory=list)
    worker: str = ""
    submitted_t: float = 0.0
    done_t: float = 0.0
    lease_t: float = 0.0
    leases: int = 0

    def to_dict(self, include_prompt: bool = False) -> Dict:
        out = {
            "id": self.id, "state": self.state,
            "tokens": list(self.tokens), "max_new": self.max_new,
            "pos": len(self.tokens), "leases": self.leases,
        }
        if include_prompt:
            out["prompt"] = list(self.prompt)
        if self.state in (DONE, FAILED) and self.done_t:
            out["latency_ms"] = round(
                (self.done_t - self.submitted_t) * 1e3, 3)
        return out


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) of a sorted list — the
    ONE implementation (benchmarks/serve.py uses it too; two copies of
    a subtle rank expression would drift)."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(-(-q / 100.0 * len(sorted_vals) // 1)) - 1))
    return sorted_vals[k]


class RequestLedger:
    """Thread-safe request ledger (the config server's handler threads
    and any in-process test all share one instance)."""

    def __init__(self, max_queue: int = 256, lease_ms: float = 10_000.0,
                 max_leases: int = 8):
        self.max_queue = int(max_queue)
        self.lease_ms = float(lease_ms)
        #: lease attempts after which a request FAILS instead of
        #: re-queueing forever (a poisonous request must not starve
        #: the tier)
        self.max_leases = int(max_leases)
        self._mu = threading.Lock()
        # plain int (not itertools.count): replication snapshots must
        # carry the next id, and a counter cannot be peeked
        self._next_id = 1  # kf: guarded_by(_mu)
        # kf: guarded_by(_mu)
        self._reqs: Dict[int, Request] = {}
        # kf: guarded_by(_mu) — FIFO admission order
        self._queue: List[int] = []
        # kf: guarded_by(_mu) — recorded protocol violations
        self._violations: List[str] = []
        # kf: guarded_by(_mu) — completion latencies of the most
        # recent window: the SLO signal must recover when latencies
        # do (an all-history p99 would pin one cold-boot spike into a
        # permanent grow signal)
        self._recent = deque(maxlen=64)

    # -- ingest -------------------------------------------------------------

    def submit(self, prompt: List[int], max_new: int) -> int:
        if not prompt or not all(isinstance(t, int) for t in prompt):
            raise ValueError("prompt must be a non-empty int list")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        with self._mu:
            depth = len(self._queue)
            if depth >= self.max_queue:
                raise AdmissionFull(
                    f"admission queue full ({depth}/{self.max_queue})")
            rid = self._next_id
            self._next_id += 1
            self._reqs[rid] = Request(
                id=rid, prompt=[int(t) for t in prompt],
                max_new=int(max_new), submitted_t=time.monotonic())
            self._queue.append(rid)
            metrics.REGISTRY.set("kf_serve_queue_depth", depth + 1)
        return rid

    def submit_batch(self, rows: List[Dict]) -> List[Dict]:
        """Admit many prompts in one call — the router's coalescing
        verb, and ONE replicated op on the tier. Per-row outcomes in
        row order: {"id": k} on admission, {"error", "code"} on a
        full queue (429, transient) or malformed row (400, permanent).
        Row order is what makes replay deterministic: a follower
        replaying this op assigns the same ids in the same order."""
        out: List[Dict] = []
        for row in rows:
            try:
                rid = self.submit(
                    list(row.get("prompt", [])),
                    int(row.get("max_new_tokens", 0)))
                out.append({"id": rid})
            except AdmissionFull as e:
                out.append({"error": str(e), "code": 429})
            except (ValueError, TypeError, AttributeError) as e:
                out.append({"error": str(e), "code": 400})
        return out

    # -- worker side --------------------------------------------------------

    def _reclaim_locked(self, now: float) -> None:
        """Re-queue RUNNING requests whose lease expired (their worker
        died or was evicted without releasing)."""
        for r in self._reqs.values():
            if r.state == RUNNING and \
                    (now - r.lease_t) * 1e3 > self.lease_ms:
                if r.leases >= self.max_leases:
                    r.state = FAILED
                    r.done_t = now
                else:
                    r.state, r.worker = QUEUED, ""
                    # _locked helper: every caller (lease/stats)
                    # already holds _mu around this call
                    # kflint: disable=lock-discipline — caller holds _mu
                    self._queue.append(r.id)

    def lease(self, n: int, worker: str) -> List[Dict]:
        """Hand up to `n` queued requests to `worker` (stale leases
        reclaimed first). Each entry carries the prompt AND the
        generated-so-far tokens: a resumed request is re-prefilled
        from prompt + tokens and continues at `pos`."""
        now = time.monotonic()
        out: List[Dict] = []
        with self._mu:
            self._reclaim_locked(now)
            while self._queue and len(out) < max(n, 0):
                rid = self._queue.pop(0)
                r = self._reqs[rid]
                if r.state != QUEUED:  # released twice / raced
                    continue
                if r.leases >= self.max_leases:
                    # the poison bound applies at LEASE time too: a
                    # request every worker releases as unadmittable
                    # (e.g. a prompt no engine's max_len can hold)
                    # would otherwise bounce lease->release forever,
                    # never DONE nor FAILED, starving the drain
                    r.state, r.done_t = FAILED, now
                    continue
                r.state, r.worker = RUNNING, worker
                r.lease_t, r.leases = now, r.leases + 1
                out.append(r.to_dict(include_prompt=True))
            metrics.REGISTRY.set("kf_serve_queue_depth",
                                 len(self._queue))
        return out

    def append_tokens(self, rid: int, pos: int, tokens: List[int],
                      done: bool = False, worker: str = "") -> str:
        """Record generated tokens starting at position `pos`.

        Returns "ok", "stale" (the caller no longer holds the lease —
        drop the sequence) or "done" (already finished). Gaps raise;
        conflicting overlaps are recorded violations (greedy decode is
        deterministic — a disagreement is a real bug, not noise)."""
        now = time.monotonic()
        with self._mu:
            r = self._reqs.get(rid)
            if r is None:
                raise KeyError(f"unknown request {rid}")
            if r.state in (DONE, FAILED):
                return "done"
            if r.state != RUNNING or (worker and r.worker != worker):
                return "stale"
            if pos > len(r.tokens):
                raise ValueError(
                    f"request {rid}: append at pos {pos} leaves a gap "
                    f"(have {len(r.tokens)})")
            overlap = len(r.tokens) - pos
            for i in range(min(overlap, len(tokens))):
                if r.tokens[pos + i] != int(tokens[i]):
                    self._violations.append(
                        f"request {rid}: overlap mismatch at "
                        f"{pos + i}: {r.tokens[pos + i]} vs "
                        f"{tokens[i]}")
            fresh = [int(t) for t in tokens[overlap:]]
            if len(r.tokens) + len(fresh) > r.max_new:
                self._violations.append(
                    f"request {rid}: {len(r.tokens) + len(fresh)} "
                    f"tokens exceed max_new {r.max_new}")
                fresh = fresh[:r.max_new - len(r.tokens)]
            r.tokens.extend(fresh)
            r.lease_t = now  # an append renews the lease
            if done:
                r.state, r.done_t = DONE, now
                self._recent.append((now - r.submitted_t) * 1e3)
                metrics.REGISTRY.observe(
                    "kf_request_latency_ms",
                    (now - r.submitted_t) * 1e3)
                metrics.REGISTRY.inc("kf_serve_tokens_total",
                                     len(r.tokens))
        return "ok"

    def append_batch(self, rows: List[Dict],
                     worker: str = "") -> List[str]:
        """One control-plane round trip for a whole decode iteration:
        per-row `append_tokens` semantics ("ok"/"stale"/"done"), plus
        "error" for a row that would raise (recorded as a violation —
        one malformed row must not abort its batch-mates' appends).
        BENCH_r15's inverse np scaling was exactly the per-sequence
        /serve/append storm this folds into a single POST."""
        out: List[str] = []
        for row in rows:
            try:
                out.append(self.append_tokens(
                    int(row["id"]), int(row["pos"]),
                    [int(t) for t in row.get("tokens", [])],
                    done=bool(row.get("done", False)), worker=worker))
            except (KeyError, ValueError, TypeError) as e:
                with self._mu:
                    self._violations.append(f"append_batch: {e}")
                out.append("error")
        return out

    def release(self, rid: int, worker: str = "") -> None:
        """Return a leased request to the queue (eviction/shutdown:
        its tokens stay; a later lease resumes it)."""
        with self._mu:
            r = self._reqs.get(rid)
            if r is None or r.state != RUNNING:
                return
            if worker and r.worker != worker:
                return  # reclaimed and re-leased already
            r.state, r.worker = QUEUED, ""
            self._queue.append(rid)
            metrics.REGISTRY.set("kf_serve_queue_depth",
                                 len(self._queue))

    # -- observation --------------------------------------------------------

    def result(self, rid: int) -> Dict:
        with self._mu:
            r = self._reqs.get(rid)
            if r is None:
                raise KeyError(f"unknown request {rid}")
            return r.to_dict()

    def stats(self) -> Dict:
        """The SLO policy's signal: queue depth, in-flight, completion
        counts, and p50/p99 over the most RECENT completion window
        (not all history — the latency signal must recover when
        latencies do, or one cold-boot spike pins `SLOPolicy` in a
        permanent grow)."""
        with self._mu:
            self._reclaim_locked(time.monotonic())
            states: Dict[str, int] = {QUEUED: 0, RUNNING: 0, DONE: 0,
                                      FAILED: 0}
            toks = 0
            for r in self._reqs.values():
                states[r.state] += 1
                toks += len(r.tokens)
            lats = sorted(self._recent)
            return {
                "submitted": len(self._reqs),
                "queue_depth": states[QUEUED],
                "running": states[RUNNING],
                "done": states[DONE],
                "failed": states[FAILED],
                "tokens": toks,
                "p50_ms": round(percentile(lats, 50), 3),
                "p99_ms": round(percentile(lats, 99), 3),
            }

    def results(self) -> List[Dict]:
        with self._mu:
            return [r.to_dict() for r in
                    sorted(self._reqs.values(), key=lambda r: r.id)]

    # -- replication (docs/control_plane.md) --------------------------------

    def snapshot(self) -> Dict:
        """Full JSON-serializable state for primary-backup replication.
        Timestamps stay in the leader's time.monotonic domain —
        CLOCK_MONOTONIC is system-wide on Linux, so a same-host replica
        tier reads them directly; a takeover across hosts calls
        `renew_leases` anyway, which re-bases the only timestamps whose
        absolute value matters (lease expiry)."""
        with self._mu:
            return {
                "next_id": self._next_id,
                "queue": list(self._queue),
                "violations": list(self._violations),
                "recent": list(self._recent),
                "reqs": [
                    {
                        "id": r.id, "prompt": list(r.prompt),
                        "max_new": r.max_new, "state": r.state,
                        "tokens": list(r.tokens), "worker": r.worker,
                        "submitted_t": r.submitted_t,
                        "done_t": r.done_t, "lease_t": r.lease_t,
                        "leases": r.leases,
                    }
                    for r in self._reqs.values()
                ],
            }

    def restore(self, snap: Dict) -> None:
        """Adopt a leader's snapshot wholesale (idempotent: re-applying
        the same snapshot is a no-op by construction)."""
        with self._mu:
            self._next_id = int(snap["next_id"])
            self._queue = [int(x) for x in snap["queue"]]
            self._violations = [str(x) for x in snap["violations"]]
            self._recent = deque(snap["recent"], maxlen=64)
            self._reqs = {
                int(d["id"]): Request(
                    id=int(d["id"]), prompt=list(d["prompt"]),
                    max_new=int(d["max_new"]), state=str(d["state"]),
                    tokens=list(d["tokens"]), worker=str(d["worker"]),
                    submitted_t=float(d["submitted_t"]),
                    done_t=float(d["done_t"]),
                    lease_t=float(d["lease_t"]),
                    leases=int(d["leases"]))
                for d in snap["reqs"]
            }

    def renew_leases(self) -> int:
        """Re-base every RUNNING lease to now — leader takeover. The
        election window ate into the leases the dead leader granted;
        without the re-base a takeover longer than lease_ms would
        reclaim every in-flight request at once and re-run work whose
        workers are still healthily decoding. Returns renewals."""
        now = time.monotonic()
        n = 0
        with self._mu:
            for r in self._reqs.values():
                if r.state == RUNNING:
                    r.lease_t = now
                    n += 1
        return n

    def check_invariants(self) -> List[str]:
        """Empty list == healthy (see module docstring)."""
        out: List[str] = []
        with self._mu:
            out.extend(self._violations)
            queued = set()
            for rid in self._queue:
                if rid in queued:
                    out.append(f"request {rid} queued twice")
                queued.add(rid)
            for r in self._reqs.values():
                if r.state == QUEUED and r.id not in queued:
                    out.append(f"request {r.id} QUEUED but not in "
                               "queue")
                if r.state != QUEUED and r.id in queued:
                    out.append(f"request {r.id} {r.state} but still "
                               "in queue")
                if r.state == DONE and not \
                        1 <= len(r.tokens) <= r.max_new:
                    out.append(
                        f"request {r.id} DONE with {len(r.tokens)} "
                        f"tokens (max_new {r.max_new})")
                if r.state == RUNNING and not r.worker:
                    out.append(f"request {r.id} RUNNING without a "
                               "worker")
        return out
