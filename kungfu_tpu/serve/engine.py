"""The continuous-batching decode engine: Orca's iteration-level loop.

One `DecodeEngine` owns the model params, the paged KV pool and the
jitted decode step, and exposes exactly two scheduling verbs:

- ``admit(seq_id, prompt, max_new)`` — prefill a new request into a
  free batch slot (one batched causal forward through the MODEL's own
  prefill path fills the sequence's pool blocks) and emit its first
  token;
- ``step()`` — ONE decode iteration for every live slot, whatever
  mix of requests currently occupies them. New requests join the
  running batch between iterations (iteration-level scheduling,
  PAPERS.md Orca), finished requests retire and their blocks return
  to the pool immediately — no batch drains, no padding to the
  longest request.

When the pool runs dry mid-decode the engine PREEMPTS the youngest
sequence (fewest generated tokens — the cheapest redo) instead of
corrupting a live block: `step()` reports it and the caller returns
the request to the ledger, where its generated-so-far tokens are
already recorded and a later admission resumes it by re-prefilling
prompt + generated (docs/serving.md, "KV block lifecycle").

`build_lm` is the ONE model/params(+tp-sharding) setup both this
engine and `benchmarks/lm.py --decode` call, so the published
`gpt_decode_tokens_per_sec` row and the serving tier cannot drift
apart. Sampling is greedy (argmax) throughout — serving determinism
is what the parity tests pin.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import trace
from .kv_cache import KVPoolExhausted, PagedKVPool, pool_capacity_blocks

SIZES = {
    # name -> (hidden, layers, heads, intermediate); the canonical
    # GPT size table (benchmarks/lm.py re-exports it)
    "tiny": (128, 2, 8, 256),
    "small": (768, 12, 12, 3072),   # GPT-2 124M
    "medium": (1024, 24, 16, 4096),  # GPT-2 350M
}


def build_lm(size: str, max_position: int, tp: int = 1, dtype=None,
             seed: int = 0, vocab_size: int = 50257):
    """Model + params (+ tp sharding) for decoding: the shared setup
    of `benchmarks.lm.measure_decode_rate` and `DecodeEngine`.

    Returns ``(model, params, mesh)`` — `mesh` is None at tp=1,
    otherwise the (1, tp) ("data", "model") mesh with the params
    Megatron-sharded per the `serve` rules table
    (`parallel.rules.gpt_serve_rules` — registered, so the
    shard-rule-coverage/mesh lint passes gate serving's plan like
    every other family's). Raises SystemExit with the same messages
    the benchmark always printed for impossible tp splits.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..models import GPTConfig, GPTLM

    if size not in SIZES:
        raise SystemExit(f"unknown size {size!r} (known: {sorted(SIZES)})")
    hidden, layers, heads, inter = SIZES[size]
    n = jax.device_count()
    if tp > n:
        raise SystemExit(f"--tp {tp} exceeds device count {n}")
    if heads % tp:
        raise SystemExit(
            f"--tp {tp} must divide num_heads {heads} of size={size}")
    cfg = GPTConfig(vocab_size=vocab_size, hidden_size=hidden,
                    num_layers=layers, num_heads=heads,
                    intermediate_size=inter,
                    max_position=max_position,
                    dtype=dtype if dtype is not None else jnp.bfloat16)
    model = GPTLM(cfg)
    probe = jnp.zeros((1, 1), jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), probe)["params"]
    mesh = None
    if tp > 1:
        from jax.sharding import Mesh

        from ..parallel.rules import gpt_serve_rules, shard_params

        # decode's mesh is (1, tp) over the first tp devices — the
        # standard TPU serving layout (GSPMD propagates the Megatron
        # head sharding into the KV caches and inserts the ICI
        # collectives)
        mesh = Mesh(np.array(jax.devices()[:tp]).reshape(1, tp),
                    ("data", "model"))
        params = shard_params(jax.device_get(params), mesh,
                              gpt_serve_rules())
    return model, params, mesh


@dataclass
class _Seq:
    """One live sequence's engine-side state."""

    slot: int
    prompt_len: int
    max_new: int
    cache_len: int                    # tokens currently in pool blocks
    last_token: int                   # next decode input
    generated: List[int] = field(default_factory=list)
    # chunked-prefill state: `prompt` holds the full token list while
    # the sequence is still prefilling (None once decode-ready);
    # `prefill_pos` is the next position to prefill (starts past any
    # prefix-shared tokens)
    prompt: Optional[List[int]] = None
    prefill_pos: int = 0
    order: int = 0                    # admission order (FIFO prefill)
    # deferred prefills hold NO pool blocks until their first chunk
    # runs (`_prefill_step` admits lazily) — by then every
    # earlier-ordered prefill has committed, so a burst of identical
    # prompts admitted in one iteration still shares the first
    # arrival's blocks instead of each prefilling privately
    pending: bool = False


class DecodeEngine:
    """Iteration-level continuous batching over the paged KV pool."""

    def __init__(self, model, params, max_batch: int,
                 block_tokens: int, max_len: int,
                 num_blocks: int = 0, eos: Optional[int] = None,
                 kernel: str = "functional", prefill_chunk: int = 0,
                 share_prefix: bool = False):
        from . import paged

        cfg = model.config
        if max_len > cfg.max_position:
            raise ValueError(
                f"max_len {max_len} exceeds the model's max_position "
                f"{cfg.max_position}")
        if max_batch <= 0:
            raise ValueError(f"max_batch must be positive, got "
                             f"{max_batch}")
        self.model = model
        self.cfg = cfg
        self.params = params
        self.max_batch = int(max_batch)
        self.max_len = int(max_len)
        self.eos = eos
        self.max_blocks = paged.max_blocks_for(max_len, block_tokens)
        num_blocks = num_blocks or pool_capacity_blocks(
            max_batch, max_len, block_tokens)
        self.pool = PagedKVPool(num_blocks, block_tokens)
        self.pool_k, self.pool_v = paged.init_pool_tensors(
            cfg, num_blocks, block_tokens)
        # KF_SERVE_KERNEL resolution happens ONCE, here: "auto" means
        # the plan's pick on TPU and the functional path on CPU;
        # "kernel" forces the plan's pick (interpret mode off-TPU);
        # an over-budget plan degrades to functional either way
        self.kernel = self._resolve_kernel(kernel, block_tokens)
        self._decode = paged.make_decode_fn(cfg, kernel=self.kernel)
        self._prefill = paged.make_prefill_chunk_fn(cfg)
        self.prefill_chunk = int(prefill_chunk)
        self.share_prefix = bool(share_prefix)
        self._slots: List[Optional[object]] = [None] * self.max_batch
        self._seqs: Dict[object, _Seq] = {}
        self._admitted = 0
        self.steps = 0
        # wall-clock accounting for the per-np breakdown benchmark
        self.decode_s = 0.0
        self.prefill_s = 0.0
        self.prefill_chunks = 0

    def _resolve_kernel(self, knob: str, block_tokens: int) -> str:
        """Map the KF_SERVE_KERNEL knob to the decode_step kernel
        argument, consulting `paged_plan` so an over-budget shape
        falls back to the functional path at construction (not at
        Mosaic compile time)."""
        if knob == "functional":
            return "functional"
        import jax

        if knob == "auto" and jax.default_backend() != "tpu":
            return "functional"
        if knob in ("auto", "kernel"):
            from ..ops import paged_attn

            plan = paged_attn.paged_plan(
                self.max_blocks, block_tokens, self.cfg.num_heads,
                self.cfg.hidden_size // self.cfg.num_heads,
                dtype=self.cfg.dtype)
            return plan["scheme"]
        return knob  # explicit "resident"/"stream" (tests)

    def warm(self) -> None:
        """Compile every signature the serving loop can hit, BEFORE
        the first request: the decode step at its one fixed
        (max_batch, max_blocks) shape, the chunk-prefill buckets, and
        the whole-prefill length buckets. A replica that jits on its
        first real request stalls it for seconds — and on a shared
        host every OTHER replica's requests contend with that compile
        (the inverse-np scaling BENCH_r15 published was mostly
        laggard replicas compiling inside the measured window). All
        warm traffic lands in the scratch block (length/true_len 0 —
        masked out of every real row forever); wall time is NOT added
        to the prefill/decode accounting."""
        import numpy as np

        import jax
        import jax.numpy as jnp

        from . import paged

        bt = self.pool.block_tokens
        tables = self.pool.batch_tables([], self.max_blocks,
                                        pad_rows=self.max_batch)
        zeros = np.zeros(self.max_batch, np.int32)
        logits, self.pool_k, self.pool_v = self._decode(
            self.params, self.pool_k, self.pool_v, tables, zeros,
            zeros)
        jax.block_until_ready(logits)
        # chunk buckets: the configured chunk size plus the one-block
        # bucket (remainders and the recomputed tail of a fully
        # shared prompt both land there)
        chunk_buckets = {bt}
        if self.prefill_chunk:
            chunk_buckets.add(-(-self.prefill_chunk // bt) * bt)
        row = np.zeros(self.max_blocks, np.int32)
        for c in sorted(chunk_buckets):
            logits, self.pool_k, self.pool_v = self._prefill(
                self.params, self.pool_k, self.pool_v,
                jnp.asarray(row), 0,
                jnp.asarray(np.zeros(c, np.int32)), 0)
            jax.block_until_ready(logits)
        # whole-prefill buckets: with chunking on, prompts longer
        # than the chunk defer to the incremental path, so only the
        # buckets up to the chunk size can reach paged.prefill
        whole = (min(-(-self.prefill_chunk // bt), self.max_blocks)
                 if self.prefill_chunk else self.max_blocks)
        for nb in range(1, whole + 1):
            arr = jnp.zeros((1, nb * bt), jnp.int32)
            logits, ks, vs = paged.prefill(self.model, self.params,
                                           arr)
            self.pool_k, self.pool_v = paged.write_prefill(
                self.pool_k, self.pool_v, [0] * nb, ks[:, 0],
                vs[:, 0], bt)
            jax.block_until_ready(logits)

    # -- admission ----------------------------------------------------------

    @property
    def active(self) -> int:
        return len(self._seqs)

    def free_slots(self) -> int:
        return self.max_batch - len(self._seqs)

    def can_admit(self, prompt_len: int) -> bool:
        return (self.free_slots() > 0
                and prompt_len < self.max_len
                and self.pool.can_admit(prompt_len))

    def admit(self, seq_id, prompt: List[int],
              max_new: int) -> Tuple[Optional[int], bool]:
        """Admit `prompt` into a free slot. When neither prefix
        sharing nor chunking applies, the whole prompt prefills here
        and ``(first_token, done)`` returns as before. Otherwise the
        prefill is DEFERRED: the sequence enters the prefilling state,
        ``(None, False)`` returns immediately, and `step()` advances
        the prefill one chunk per iteration (interleaved with decode)
        until the first token is emitted through its `emitted` map.
        Raises KVPoolExhausted / ValueError when it cannot admit — the
        caller's admission queue keeps the request."""
        import time

        import numpy as np

        import jax.numpy as jnp

        from . import paged

        if seq_id in self._seqs:
            raise ValueError(f"sequence {seq_id!r} already live")
        if self.free_slots() <= 0:
            raise KVPoolExhausted("no free batch slot")
        t = len(prompt)
        if not 0 < t < self.max_len:
            raise ValueError(
                f"prompt length {t} outside (0, {self.max_len})")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        bt = self.pool.block_tokens
        # how much of the prompt COULD be skipped: committed donors in
        # the prefix index now, plus full-block prefixes of sequences
        # still prefilling — those run before this one (FIFO order)
        # and commit on completion, so deferring lets this sequence
        # share blocks that do not exist yet
        committed = inflight = 0
        if self.share_prefix:
            committed = self.pool.match_prefix(prompt)[1]
            for q in self._seqs.values():
                if q.prompt is None:
                    continue
                lim = min(q.prompt_len, t)
                m = 0
                while ((m + 1) * bt <= lim
                       and q.prompt[m * bt:(m + 1) * bt]
                       == prompt[m * bt:(m + 1) * bt]):
                    m += 1
                inflight = max(inflight, m * bt)
        potential = min(max(committed, inflight), t - 1)
        slot = self._slots.index(None)
        self._admitted += 1
        if potential > 0 or (self.prefill_chunk
                             and t - potential > self.prefill_chunk):
            # incremental path: step() owns the prefill from here
            seq = _Seq(slot=slot, prompt_len=t, max_new=int(max_new),
                       cache_len=t, last_token=int(prompt[-1]),
                       prompt=list(prompt), prefill_pos=0,
                       order=self._admitted, pending=True)
            if committed > 0 and committed >= inflight:
                # donors are ALREADY committed: map them now, so the
                # blocks-in-use collapse is visible at admit time and
                # pool pressure accounts the sharer immediately (a
                # failure here propagates with nothing registered)
                self.pool.admit(seq_id, t, prompt=prompt)
                seq.pending = False
                seq.prefill_pos = min(self.pool.shared_tokens(seq_id),
                                      t - 1)
            # otherwise the pool admission is LAZY (`pending`) so the
            # prefix match runs after the in-flight donors commit
            self._slots[slot] = seq_id
            self._seqs[seq_id] = seq
            return None, False
        table = self.pool.admit(
            seq_id, t, prompt=prompt if self.share_prefix else None)
        # pad the prompt to a block-sized bucket: one prefill compile
        # per bucket instead of per distinct length (causal masking
        # keeps every real position independent of the padding)
        padded = -(-t // bt) * bt
        arr = np.zeros((1, padded), np.int32)
        arr[0, :t] = prompt
        t0 = time.perf_counter()
        with trace.span("request.prefill", cat="serve", seq=str(seq_id),
                        prompt_len=t):
            logits, ks, vs = paged.prefill(self.model, self.params,
                                           jnp.asarray(arr))
            # the full padded prefix ships to the pool in ONE donated
            # scatter (padded tail masked by length, never visible)
            self.pool_k, self.pool_v = paged.write_prefill(
                self.pool_k, self.pool_v, table,
                ks[:, 0], vs[:, 0], bt)
            tok0 = int(jnp.argmax(logits[0, t - 1]))
        self.prefill_s += time.perf_counter() - t0
        if self.share_prefix:
            self.pool.commit_prefix(seq_id, prompt)
        seq = _Seq(slot=slot, prompt_len=t, max_new=int(max_new),
                   cache_len=t, last_token=tok0, generated=[tok0],
                   order=self._admitted)
        done = self._finished(seq)
        if done:
            self.pool.release(seq_id)
        else:
            self._slots[slot] = seq_id
            self._seqs[seq_id] = seq
        return tok0, done

    def _finished(self, seq: _Seq) -> bool:
        if len(seq.generated) >= seq.max_new:
            return True
        if self.eos is not None and seq.generated[-1] == self.eos:
            return True
        # hard cap: the pool reservation ends at max_len positions
        return seq.cache_len + 1 >= self.max_len

    # -- the iteration ------------------------------------------------------

    def _reserve(self, seq_id, attempt) -> Tuple[
            List[object], List[Tuple[int, int]]]:
        """Run `attempt` (an allocator call on behalf of `seq_id`),
        preempting the youngest OTHER live sequence (fewest generated
        tokens) on exhaustion until it succeeds; preempting `seq_id`
        itself is the last resort. Returns ``(preempted ids,
        (src, dst) pool-tensor copies the allocator requested)``."""
        preempted: List[object] = []
        while True:
            try:
                return preempted, attempt()
            except KVPoolExhausted:
                victims = sorted(
                    self._seqs,
                    key=lambda s: (s == seq_id,
                                   len(self._seqs[s].generated)))
                victim = victims[0]
                self._drop(victim)
                preempted.append(victim)
                if victim == seq_id:
                    return preempted, []

    def _make_room(self, seq_id) -> Tuple[List[object],
                                          List[Tuple[int, int]]]:
        """Extend `seq_id`'s table by one position (copy-on-write of
        a shared last block included)."""
        return self._reserve(
            seq_id,
            lambda: self.pool.grow(
                seq_id, self._seqs[seq_id].cache_len + 1))

    def _drop(self, seq_id) -> None:
        seq = self._seqs.pop(seq_id)
        self._slots[seq.slot] = None
        if not seq.pending:  # pending seqs hold no pool blocks yet
            self.pool.release(seq_id)

    def _prefill_step(self, seq_id, emitted: Dict[object,
                                                  Tuple[int, bool]],
                      preempted: List[object]) -> None:
        """Advance `seq_id`'s deferred prefill by one chunk. On the
        final chunk the first token is computed from the last real
        position's logits and reported through `emitted` exactly like
        a decode step's token."""
        import time

        import numpy as np

        import jax
        import jax.numpy as jnp

        from . import paged

        seq = self._seqs[seq_id]
        t = seq.prompt_len
        bt = self.pool.block_tokens
        if seq.pending:
            # lazy pool admission: every earlier-ordered prefill has
            # completed (and, with sharing, committed), so the prefix
            # match sees donors that did not exist at admit() time
            pre, _ = self._reserve(
                seq_id,
                lambda: self.pool.admit(
                    seq_id, t,
                    prompt=seq.prompt if self.share_prefix else None))
            preempted.extend(pre)
            if seq_id not in self._seqs:  # could not fit even alone
                return
            seq.pending = False
            # never share the FULL prompt: position t-1 must be
            # recomputed so the first token's logits exist (the
            # one-token chunk that recomputes it goes through
            # copy-on-write, so a shared donor block is never
            # overwritten)
            seq.prefill_pos = min(self.pool.shared_tokens(seq_id),
                                  t - 1)
        start = seq.prefill_pos
        real = t - start
        if self.prefill_chunk:
            real = min(real, self.prefill_chunk)
        # writes into shared/committed blocks (the divergence point,
        # or the recomputed last position of a fully-shared prompt)
        # swap in private copies first
        pre, copies = self._reserve(
            seq_id,
            lambda: self.pool.cow_for_write(seq_id, start, start + real))
        preempted.extend(pre)
        if seq_id not in self._seqs:  # lost its own blocks
            return
        if copies:
            self.pool_k, self.pool_v = paged.copy_blocks(
                self.pool_k, self.pool_v, copies)
        # chunks pad to a block multiple: one compile per chunk bucket
        # (pad positions scatter to the scratch block, masked off)
        c = -(-real // bt) * bt
        toks = np.zeros(c, np.int32)
        toks[:real] = seq.prompt[start:start + real]
        table = np.full(self.max_blocks, 0, np.int32)
        row = self.pool.table(seq_id)
        table[:len(row)] = row
        t0 = time.perf_counter()
        with trace.span("request.prefill_chunk", cat="serve",
                        seq=str(seq_id), start=start, tokens=real):
            logits, self.pool_k, self.pool_v = self._prefill(
                self.params, self.pool_k, self.pool_v,
                jnp.asarray(table), start, jnp.asarray(toks), t)
            logits = jax.block_until_ready(logits)
        self.prefill_s += time.perf_counter() - t0
        self.prefill_chunks += 1
        seq.prefill_pos = start + real
        if seq.prefill_pos < t:
            return
        tok0 = int(np.asarray(logits)[real - 1].argmax())
        if self.share_prefix:
            self.pool.commit_prefix(seq_id, seq.prompt)
        seq.prompt = None
        seq.generated = [tok0]
        seq.last_token = tok0
        seq.cache_len = t
        done = self._finished(seq)
        if done:
            self._drop(seq_id)
        emitted[seq_id] = (tok0, done)

    def step(self) -> Tuple[Dict[object, Tuple[int, bool]],
                            List[object]]:
        """One iteration over every live slot: at most ONE prefilling
        sequence advances by one chunk (admission order), then every
        decode-ready slot decodes — prefill is interleaved with
        decode instead of stalling it.

        Returns ``(emitted, preempted)``: `emitted` maps seq_id ->
        (token, done) for every sequence that emitted a token this
        iteration (a decode step's token, or a completed prefill's
        first token); `preempted` lists sequences evicted by pool
        pressure (their blocks are freed; re-admit to resume). No
        live slots -> both empty.
        """
        import time

        import numpy as np

        from . import paged

        if not self._seqs:
            return {}, []
        emitted: Dict[object, Tuple[int, bool]] = {}
        preempted: List[object] = []
        prefilling = sorted(
            (s for s, q in self._seqs.items() if q.prompt is not None),
            key=lambda s: self._seqs[s].order)
        if prefilling:
            self._prefill_step(prefilling[0], emitted, preempted)
        # capacity first: every decoding row's incoming token needs a
        # slot in its block table BEFORE the batched scatter runs —
        # and any copy-on-write the growth requests must land BEFORE
        # the scatter too (one batched copy, gathers read pre-copy
        # state so overlapping src/dst rows stay consistent)
        copies: List[Tuple[int, int]] = []
        for seq_id in [s for s in self._slots if s is not None]:
            if (seq_id in self._seqs and seq_id not in emitted
                    and self._seqs[seq_id].prompt is None):
                pre, cps = self._make_room(seq_id)
                preempted.extend(pre)
                copies.extend(cps)
        if copies:
            self.pool_k, self.pool_v = paged.copy_blocks(
                self.pool_k, self.pool_v, copies)
        live = [s for s in self._slots
                if s is not None and s in self._seqs
                and s not in emitted
                and self._seqs[s].prompt is None]
        self.steps += 1
        if not live:
            return emitted, preempted
        order = {s: self._seqs[s].slot for s in live}
        tokens = np.zeros(self.max_batch, np.int32)
        lengths = np.zeros(self.max_batch, np.int32)
        tables = self.pool.batch_tables([], self.max_blocks,
                                        pad_rows=self.max_batch)
        for s, slot in order.items():
            seq = self._seqs[s]
            tokens[slot] = seq.last_token
            lengths[slot] = seq.cache_len
            row = self.pool.table(s)
            tables[slot, :len(row)] = row
        t0 = time.perf_counter()
        with trace.span("serve.decode_step", cat="serve",
                        batch=len(live)):
            logits, self.pool_k, self.pool_v = self._decode(
                self.params, self.pool_k, self.pool_v, tables,
                lengths, tokens)
            toks = np.asarray(logits.argmax(axis=-1))
        self.decode_s += time.perf_counter() - t0
        for s, slot in order.items():
            seq = self._seqs[s]
            tok = int(toks[slot])
            seq.generated.append(tok)
            seq.last_token = tok
            seq.cache_len += 1
            done = self._finished(seq)
            if done:
                self._drop(s)
            emitted[s] = (tok, done)
        return emitted, preempted

    def drain(self, seq_id) -> None:
        """Release a live sequence without finishing it (eviction /
        shutdown: its blocks return to the pool; the ledger keeps the
        generated-so-far record)."""
        if seq_id in self._seqs:
            self._drop(seq_id)

    def live(self) -> List[object]:
        return [s for s in self._slots if s is not None]

    def prefilling(self) -> List[object]:
        """Live sequences still in the chunked-prefill state (they
        emit nothing until their last chunk — the worker heartbeats
        their leases)."""
        return [s for s, q in self._seqs.items()
                if q.prompt is not None]

    def is_live(self, seq_id) -> bool:
        return seq_id in self._seqs

    def generated(self, seq_id) -> List[int]:
        return list(self._seqs[seq_id].generated)
