"""The continuous-batching decode engine: Orca's iteration-level loop.

One `DecodeEngine` owns the model params, the paged KV pool and the
jitted decode step, and exposes exactly two scheduling verbs:

- ``admit(seq_id, prompt, max_new)`` — prefill a new request into a
  free batch slot (one batched causal forward through the MODEL's own
  prefill path fills the sequence's pool blocks) and emit its first
  token;
- ``step()`` — ONE decode iteration for every live slot, whatever
  mix of requests currently occupies them. New requests join the
  running batch between iterations (iteration-level scheduling,
  PAPERS.md Orca), finished requests retire and their blocks return
  to the pool immediately — no batch drains, no padding to the
  longest request.

When the pool runs dry mid-decode the engine PREEMPTS the youngest
sequence (fewest generated tokens — the cheapest redo) instead of
corrupting a live block: `step()` reports it and the caller returns
the request to the ledger, where its generated-so-far tokens are
already recorded and a later admission resumes it by re-prefilling
prompt + generated (docs/serving.md, "KV block lifecycle").

`build_lm` is the ONE model/params(+tp-sharding) setup both this
engine and `benchmarks/lm.py --decode` call, so the published
`gpt_decode_tokens_per_sec` row and the serving tier cannot drift
apart. Sampling is greedy (argmax) throughout — serving determinism
is what the parity tests pin.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import trace
from .kv_cache import KVPoolExhausted, PagedKVPool, pool_capacity_blocks

SIZES = {
    # name -> (hidden, layers, heads, intermediate); the canonical
    # GPT size table (benchmarks/lm.py re-exports it)
    "tiny": (128, 2, 8, 256),
    "small": (768, 12, 12, 3072),   # GPT-2 124M
    "medium": (1024, 24, 16, 4096),  # GPT-2 350M
}


def build_lm(size: str, max_position: int, tp: int = 1, dtype=None,
             seed: int = 0, vocab_size: int = 50257):
    """Model + params (+ tp sharding) for decoding: the shared setup
    of `benchmarks.lm.measure_decode_rate` and `DecodeEngine`.

    Returns ``(model, params, mesh)`` — `mesh` is None at tp=1,
    otherwise the (1, tp) ("data", "model") mesh with the params
    Megatron-sharded per the `serve` rules table
    (`parallel.rules.gpt_serve_rules` — registered, so the
    shard-rule-coverage/mesh lint passes gate serving's plan like
    every other family's). Raises SystemExit with the same messages
    the benchmark always printed for impossible tp splits.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..models import GPTConfig, GPTLM

    if size not in SIZES:
        raise SystemExit(f"unknown size {size!r} (known: {sorted(SIZES)})")
    hidden, layers, heads, inter = SIZES[size]
    n = jax.device_count()
    if tp > n:
        raise SystemExit(f"--tp {tp} exceeds device count {n}")
    if heads % tp:
        raise SystemExit(
            f"--tp {tp} must divide num_heads {heads} of size={size}")
    cfg = GPTConfig(vocab_size=vocab_size, hidden_size=hidden,
                    num_layers=layers, num_heads=heads,
                    intermediate_size=inter,
                    max_position=max_position,
                    dtype=dtype if dtype is not None else jnp.bfloat16)
    model = GPTLM(cfg)
    probe = jnp.zeros((1, 1), jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), probe)["params"]
    mesh = None
    if tp > 1:
        from jax.sharding import Mesh

        from ..parallel.rules import gpt_serve_rules, shard_params

        # decode's mesh is (1, tp) over the first tp devices — the
        # standard TPU serving layout (GSPMD propagates the Megatron
        # head sharding into the KV caches and inserts the ICI
        # collectives)
        mesh = Mesh(np.array(jax.devices()[:tp]).reshape(1, tp),
                    ("data", "model"))
        params = shard_params(jax.device_get(params), mesh,
                              gpt_serve_rules())
    return model, params, mesh


@dataclass
class _Seq:
    """One live sequence's engine-side state."""

    slot: int
    prompt_len: int
    max_new: int
    cache_len: int                    # tokens currently in pool blocks
    last_token: int                   # next decode input
    generated: List[int] = field(default_factory=list)


class DecodeEngine:
    """Iteration-level continuous batching over the paged KV pool."""

    def __init__(self, model, params, max_batch: int,
                 block_tokens: int, max_len: int,
                 num_blocks: int = 0, eos: Optional[int] = None):
        from . import paged

        cfg = model.config
        if max_len > cfg.max_position:
            raise ValueError(
                f"max_len {max_len} exceeds the model's max_position "
                f"{cfg.max_position}")
        if max_batch <= 0:
            raise ValueError(f"max_batch must be positive, got "
                             f"{max_batch}")
        self.model = model
        self.cfg = cfg
        self.params = params
        self.max_batch = int(max_batch)
        self.max_len = int(max_len)
        self.eos = eos
        self.max_blocks = paged.max_blocks_for(max_len, block_tokens)
        num_blocks = num_blocks or pool_capacity_blocks(
            max_batch, max_len, block_tokens)
        self.pool = PagedKVPool(num_blocks, block_tokens)
        self.pool_k, self.pool_v = paged.init_pool_tensors(
            cfg, num_blocks, block_tokens)
        self._decode = paged.make_decode_fn(cfg)
        self._slots: List[Optional[object]] = [None] * self.max_batch
        self._seqs: Dict[object, _Seq] = {}
        self.steps = 0

    # -- admission ----------------------------------------------------------

    @property
    def active(self) -> int:
        return len(self._seqs)

    def free_slots(self) -> int:
        return self.max_batch - len(self._seqs)

    def can_admit(self, prompt_len: int) -> bool:
        return (self.free_slots() > 0
                and prompt_len < self.max_len
                and self.pool.can_admit(prompt_len))

    def admit(self, seq_id, prompt: List[int],
              max_new: int) -> Tuple[int, bool]:
        """Prefill `prompt` into a free slot; returns ``(first_token,
        done)``. Raises KVPoolExhausted / ValueError when it cannot —
        the caller's admission queue keeps the request."""
        import numpy as np

        import jax.numpy as jnp

        from . import paged

        if seq_id in self._seqs:
            raise ValueError(f"sequence {seq_id!r} already live")
        if self.free_slots() <= 0:
            raise KVPoolExhausted("no free batch slot")
        t = len(prompt)
        if not 0 < t < self.max_len:
            raise ValueError(
                f"prompt length {t} outside (0, {self.max_len})")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        table = self.pool.admit(seq_id, t)
        bt = self.pool.block_tokens
        # pad the prompt to a block-sized bucket: one prefill compile
        # per bucket instead of per distinct length (causal masking
        # keeps every real position independent of the padding)
        padded = -(-t // bt) * bt
        arr = np.zeros((1, padded), np.int32)
        arr[0, :t] = prompt
        with trace.span("request.prefill", cat="serve", seq=str(seq_id),
                        prompt_len=t):
            logits, ks, vs = paged.prefill(self.model, self.params,
                                           jnp.asarray(arr))
            # the full padded prefix ships to the pool in ONE donated
            # scatter (padded tail masked by length, never visible)
            self.pool_k, self.pool_v = paged.write_prefill(
                self.pool_k, self.pool_v, table,
                ks[:, 0], vs[:, 0], bt)
            tok0 = int(jnp.argmax(logits[0, t - 1]))
        slot = self._slots.index(None)
        seq = _Seq(slot=slot, prompt_len=t, max_new=int(max_new),
                   cache_len=t, last_token=tok0, generated=[tok0])
        done = self._finished(seq)
        if done:
            self.pool.release(seq_id)
        else:
            self._slots[slot] = seq_id
            self._seqs[seq_id] = seq
        return tok0, done

    def _finished(self, seq: _Seq) -> bool:
        if len(seq.generated) >= seq.max_new:
            return True
        if self.eos is not None and seq.generated[-1] == self.eos:
            return True
        # hard cap: the pool reservation ends at max_len positions
        return seq.cache_len + 1 >= self.max_len

    # -- the iteration ------------------------------------------------------

    def _make_room(self, seq_id) -> List[object]:
        """Extend `seq_id`'s table by one position, preempting the
        youngest OTHER live sequence (fewest generated tokens) until
        it fits; preempting `seq_id` itself is the last resort.
        Returns the preempted ids."""
        preempted: List[object] = []
        while True:
            try:
                self.pool.grow(
                    seq_id, self._seqs[seq_id].cache_len + 1)
                return preempted
            except KVPoolExhausted:
                victims = sorted(
                    self._seqs,
                    key=lambda s: (s == seq_id,
                                   len(self._seqs[s].generated)))
                victim = victims[0]
                self._drop(victim)
                preempted.append(victim)
                if victim == seq_id:
                    return preempted

    def _drop(self, seq_id) -> None:
        seq = self._seqs.pop(seq_id)
        self._slots[seq.slot] = None
        self.pool.release(seq_id)

    def step(self) -> Tuple[Dict[object, Tuple[int, bool]],
                            List[object]]:
        """One decode iteration over every live slot.

        Returns ``(emitted, preempted)``: `emitted` maps seq_id ->
        (token, done) for every sequence that decoded this iteration;
        `preempted` lists sequences evicted by pool pressure (their
        blocks are freed; re-admit to resume). No live slots -> both
        empty.
        """
        import numpy as np

        if not self._seqs:
            return {}, []
        # capacity first: every row's incoming token needs a slot in
        # its block table BEFORE the batched scatter runs
        preempted: List[object] = []
        for seq_id in [s for s in self._slots if s is not None]:
            if seq_id in self._seqs:  # not preempted by an earlier row
                preempted.extend(self._make_room(seq_id))
        live = [s for s in self._slots if s is not None]
        if not live:
            return {}, preempted
        order = {s: self._seqs[s].slot for s in live}
        tokens = np.zeros(self.max_batch, np.int32)
        lengths = np.zeros(self.max_batch, np.int32)
        tables = self.pool.batch_tables([], self.max_blocks,
                                        pad_rows=self.max_batch)
        for s, slot in order.items():
            seq = self._seqs[s]
            tokens[slot] = seq.last_token
            lengths[slot] = seq.cache_len
            row = self.pool.table(s)
            tables[slot, :len(row)] = row
        with trace.span("serve.decode_step", cat="serve",
                        batch=len(live)):
            logits, self.pool_k, self.pool_v = self._decode(
                self.params, self.pool_k, self.pool_v, tables,
                lengths, tokens)
            toks = np.asarray(logits.argmax(axis=-1))
        emitted: Dict[object, Tuple[int, bool]] = {}
        for s, slot in order.items():
            seq = self._seqs[s]
            tok = int(toks[slot])
            seq.generated.append(tok)
            seq.last_token = tok
            seq.cache_len += 1
            done = self._finished(seq)
            if done:
                self._drop(s)
            emitted[s] = (tok, done)
        self.steps += 1
        return emitted, preempted

    def drain(self, seq_id) -> None:
        """Release a live sequence without finishing it (eviction /
        shutdown: its blocks return to the pool; the ledger keeps the
        generated-so-far record)."""
        if seq_id in self._seqs:
            self._drop(seq_id)

    def live(self) -> List[object]:
        return [s for s in self._slots if s is not None]

    def is_live(self, seq_id) -> bool:
        return seq_id in self._seqs

    def generated(self, seq_id) -> List[int]:
        return list(self._seqs[seq_id].generated)
