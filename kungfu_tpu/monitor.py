"""Per-peer traffic monitoring endpoint.

Rebuild of the reference's monitor subsystem (reference:
srcs/go/monitor/{monitor,counters,server}.go — egress/ingress byte
counters + rates served as Prometheus-style text at
``http://peer:port+10000/metrics``, enabled by
KUNGFU_CONFIG_ENABLE_MONITORING, 1s default period). Counters live in the
C++ control plane (kf_stats); this module samples them to derive rates and
serves the text endpoint, gated by KF_ENABLE_MONITORING.
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

METRICS_PORT_OFFSET = 10000  # reference: monitor runs on peer port + 10000


class MetricsServer:
    """Serves /metrics for one peer; sample() keeps rate gauges fresh."""

    def __init__(self, peer, port: int, period_s: float = 1.0):
        self._peer = peer
        self._port = port
        self._period = period_s
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads = []
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # sampled from the tick thread AND every /metrics handler thread
        self._last = (time.monotonic(), 0, 0)  # kf: guarded_by(_lock)
        self._rates = (0.0, 0.0)  # kf: guarded_by(_lock)

    def _sample(self):
        """Advance the rate window and return ONE consistent
        ``(stats, (egress_rate, ingress_rate))`` pair, computed and
        read under the same lock acquisition. Both the tick thread and
        every /metrics handler thread land here; returning rates from
        a second lock acquisition (the pre-round-10 shape) let another
        thread's sample slip between the two, pairing this scrape's
        totals with a different window's rates."""
        with self._lock:
            # the stats read sits INSIDE the lock too: two samplers
            # interleaving an outside read could record the newer
            # totals first and hand the older sampler a negative rate
            stats = self._peer.stats()
            now = time.monotonic()
            t0, eg0, in0 = self._last
            dt = max(now - t0, 1e-9)
            self._rates = ((stats["egress_bytes"] - eg0) / dt,
                           (stats["ingress_bytes"] - in0) / dt)
            self._last = (now, stats["egress_bytes"], stats["ingress_bytes"])
            return stats, self._rates

    def render(self) -> str:
        stats, (eg_rate, in_rate) = self._sample()
        rank = self._peer.rank
        lines = [
            f'kf_egress_bytes_total{{rank="{rank}"}} {stats["egress_bytes"]}',
            f'kf_ingress_bytes_total{{rank="{rank}"}} {stats["ingress_bytes"]}',
            f'kf_egress_bytes_per_sec{{rank="{rank}"}} {eg_rate:.1f}',
            f'kf_ingress_bytes_per_sec{{rank="{rank}"}} {in_rate:.1f}',
        ]
        # scoped hot-path timers (KF_TRACE=1): send/dial/recv_wait/...
        from .ffi import trace_report

        for scope, c in trace_report().items():
            tags = f'{{rank="{rank}",scope="{scope}"}}'
            lines += [
                f"kf_trace_count{tags} {c['count']}",
                f"kf_trace_total_us{tags} {c['total_us']}",
                f"kf_trace_max_us{tags} {c['max_us']}",
            ]
        # the unified metrics plane (docs/observability.md): step
        # latency histograms, per-collective wire bytes, queue depths
        # — whatever the runtime components registered this process
        from . import trace as kftrace
        from .trace.metrics import REGISTRY

        if kftrace.enabled():
            REGISTRY.set("kf_trace_dropped_events",
                         kftrace.recorder().dropped_events)
        lines += REGISTRY.render(extra_labels={"rank": str(rank)})
        return "\n".join(lines) + "\n"

    def start(self) -> "MetricsServer":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                body = outer.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self._httpd = ThreadingHTTPServer(("0.0.0.0", self._port), Handler)
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="kf-metrics", daemon=True)
        t.start()
        self._threads.append(t)

        def tick():
            while not self._stop.wait(self._period):
                try:
                    self._sample()
                except (RuntimeError, OSError, KeyError):
                    return  # peer shut down (KfError is a RuntimeError)
        t2 = threading.Thread(target=tick, name="kf-metrics-tick", daemon=True)
        t2.start()
        self._threads.append(t2)
        return self

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def stop(self):
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
