"""Zero-stall elastic checkpointing: async sharded incremental saves.

`checkpoint.py`'s npz dump is the restart-from-zero backstop, but it is
synchronous and whole-tree: rank 0 `device_get`s and serializes every
byte while all peers stall at the next collective, so durable
checkpoints are either rare (big recovery-loss window) or expensive (a
fixed % of every step burned). This module is the checkpoint tier the
fault-tolerance story needs — the last rung of the recovery state
machine (docs/fault_tolerance.md): when the whole cluster dies and the
live-resync path has nobody left to resync from, a relaunched cluster
(of ANY size) restores the latest complete generation instead of losing
all state.

Three properties, each riding machinery the elastic runtime already
proved:

- **Sharded.** Each peer writes only its shard of the param/opt tree.
  Shard assignment is `ops.collective.shard_schedule` — the same
  deterministic `chunk_schedule` spans the elastic streaming resync
  uses, round-robined over ranks — and bytes are taken through
  `leaf_byte_views`, so a peer's shard file is a sequence of zero-copy
  span writes with no model-sized staging buffer. Because the schedule
  is a pure function of shapes/dtypes, the save path needs NO
  collectives at all: every rank derives the identical owner map from
  its own replica, and the filesystem is the rendezvous (per-rank
  manifest pieces are the commit markers; a generation is complete iff
  every rank's piece exists and agrees).
- **Asynchronous.** `AsyncShardedCheckpointer.save()` snapshots the
  tree and returns; hashing, span writes, fsync and the manifest commit
  run on an executor thread overlapped with the next training steps.
  The snapshot itself is double-buffered and nearly free: jax leaves
  are immutable, so the training thread only *captures references* and
  the writer thread pays the D2H (`np.asarray`) per leaf — JAX async
  dispatch blocks only until that leaf's producing computation is done,
  which the next steps' dispatch hides. Only writeable numpy leaves
  (which a trainer may mutate in place) are copied eagerly, and only
  the spans this rank owns. A bounded number of snapshots may be in
  flight (`max_pending`, default 2 — the double buffer); a third
  `save()` blocks until the oldest write lands, which is the
  backpressure keeping a slow disk from hoarding host memory.
- **Incremental.** A per-leaf content hash (blake2b) skips leaves
  unchanged since the previous generation; tiny leaves (opt-state
  `step`, scalars — `ALWAYS_WRITE_BYTES`) are always written. The
  manifest records which generation owns each leaf's bytes, so a
  generation is a delta chain whose referenced ancestors are retained
  by GC until unreferenced. Replica divergence cannot corrupt the
  chain: two ranks sharing spans of one leaf both record its hash, and
  the manifest merge fails loudly if they disagree.

**Restore re-shards.** A cluster of a *different* np than the save
reads the manifest, derives a restore-side `shard_schedule` for its own
size, has each peer read exactly its spans from the owning generations'
shard files, and exchanges chunks over DCN with the same pipelined
in-place broadcasts the elastic resync uses (`broadcast_inplace`,
per-chunk roots). Every leaf is then verified against its manifest
hash before the tree is returned — a torn shard, a missing shard or a
mismatched manifest makes the generation fail loudly and restore falls
back to the previous *complete* generation; a mixed restore is
impossible by construction. `GradBucketPipeline` error-feedback
residuals are PER-RANK state (docs/grad_pipeline.md): each rank writes
its own `residual-r{rank}.npz` sidecar, restore rank r adopts save
rank r's residuals, and ranks beyond the save size start from zero —
exactly the survivor/joiner semantics of an elastic resize.

On-disk layout (one directory per generation)::

    <dir>/gen-00000007/
        shard-r0.bin       rank 0's spans of this generation's delta
        shard-r1.bin       ...
        residual-r0.npz    optional per-rank EF residual state
        manifest-r0.json   per-rank commit marker, written LAST
        manifest-r1.json   (atomic + fsynced; agreement checked on read)
"""

from __future__ import annotations

import errno
import json
import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from hashlib import blake2b
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from . import trace
from .checkpoint import _path_str, fsync_dir as _fsync_dir
from .env import env_float
from .ops.collective import shard_schedule
from .trace import metrics

#: v2 added the mandatory per-piece `shared_sum` self-checksum — a v1
#: generation is rejected as "unknown format" (restore falls back past
#: it), not misreported as tampered.
FORMAT = "kf-sharded-ckpt-v2"
GEN_PREFIX = "gen-"
#: default shard chunk size (MiB) — the same granularity trade-off as
#: the elastic streaming path; override with KF_CKPT_CHUNK_MB.
DEFAULT_CHUNK_MB = 4.0
#: leaves at or below this byte size are written every generation
#: regardless of hash — opt-state step counters and scalars change
#: every step anyway, and always-writing them keeps the newest
#: generation self-describing for the fast-moving state.
ALWAYS_WRITE_BYTES = 512


class CheckpointError(RuntimeError):
    """A generation could not be saved or restored."""


class CheckpointCorrupt(CheckpointError):
    """A generation exists but its bytes cannot be trusted: torn or
    missing shard, mismatched manifest pieces, or a leaf whose content
    hash disagrees with its manifest entry."""


def _gen_dir(directory: str, gen: int) -> str:
    return os.path.join(directory, f"{GEN_PREFIX}{gen:08d}")


def _manifest_path(gen_dir: str, rank: int) -> str:
    return os.path.join(gen_dir, f"manifest-r{rank}.json")


def _shard_path(gen_dir: str, rank: int) -> str:
    return os.path.join(gen_dir, f"shard-r{rank}.bin")


def _residual_path(gen_dir: str, rank: int) -> str:
    return os.path.join(gen_dir, f"residual-r{rank}.npz")


def _atomic_write(path: str, data: bytes) -> None:
    """Write-fsync-rename-fsync: after this returns, a power loss can
    not lose the file or leave a torn one at `path`."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


def _leaf_hash(view: np.ndarray) -> str:
    return blake2b(view, digest_size=16).hexdigest()


#: manifest fields every rank's piece must agree on — and that the
#: per-piece self-checksum covers, so a single-rank save (no cross-rank
#: agreement possible) is still tamper/tear-evident.
SHARED_FIELDS = ("format", "gen", "step", "nprocs", "chunk_bytes",
                 "keys", "shapes", "dtypes", "meta")


def _shared_sum(piece: Dict) -> str:
    """Checksum of a manifest piece's shared fields. Computed over the
    canonical JSON of the field VALUES, so it survives a JSON
    round-trip but changes if any shared field is edited in place
    (e.g. the chaos `mismatch_manifest` step bump)."""
    blob = json.dumps([piece.get(f) for f in SHARED_FIELDS],
                      sort_keys=True, separators=(",", ":")).encode()
    return blake2b(blob, digest_size=16).hexdigest()


def _dtype_from_name(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


class _Spec:
    """shape/dtype stand-in leaf for schedule recomputation at restore
    time (np.shape/np.dtype read the attributes; no allocation)."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape = tuple(shape)
        self.dtype = dtype


def tree_spec(tree) -> Tuple[List[str], List[Tuple], List[str], Any]:
    """(keys, shapes, dtype names, treedef) of a pytree in leaf order.

    Keys are the flat tree paths (`checkpoint._path_str`); dtypes come
    from leaf metadata without forcing a device->host transfer."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    keys, shapes, dtypes = [], [], []
    for path, leaf in flat:
        keys.append(_path_str(path))
        dt = getattr(leaf, "dtype", None)
        if dt is None:
            a = np.asarray(leaf)
            shapes.append(tuple(a.shape))
            dtypes.append(str(a.dtype))
        else:
            shapes.append(tuple(np.shape(leaf)))
            dtypes.append(str(np.dtype(dt)))
    if len(set(keys)) != len(keys):
        raise ValueError("duplicate flat keys in checkpoint tree")
    return keys, shapes, dtypes, treedef


def ckpt_chunk_bytes(chunk_mb: Optional[float] = None) -> int:
    """Resolve the shard chunk size in bytes: explicit argument, else
    KF_CKPT_CHUNK_MB (validated at parse time), else
    `DEFAULT_CHUNK_MB`."""
    if chunk_mb is None:
        chunk_mb = env_float("KF_CKPT_CHUNK_MB", DEFAULT_CHUNK_MB)
    if chunk_mb <= 0:
        raise ValueError(f"checkpoint chunk size must be positive: "
                         f"{chunk_mb} MiB")
    return max(1, int(chunk_mb * 2**20))


# -- manifests ---------------------------------------------------------------


class Manifest:
    """The merged, cross-checked view of one COMPLETE generation."""

    def __init__(self, directory: str, gen: int, step: int, nprocs: int,
                 chunk_bytes: int, keys: List[str],
                 shapes: List[Tuple], dtypes: List[str],
                 entries: Dict[str, Tuple[str, int]],
                 written_by_rank: List[List[str]],
                 residual_by_rank: List[bool], meta: Dict):
        self.directory = directory
        self.gen = gen
        self.step = step
        self.nprocs = nprocs
        self.chunk_bytes = chunk_bytes
        self.keys = keys
        self.shapes = shapes
        self.dtypes = dtypes
        #: key -> (content hash, owning generation)
        self.entries = entries
        self.written_by_rank = written_by_rank
        #: save-rank -> did that rank commit a residual sidecar
        self.residual_by_rank = residual_by_rank
        self.meta = meta

    @property
    def gen_dir(self) -> str:
        return _gen_dir(self.directory, self.gen)


def list_generations(directory: str) -> List[int]:
    """All generation numbers present on disk (complete or not), desc."""
    out = []
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return out
    for n in names:
        if n.startswith(GEN_PREFIX):
            try:
                out.append(int(n[len(GEN_PREFIX):]))
            except ValueError:
                continue
    return sorted(out, reverse=True)


def next_generation(directory: str) -> int:
    gens = list_generations(directory)
    return (gens[0] + 1) if gens else 1


def load_manifest(directory: str, gen: int) -> Manifest:
    """Load and merge every rank's manifest piece of one generation.

    Raises `CheckpointCorrupt` unless the generation is COMPLETE and
    internally consistent: every rank's piece present and agreeing on
    the shared fields, every shard file present at its recorded size,
    and no two ranks disagreeing on a shared leaf's hash (which would
    mean the save-time replicas had diverged)."""
    gen_dir = _gen_dir(directory, gen)
    try:
        with open(_manifest_path(gen_dir, 0)) as f:
            head = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorrupt(
            f"gen {gen}: rank-0 manifest unreadable: {e}") from e
    # valid JSON that is not an object (null, a number, an array) is
    # still a torn/tampered piece — reject before any .get() attribute
    # access can escape as AttributeError
    if not isinstance(head, dict):
        raise CheckpointCorrupt(
            f"gen {gen}: rank-0 manifest is not a JSON object")
    if head.get("format") != FORMAT:
        raise CheckpointCorrupt(
            f"gen {gen}: unknown format {head.get('format')!r}")
    # malformed fields must surface as corruption, not TypeError —
    # anything escaping CheckpointError here skips the fallback walk
    try:
        head_gen = int(head["gen"])
        nprocs = int(head["nprocs"])
    except (KeyError, TypeError, ValueError) as e:
        raise CheckpointCorrupt(
            f"gen {gen}: rank-0 manifest malformed: {e}") from e
    if head_gen != gen:
        raise CheckpointCorrupt(
            f"gen {gen}: rank-0 manifest claims gen {head_gen} — "
            "misplaced or tampered piece")
    entries: Dict[str, Tuple[str, int]] = {}
    written_by_rank: List[List[str]] = []
    residual_by_rank: List[bool] = []
    # the whole piece walk runs under one malformed-field net: a field
    # of the wrong type ANYWHERE (shard_bytes "abc", leaves as a list,
    # a leaf entry's gen null — the non-shared fields the checksum does
    # not cover) must surface as corruption, because anything escaping
    # CheckpointError skips the restore fallback walk and, multi-rank,
    # kills this rank before the ok-vote while peers wait in it
    try:
        for r in range(nprocs):
            if r == 0:
                piece = head
            else:
                try:
                    with open(_manifest_path(gen_dir, r)) as f:
                        piece = json.load(f)
                except (OSError, ValueError) as e:
                    raise CheckpointCorrupt(
                        f"gen {gen}: manifest piece for rank {r} "
                        f"missing/unreadable: {e}") from e
                for fld in SHARED_FIELDS:
                    if piece.get(fld) != head.get(fld):
                        raise CheckpointCorrupt(
                            f"gen {gen}: manifest pieces disagree on "
                            f"{fld!r} (rank 0 vs rank {r}) — refusing "
                            "a mixed restore")
            # self-checksum: the only agreement check a single-rank
            # save has, and a faster/tamper-proof one for multi-rank
            # pieces too (an edited-in-place shared field otherwise
            # only surfaces if some OTHER rank's piece still disagrees)
            if piece.get("shared_sum") != _shared_sum(piece):
                raise CheckpointCorrupt(
                    f"gen {gen}: manifest piece for rank {r} fails "
                    "its shared-field checksum — tampered or torn "
                    "piece")
            for key, ent in piece["leaves"].items():
                have = entries.get(key)
                want = (ent["hash"], int(ent["gen"]))
                if have is not None and have != want:
                    raise CheckpointCorrupt(
                        f"gen {gen}: ranks disagree on leaf {key!r} "
                        "(save-time replica divergence?) — refusing a "
                        "mixed restore")
                entries[key] = want
            written_by_rank.append(list(piece["written"]))
            residual_by_rank.append(bool(piece.get("residual", False)))
            shard = _shard_path(gen_dir, r)
            try:
                size = os.path.getsize(shard)
            except OSError as e:
                raise CheckpointCorrupt(
                    f"gen {gen}: shard file for rank {r} missing: {e}"
                ) from e
            if size != int(piece["shard_bytes"]):
                raise CheckpointCorrupt(
                    f"gen {gen}: torn shard for rank {r}: {size} "
                    f"bytes on disk, manifest says "
                    f"{piece['shard_bytes']}")
        missing = [k for k in head["keys"] if k not in entries]
        if missing:
            raise CheckpointCorrupt(
                f"gen {gen}: no rank owns leaves {missing[:3]}...")
        return Manifest(
            directory=directory, gen=gen, step=int(head["step"]),
            nprocs=nprocs, chunk_bytes=int(head["chunk_bytes"]),
            keys=list(head["keys"]),
            shapes=[tuple(s) for s in head["shapes"]],
            dtypes=list(head["dtypes"]), entries=entries,
            written_by_rank=written_by_rank,
            residual_by_rank=residual_by_rank,
            meta=dict(head.get("meta", {})))
    except (KeyError, TypeError, ValueError, AttributeError) as e:
        raise CheckpointCorrupt(
            f"gen {gen}: manifest malformed: {e}") from e


def complete_generations(directory: str) -> List[int]:
    """Generations that pass the completeness check, newest first.
    Incomplete/corrupt ones are skipped silently here — restore warns
    loudly when it has to FALL BACK past one."""
    out = []
    for g in list_generations(directory):
        try:
            load_manifest(directory, g)
        except CheckpointError:
            continue
        out.append(g)
    return out


def latest_manifest(directory: str) -> Optional[Manifest]:
    for g in list_generations(directory):
        try:
            return load_manifest(directory, g)
        except CheckpointError:
            continue
    return None


# -- save --------------------------------------------------------------------


def _host_view(leaf) -> np.ndarray:
    """Contiguous 1-D uint8 view of a leaf's host bytes (the writer-
    thread D2H for jax leaves; zero-copy for contiguous numpy)."""
    a = np.ascontiguousarray(np.asarray(leaf))
    return a.reshape(-1).view(np.uint8)


def _gen_format(gen_dir: str) -> Optional[str]:
    """The format string a generation directory's commit marker
    claims: the rank-0 manifest's "format" field, "" when the marker
    is MISSING (abandoned debris or a save still in flight), None when
    it exists but is unreadable or not a JSON object. One probe shared
    by the parking rule and GC so their notions of "ours" cannot
    drift (their policies on ""/None deliberately differ)."""
    try:
        with open(_manifest_path(gen_dir, 0)) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return ""
    except (OSError, ValueError):
        return None
    return doc.get("format") if isinstance(doc, dict) else None


def _park_foreign_generation(gen_dir: str) -> None:
    """Move aside a pre-existing generation directory whose manifest
    this format cannot claim (a pre-upgrade generation GC deliberately
    preserves). Generation numbers restart with a post-upgrade fresh
    init, so a later save hitting the same number would otherwise
    os.replace the very bytes the parking rule promises the operator.
    The `.parked` suffix drops the directory from `list_generations`,
    so restore/GC never see it again. A current-format directory is
    left in place (a recovery redo overwrites it on purpose), as is a
    directory with no commit marker (our own abandoned debris).

    Multi-rank collisions on a shared FS are racy by nature
    (check-then-rename): foreignness is re-probed immediately before
    EVERY rename attempt, so once a peer has parked the foreign dir
    and recreated a current-format one here, the fresh probe returns
    and cannot steal it — the residual window is the I/O-free gap
    between one probe and its rename, and even a lost race only costs
    one incomplete generation (caught by the completeness check; the
    foreign bytes themselves are already safely parked)."""
    for k in range(1000):
        if not os.path.isdir(gen_dir):
            return  # gone, or a squatting file: makedirs fails loudly
        fmt = _gen_format(gen_dir)
        if fmt == "" or fmt == FORMAT:
            return
        dst = f"{gen_dir}.parked" + (f".{k}" if k else "")
        try:
            os.rename(gen_dir, dst)
        except FileNotFoundError:
            return  # another rank parked it first
        except OSError as e:
            if e.errno in (errno.EEXIST, errno.ENOTEMPTY):
                continue  # dst taken (earlier parking): next suffix
            raise CheckpointError(
                f"cannot park foreign-format generation {gen_dir} "
                f"-> {dst}: {e}") from e
        print(f"[kf-ckpt] parked foreign-format generation "
              f"{gen_dir} -> {dst}", flush=True)
        return
    raise CheckpointError(
        f"cannot park foreign-format generation at {gen_dir}: "
        "out of .parked suffixes")


def write_generation(directory: str, gen: int, leaves: List,
                     keys: List[str], shapes: List[Tuple],
                     dtypes: List[str], *, step: int, rank: int,
                     nprocs: int, chunk_bytes: int,
                     incremental: bool = True,
                     prev_hashes: Optional[Dict[str, Tuple[str, int]]]
                     = None,
                     known_hashes: Optional[Dict[int, str]] = None,
                     meta: Optional[Dict] = None,
                     residual: Optional[Dict] = None) -> Dict:
    """Write THIS rank's shard + manifest piece of one generation.

    `leaves` may hold None at indices this rank owns no spans of (the
    snapshot only captures owned leaves). Pure filesystem protocol —
    no collectives; the manifest piece is this rank's commit marker
    and is written (atomically, fsynced) only after the shard and the
    residual sidecar are durable. `known_hashes` (leaf index -> hash)
    lets the caller vouch for leaves whose bytes provably did not
    change since the previous generation (the async front end's
    identity shortcut) — those leaves skip the hash pass AND the D2H
    entirely unless the always-write rule forces them out. Returns
    timing/volume info."""
    t0 = time.perf_counter()
    gen_dir = _gen_dir(directory, gen)
    _park_foreign_generation(gen_dir)
    os.makedirs(gen_dir, exist_ok=True)
    schedule = shard_schedule(
        [_Spec(s, _dtype_from_name(d)) for s, d in zip(shapes, dtypes)],
        chunk_bytes, nprocs)
    my_chunks = [spans for owner, spans in schedule if owner == rank]
    owned = {i for spans in my_chunks for i, _, _ in spans}
    nbytes = [int(np.prod(s, dtype=np.int64))
              * _dtype_from_name(d).itemsize
              for s, d in zip(shapes, dtypes)]
    # zero-size leaves have no spans and therefore no schedule owner:
    # EVERY rank records their (trivial) entry so the manifest merge
    # still covers each leaf
    zero = {i for i, n in enumerate(nbytes) if n == 0}
    owned = sorted(owned | zero)
    views: Dict[int, np.ndarray] = {}

    def view(i: int) -> np.ndarray:
        v = views.get(i)
        if v is None:
            if leaves[i] is None:
                if i in zero:
                    v = np.zeros(0, np.uint8)
                else:
                    raise CheckpointError(
                        f"rank {rank} owns spans of leaf "
                        f"{keys[i]!r} but the snapshot did not "
                        "capture it")
            else:
                v = _host_view(leaves[i])
            views[i] = v
        return v

    t_host = time.perf_counter()

    # per-leaf content hashes decide the delta; tiny leaves are always
    # written. Replicas are bit-identical under S-SGD, so every rank
    # owning spans of a leaf reaches the same decision from its own
    # bytes — the manifest merge cross-checks exactly that.
    entries: Dict[str, Dict] = {}
    written: List[str] = []
    prev_hashes = prev_hashes or {}
    known_hashes = known_hashes or {}
    with trace.span("ckpt.hash", cat="ckpt", gen=gen):
        for i in owned:
            h = known_hashes.get(i)
            if h is None or nbytes[i] <= ALWAYS_WRITE_BYTES:
                h = _leaf_hash(view(i))
            prev = prev_hashes.get(keys[i])
            if prev is not None and prev[1] >= gen:
                # re-writing an existing generation (a recovery
                # redoing the step it lost): the chain entry points
                # at the very bytes the os.replace below destroys, so
                # honoring it would mark the leaf not-fresh while
                # deleting its only copy — and GC could then drop the
                # older generations that still hold real bytes. Force
                # fresh. (save_sharded filters whole manifests with
                # `g < gen`; this per-entry guard covers the async
                # front end's live chain too.)
                prev = None
            fresh = (not incremental or prev is None or prev[0] != h
                     or nbytes[i] <= ALWAYS_WRITE_BYTES)
            entries[keys[i]] = {
                "hash": h, "gen": gen if fresh else prev[1]}
            if fresh:
                written.append(keys[i])
        written_set = set(written)
    t_hash = time.perf_counter()

    shard = _shard_path(gen_dir, rank)
    tmp = shard + ".tmp"
    shard_bytes = 0
    with trace.span("ckpt.write", cat="ckpt", gen=gen) as sp_write:
        with open(tmp, "wb") as f:
            for spans in my_chunks:
                for i, off, nb in spans:
                    if keys[i] in written_set:
                        f.write(view(i)[off:off + nb])
                        shard_bytes += nb
            f.flush()
            with trace.span("ckpt.fsync", cat="ckpt", gen=gen):
                os.fsync(f.fileno())
        os.replace(tmp, shard)
        sp_write.set(bytes=shard_bytes)

    if residual is not None:
        payload: Dict[str, np.ndarray] = {
            "compression": np.asarray(residual.get("compression",
                                                   "none"))}
        for k, r in enumerate(residual.get("residual", [])):
            payload[f"res_{k}"] = np.asarray(r)
        rtmp = _residual_path(gen_dir, rank) + ".tmp"
        with open(rtmp, "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(rtmp, _residual_path(gen_dir, rank))
    else:
        # a redo of this generation may run WITHOUT the gradient
        # pipeline (relaunch with compression off): the first
        # attempt's sidecar must not survive it — restore loads
        # residuals by existence, and a stale one would hand a later
        # cluster error-feedback state that never matched these
        # weights
        try:
            os.unlink(_residual_path(gen_dir, rank))
        except FileNotFoundError:
            pass
    t_write = time.perf_counter()

    piece = {
        "format": FORMAT, "gen": gen, "step": int(step),
        "nprocs": nprocs, "chunk_bytes": int(chunk_bytes),
        "keys": keys, "shapes": [list(s) for s in shapes],
        "dtypes": dtypes, "meta": dict(meta or {}),
        "rank": rank, "leaves": entries, "written": written,
        "shard_bytes": shard_bytes,
        "residual": residual is not None,
    }
    # compute the checksum over the JSON round-trip of the values so
    # load-time recomputation sees identical types (tuples -> lists)
    piece = json.loads(json.dumps(piece))
    piece["shared_sum"] = _shared_sum(piece)
    with trace.span("ckpt.commit", cat="ckpt", gen=gen):
        _atomic_write(_manifest_path(gen_dir, rank),
                      json.dumps(piece).encode())
    t_done = time.perf_counter()
    return {
        "piece": piece,  # callers chain deltas without re-parsing it
        "gen": gen, "rank": rank,
        "host_ms": (t_host - t0) * 1e3,
        "hash_ms": (t_hash - t_host) * 1e3,
        "write_ms": (t_write - t_hash) * 1e3,
        "commit_ms": (t_done - t_write) * 1e3,
        "wall_ms": (t_done - t0) * 1e3,
        "bytes_written": shard_bytes,
        "leaves_written": len(written),
        "leaves_skipped": len(owned) - len(written),
    }


def save_sharded(directory: str, tree, *, step: int, rank: int = 0,
                 nprocs: int = 1, chunk_bytes: Optional[int] = None,
                 incremental: bool = True, gen: Optional[int] = None,
                 meta: Optional[Dict] = None,
                 residual: Optional[Dict] = None,
                 mesh_axes: Optional[Dict] = None) -> int:
    """Synchronously write this rank's shard of one generation.

    The blocking convenience form (tests, benchmarks, one-shot tools);
    training loops should use `AsyncShardedCheckpointer`. When saving
    from several ranks, derive `gen` ONCE (e.g. `next_generation`) and
    pass the same value to every rank. Returns the generation.

    ``mesh_axes`` (e.g. ``dict(mesh.shape)``) records the mesh shape
    the tree was planned for into ``meta["mesh_axes"]`` — what
    `restore_on_mesh` diffs the restore-side plan against. Omit it
    for layouts with no mesh (worker-stacked DP state) and the
    restore diff conservatively reports every sharded leaf."""
    os.makedirs(directory, exist_ok=True)
    if mesh_axes is not None:
        meta = {**(meta or {}), "mesh_axes": dict(mesh_axes)}
    if chunk_bytes is None:
        chunk_bytes = ckpt_chunk_bytes()
    if gen is None:
        gen = next_generation(directory)
    keys, shapes, dtypes, _ = tree_spec(tree)
    prev = None
    if incremental:
        for g in complete_generations(directory):
            if g < gen:
                prev = load_manifest(directory, g)
                break
        if prev is not None and (prev.keys != keys
                                 or prev.shapes != shapes
                                 or prev.dtypes != dtypes):
            prev = None  # tree changed spec: restart a full chain
    write_generation(
        directory, gen, jax.tree_util.tree_leaves(tree), keys, shapes,
        dtypes, step=step, rank=rank, nprocs=nprocs,
        chunk_bytes=chunk_bytes, incremental=incremental,
        prev_hashes=prev.entries if prev is not None else None,
        meta=meta, residual=residual)
    return gen


# -- restore -----------------------------------------------------------------


def _source_locations(manifest: Manifest, source_gen: int,
                      nbytes_by_key: Dict[str, int]
                      ) -> Dict[str, List[Tuple[int, int, int, int]]]:
    """Replay generation `source_gen`'s write layout: for every leaf
    whose bytes the CURRENT manifest attributes to `source_gen`, the
    disk segments ``(leaf_off, nb, shard_rank, file_off)`` covering it.

    Deterministic from the source manifest alone: the save-side
    schedule is recomputed shape-only and walked in write order."""
    src = (manifest if source_gen == manifest.gen
           else load_manifest(manifest.directory, source_gen))
    if src.keys != manifest.keys or src.shapes != manifest.shapes \
            or src.dtypes != manifest.dtypes:
        raise CheckpointCorrupt(
            f"gen {source_gen}: tree spec drifted from gen "
            f"{manifest.gen} that references it")
    specs = [_Spec(s, _dtype_from_name(d))
             for s, d in zip(src.shapes, src.dtypes)]
    schedule = shard_schedule(specs, src.chunk_bytes, src.nprocs)
    written_sets = [set(w) for w in src.written_by_rank]
    wanted = {k for k, (_, g) in manifest.entries.items()
              if g == source_gen}
    file_off = [0] * src.nprocs
    locs: Dict[str, List[Tuple[int, int, int, int]]] = {}
    for owner, spans in schedule:
        for i, off, nb in spans:
            key = src.keys[i]
            if key not in written_sets[owner]:
                continue
            if key in wanted:
                locs.setdefault(key, []).append(
                    (off, nb, owner, file_off[owner]))
            file_off[owner] += nb
    for key in wanted:
        have = sum(nb for _, nb, _, _ in locs.get(key, []))
        want = nbytes_by_key[key]
        if have != want:
            raise CheckpointCorrupt(
                f"gen {source_gen}: leaf {key!r} bytes incomplete on "
                f"disk ({have} of {want}) — manifest chain is "
                "inconsistent")
    return locs


def _read_my_spans(manifest: Manifest, views: List[np.ndarray],
                   restore_schedule, rank: int) -> int:
    """Fill this rank's restore spans straight from the owning
    generations' shard files (seek + readinto the leaf views — no
    staging buffer). Returns bytes read."""
    keys = manifest.keys
    nbytes_by_key = {k: views[i].size for i, k in enumerate(keys)}
    source_gens = sorted({g for _, g in manifest.entries.values()})
    locs: Dict[str, List[Tuple[int, int, int, int]]] = {}
    for g in source_gens:
        locs.update(_source_locations(manifest, g, nbytes_by_key))
    gen_of = {k: g for k, (_, g) in manifest.entries.items()}
    handles: Dict[Tuple[int, int], Any] = {}
    total = 0
    try:
        for owner, spans in restore_schedule:
            if owner != rank:
                continue
            for i, off, nb in spans:
                key = keys[i]
                src_gen = gen_of[key]
                for loff, lnb, srank, foff in locs[key]:
                    s = max(off, loff)
                    e = min(off + nb, loff + lnb)
                    if s >= e:
                        continue
                    hk = (src_gen, srank)
                    f = handles.get(hk)
                    if f is None:
                        path = _shard_path(
                            _gen_dir(manifest.directory, src_gen),
                            srank)
                        try:
                            f = handles[hk] = open(path, "rb")
                        except OSError as exc:
                            raise CheckpointCorrupt(
                                f"gen {src_gen}: shard for rank "
                                f"{srank} unreadable: {exc}") from exc
                    f.seek(foff + (s - loff))
                    mv = memoryview(views[i][s:e])
                    while mv:
                        n = f.readinto(mv)
                        if not n:
                            raise CheckpointCorrupt(
                                f"gen {src_gen}: shard for rank "
                                f"{srank} truncated reading "
                                f"{key!r}")
                        mv = mv[n:]
                    total += e - s
    finally:
        for f in handles.values():
            f.close()
    return total


def _exchange_chunks(peer, views: List[np.ndarray], restore_schedule,
                     name: str) -> None:
    """Re-shard over DCN: every restore chunk broadcast in place from
    its owning rank, pipelined on one executor thread (the elastic
    streaming pattern — single-span chunks are pure views end to end,
    the small-leaf tail passes through a bounded scratch)."""
    rank = peer.rank
    pending: deque = deque()

    def pop_one():
        fut, owner, scratch, spans = pending.popleft()
        fut.result()
        if owner != rank and scratch is not None:
            o = 0
            for i, off, nb in spans:
                views[i][off:off + nb] = scratch[o:o + nb]
                o += nb

    ex = ThreadPoolExecutor(max_workers=1,
                            thread_name_prefix="kf-ckpt-restore")
    try:
        for ci, (owner, spans) in enumerate(restore_schedule):
            if len(spans) == 1:
                i, off, nb = spans[0]
                buf, scratch = views[i][off:off + nb], None
            else:
                if owner == rank:
                    scratch = np.concatenate(
                        [views[i][off:off + nb]
                         for i, off, nb in spans])
                else:
                    scratch = np.empty(sum(s[2] for s in spans),
                                       np.uint8)
                buf = scratch
            pending.append((
                ex.submit(peer.broadcast_inplace, buf, owner,
                          f"{name}:c{ci}"),
                owner, scratch, spans))
            while pending and pending[0][0].done():
                pop_one()
            while len(pending) > 3:
                pop_one()
        while pending:
            pop_one()
    finally:
        ex.shutdown(wait=True)


def _load_residual(gen_dir: str, rank: int) -> Optional[Dict]:
    path = _residual_path(gen_dir, rank)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            res = []
            k = 0
            while f"res_{k}" in z.files:
                res.append(z[f"res_{k}"])
                k += 1
            return {"compression": str(z["compression"]),
                    "residual": res}
    # numpy's zip stack raises module-private error types (zlib.error,
    # BadZipFile, ValueError); anything here means the sidecar is
    # unreadable — re-raise as corruption so the caller falls back a
    # generation rather than training on a garbled residual
    except Exception as e:
        raise CheckpointCorrupt(
            f"residual sidecar {path} unreadable: {e}") from e


def _attempt_generation(directory: str, gen: int, like, rank: int,
                        nprocs: int
                        ) -> Tuple[Manifest, List, List[np.ndarray],
                                   Any, Optional[Dict]]:
    """Local (collective-free) half of a restore attempt: manifest
    load, template validation, host buffers, this rank's disk reads,
    residual sidecar. Raises CheckpointError on anything untrustworthy
    — BEFORE any wire op, so a multi-peer restore can agree to fall
    back without deadlocking."""
    manifest = load_manifest(directory, gen)
    keys, shapes, dtypes, treedef = tree_spec(like)
    if keys != manifest.keys:
        raise CheckpointError(
            f"gen {gen}: template tree has different leaves than the "
            f"checkpoint (e.g. {next(iter(set(keys) ^ set(manifest.keys)), '?')!r})")
    if shapes != manifest.shapes or dtypes != manifest.dtypes:
        bad = [k for k, s, d, ms, md in zip(
            keys, shapes, dtypes, manifest.shapes, manifest.dtypes)
            if s != ms or d != md]
        raise CheckpointError(
            f"gen {gen}: shape/dtype mismatch vs template for "
            f"{bad[:3]}")
    host = [np.empty(s, dtype=_dtype_from_name(d))
            for s, d in zip(shapes, dtypes)]
    views = [h.reshape(-1).view(np.uint8) for h in host]
    specs = [_Spec(s, _dtype_from_name(d))
             for s, d in zip(shapes, dtypes)]
    restore_schedule = shard_schedule(specs, manifest.chunk_bytes,
                                      nprocs)
    _read_my_spans(manifest, views, restore_schedule, rank)
    residual = _load_residual(manifest.gen_dir, rank)
    # cross-check the sidecar against the manifest's commitment: a
    # crash between a redo's sidecar unlink and its manifest commit
    # leaves a residual:true piece with no sidecar (silent EF-state
    # loss without this check), and the reverse — a sidecar surviving
    # from an aborted earlier attempt a residual:false redo committed
    # over — would hand back state that never matched these weights
    promised = (manifest.residual_by_rank[rank]
                if rank < len(manifest.residual_by_rank) else False)
    if promised and residual is None:
        raise CheckpointCorrupt(
            f"gen {gen}: manifest promises a residual sidecar for "
            f"rank {rank} but none is on disk")
    if residual is not None and not promised:
        residual = None  # stale sidecar the manifest does not claim
    return manifest, host, views, (treedef, restore_schedule), residual


def _verify(manifest: Manifest, views: List[np.ndarray]) -> None:
    bad = [k for k, v in zip(manifest.keys, views)
           if _leaf_hash(v) != manifest.entries[k][0]]
    if bad:
        raise CheckpointCorrupt(
            f"gen {manifest.gen}: content hash mismatch for "
            f"{bad[:3]} ({len(bad)} leaves) — torn or corrupted "
            "shard data")


def restore_sharded(directory: str, like, *, peer=None,
                    gen: Optional[int] = None):
    """Restore the latest complete generation, re-sharded to the
    CURRENT cluster.

    `like` is a pytree with the target structure/shapes/dtypes (e.g.
    fresh-initialized params+opt). With a `peer` of size > 1 every
    rank reads exactly its spans of the restore-side `shard_schedule`
    from the owning generations' shard files and the chunks are
    exchanged as pipelined in-place broadcasts — the save-time np and
    the restore-time np are independent. Leaves come back as jax
    arrays where the template leaf was jax, numpy otherwise (the
    streaming discipline).

    Every leaf is hash-verified against the manifest before anything
    is returned. A generation that fails ANY check — incomplete
    manifest set, mismatched pieces, torn/missing shard, hash mismatch
    — is reported loudly and restore falls back to the previous
    complete generation (all ranks fall back together: attempts are
    agreed via a rank-0 pick broadcast plus an ok-vote all-reduce, so
    no rank can return state from a generation another rank rejected).
    Raises `CheckpointError` when no generation survives.

    Returns ``(tree, step, meta, residual)`` — `residual` is this
    rank's `GradBucketPipeline.state()` sidecar or None (ranks beyond
    the save size, or uncompressed runs, start from zero — the PR 5
    joiner semantics)."""
    multi = peer is not None and peer.size > 1
    rank = peer.rank if peer is not None else 0
    nprocs = peer.size if peer is not None else 1
    # walk EVERY generation on disk, newest first: an incomplete or
    # corrupt one is rejected loudly inside the attempt (so the
    # operator sees exactly what was skipped), not filtered silently
    candidates = [gen] if gen is not None \
        else list_generations(directory)
    errors: List[str] = []
    attempt = 0
    while True:
        if multi:
            # rank 0 drives the fallback walk so every rank attempts
            # the SAME generation (local completeness scans could
            # transiently disagree under concurrent saves)
            pick = np.array(
                [candidates[attempt] if attempt < len(candidates)
                 else -1], np.int64)
            pick = peer.broadcast(pick, root=0,
                                  name=f"kf::ckpt::pick:{attempt}")
            g = int(pick[0])
        else:
            g = candidates[attempt] if attempt < len(candidates) else -1
        if g < 0:
            raise CheckpointError(
                f"no restorable checkpoint generation under "
                f"{directory!r}"
                + (f" (rejected: {'; '.join(errors)})" if errors
                   else " (none complete)"))
        manifest = host = views = aux = residual = None
        try:
            manifest, host, views, aux, residual = \
                _attempt_generation(directory, g, like, rank, nprocs)
            ok = 1
        except CheckpointError as e:
            errors.append(f"gen {g}: {e}")
            print(f"[kf-ckpt] restore: generation {g} rejected "
                  f"({e}); falling back", flush=True)
            ok = 0
        if multi:
            # unanimity vote BEFORE the exchange: a rank that failed
            # locally must not be waited on in the chunk broadcasts
            agreed = peer.all_reduce(np.array([ok], np.int64),
                                     op="min",
                                     name=f"kf::ckpt::ok:{attempt}")
            ok = int(agreed[0])
        if ok:
            treedef, restore_schedule = aux
            if multi:
                _exchange_chunks(peer, views, restore_schedule,
                                 f"kf::ckpt::restore:g{g}")
            try:
                _verify(manifest, views)
                ok = 1
            except CheckpointCorrupt as e:
                errors.append(str(e))
                print(f"[kf-ckpt] restore: {e}; falling back",
                      flush=True)
                ok = 0
            if multi:
                agreed = peer.all_reduce(
                    np.array([ok], np.int64), op="min",
                    name=f"kf::ckpt::verify:{attempt}")
                ok = int(agreed[0])
            if ok:
                import jax.numpy as jnp

                leaves = jax.tree_util.tree_leaves(like)
                out = [jnp.asarray(h) if isinstance(l, jax.Array)
                       else h for l, h in zip(leaves, host)]
                return (jax.tree_util.tree_unflatten(treedef, out),
                        manifest.step, manifest.meta, residual)
        attempt += 1


def restore_on_mesh(directory: str, like, *, mesh, rules_table,
                    peer=None, gen: Optional[int] = None):
    """Restore the latest complete generation and PLACE it on ``mesh``
    per a kfspec rules table — reshard-on-restore generalized from
    "any np" to "any mesh shape" (ROADMAP item 3: a checkpoint saved
    on a dp x tp mesh restores onto a tp x pp one).

    The byte plane is :func:`restore_sharded` unchanged (any-np shard
    exchange, every leaf hash-verified, lockstep fallback). On top of
    it the placement plane is pure kfspec data: the table derives the
    spec tree for the RESTORE mesh and validates it at plan time
    (coverage, axis existence, divisibility — :class:`~kungfu_tpu
    .parallel.rules.PlanError` before any device_put), then the
    spec-diff against the SAVE mesh shape (``meta["mesh_axes"]``,
    recorded by passing ``mesh_axes=dict(mesh.shape)`` to the saver)
    says exactly which leaves' byte layouts moved; ``place``
    device_puts per spec (a leaf whose placement signature is
    unchanged costs a device map update, not a reshuffle). Because
    both sides derive placement from the same table, the two clusters
    never exchange specs — the schedule-only discipline
    chunk/bucket/shard_schedule established.

    Returns ``(placed_tree, step, meta, residual, diff)`` where
    ``diff`` is ``{leaf path: (save signature, restore signature)}``
    for the moved leaves. When ``meta`` carries no ``mesh_axes`` (the
    saver didn't know its mesh, e.g. worker-stacked DP state) the
    save layout is unknown and the diff is computed against a
    fully-replicated prior — every sharded leaf reports as moved, the
    conservative reading."""
    from .parallel import rules as kfspec

    tree, step, meta, residual = restore_sharded(directory, like,
                                                 peer=peer, gen=gen)
    mesh_shape = dict(mesh.shape)
    specs = kfspec.plan(rules_table, tree, mesh_shape)
    saved_axes = dict((meta or {}).get("mesh_axes") or {})
    diff = kfspec.spec_diff(specs, tree, saved_axes, mesh_shape)
    return (kfspec.place(tree, mesh, specs), step, meta, residual,
            diff)


# -- the async front end ------------------------------------------------------


class AsyncShardedCheckpointer:
    """Overlap sharded incremental saves with the training loop.

    ::

        ckpt = AsyncShardedCheckpointer(dir_, peer)
        ...
        ckpt.save((params, opt_state), step=elastic.state.step,
                  residual=pipe.state() if pipe else None)
        ...
        ckpt.close()    # drain pending writes

    `save()` returns after capturing a snapshot: jax leaves by
    reference (immutable — the writer thread pays the per-leaf D2H,
    which JAX async dispatch hides behind the next steps), writeable
    numpy leaves this rank owns spans of by copy. Hashing, span
    writes, fsync and the manifest commit all run on the executor
    thread. At most `max_pending` snapshots may be in flight (the
    double buffer); further saves block on the oldest write.

    NOT compatible with buffer donation of the checkpointed arrays
    (`donate_argnums` over params/opt): a donated jax buffer may be
    reused before the writer thread reads it — pass `snapshot="copy"`
    to force eager copies in that case.

    Write errors surface at the NEXT `save()`/`wait()`/`close()`
    rather than crashing the step that queued them.
    """

    def __init__(self, directory: str, peer=None, *,
                 chunk_bytes: Optional[int] = None,
                 incremental: bool = True, keep: int = 3,
                 max_pending: int = 2, snapshot: str = "auto"):
        if snapshot not in ("auto", "copy"):
            raise ValueError(f"snapshot={snapshot!r} must be "
                             "'auto' or 'copy'")
        self.directory = directory
        self.peer = peer
        self.rank = peer.rank if peer is not None else 0
        self.nprocs = peer.size if peer is not None else 1
        # init-time env read: rank-uniform via the launcher's
        # CONFIG_VARS forwarding, fixed for the object's lifetime
        self.chunk_bytes = (ckpt_chunk_bytes() if chunk_bytes is None
                            else int(chunk_bytes))
        self.incremental = incremental
        self.keep = max(1, keep)
        self.snapshot = snapshot
        os.makedirs(directory, exist_ok=True)
        # -- delta-chain state: writer-thread-owned after __init__.
        # _hashes/_id_hash/_prev_snap/_chain_spec are read and mutated
        # ONLY inside _job (plus here, before the pool exists); the
        # single-worker executor serializes jobs in submit order, so
        # no lock is needed and a spec change applied by job N can
        # never be clobbered by a still-in-flight job N-1 — the
        # reset happens on the same thread, after N-1 fully landed.
        prev = latest_manifest(directory)
        if prev is not None:
            self._hashes: Dict[str, Tuple[str, int]] = dict(
                prev.entries)
            self._chain_spec: Optional[Tuple] = (
                list(prev.keys), list(prev.shapes),
                list(prev.dtypes))
        else:
            self._hashes = {}
            self._chain_spec = None
        # -- owned-indices cache: training-thread-owned (save() only)
        self._owned: Optional[set] = None
        self._sched_spec: Optional[Tuple] = None
        # identity shortcut: key -> (id of the leaf object the hash
        # was computed from, hash). Valid ONLY because _prev_snap
        # keeps those exact objects alive — a freed object's id could
        # be recycled onto different bytes. jax arrays only (numpy is
        # mutable in place, so identity proves nothing there).
        self._id_hash: Dict[str, Tuple[int, str]] = {}
        self._prev_snap: Optional[List] = None
        self._sem = threading.Semaphore(max(1, max_pending))
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="kf-ckpt")
        self._pending: List = []
        self._mu = threading.Lock()
        self._errors: List[BaseException] = []  # kf: guarded_by(_mu)
        #: timings/volume of the most recent completed write (benign
        #: racy read: written only on the writer thread)
        self.last_save_info: Dict = {}

    # -- snapshot (training thread) ------------------------------------------

    def _owned_indices(self, keys, shapes, dtypes) -> set:
        spec = (keys, shapes, dtypes)
        if self._owned is None or self._sched_spec != spec:
            specs = [_Spec(s, _dtype_from_name(d))
                     for s, d in zip(shapes, dtypes)]
            schedule = shard_schedule(specs, self.chunk_bytes,
                                      self.nprocs)
            self._owned = {i for owner, spans in schedule
                           if owner == self.rank
                           for i, _, _ in spans}
            self._sched_spec = spec
        return self._owned

    def save(self, tree, step: int, *, meta: Optional[Dict] = None,
             residual: Optional[Dict] = None,
             mesh_axes: Optional[Dict] = None,
             block: bool = False) -> int:
        """Queue one generation; returns its number immediately (or
        after the write with `block=True`). Raises any error a
        PREVIOUS queued write hit.

        ``mesh_axes`` (e.g. ``dict(mesh.shape)``) records the mesh
        shape the tree was planned for into ``meta["mesh_axes"]`` —
        the save-side half of `restore_on_mesh`'s spec diff.

        The generation number IS `step` (which must be the
        cluster-agreed training step, >= 1): no local counter exists
        to drift, so a joiner's fresh checkpointer and the survivors'
        long-lived ones name the same generation by construction even
        while earlier generations are still being written in the
        background on other ranks — the same agreed-step rule the
        gradient pipeline's wire names follow. Re-saving the SAME
        step (a recovery redoing the step it lost) overwrites this
        rank's piece of that generation in place, which converges."""
        self._raise_pending_errors()
        if step < 1:
            raise ValueError(
                f"save() needs the cluster-agreed step >= 1, got "
                f"{step} — generation numbers derive from it")
        if mesh_axes is not None:
            meta = {**(meta or {}), "mesh_axes": dict(mesh_axes)}
        keys, shapes, dtypes, _ = tree_spec(tree)
        owned = self._owned_indices(keys, shapes, dtypes)
        leaves = jax.tree_util.tree_leaves(tree)
        snap: List = [None] * len(leaves)
        # the only save work the TRAINING thread pays: reference
        # capture / owned-numpy copies (everything else runs on the
        # writer thread, as the ckpt.save span tree shows)
        with trace.span("ckpt.snapshot", cat="ckpt", gen=int(step)):
            for i in owned:
                l = leaves[i]
                if isinstance(l, np.ndarray):
                    # a trainer may mutate numpy in place
                    snap[i] = l.copy()
                elif self.snapshot == "copy":
                    snap[i] = np.array(np.asarray(l), copy=True)
                else:
                    snap[i] = l  # immutable: writer pays the D2H
        gen = int(step)
        self._sem.acquire()  # backpressure: double buffer only
        fut = self._pool.submit(self._job, gen, snap, keys, shapes,
                                dtypes, step, meta, residual)
        self._pending.append(fut)
        # /metrics backpressure depth: generations queued behind the
        # double buffer right now (writer-thread lag indicator)
        metrics.REGISTRY.set(
            "kf_ckpt_pending",
            sum(1 for f in self._pending if not f.done()))
        if block:
            self.wait()
        return gen

    # -- writer thread --------------------------------------------------------

    def _job(self, gen, snap, keys, shapes, dtypes, step, meta,
             residual):
        sp = trace.span("ckpt.save", cat="ckpt", gen=gen)
        sp.__enter__()
        try:
            spec = (keys, shapes, dtypes)
            if self._chain_spec is not None \
                    and self._chain_spec != spec:
                # tree changed spec (keys OR shapes OR dtypes) vs the
                # chain so far: restart a full chain — chaining a
                # reshaped leaf to old generations would save fine but
                # never restore (the spec-drift check rejects it).
                # Applied HERE, on the writer thread, so an in-flight
                # old-spec job (which repopulates the chain state when
                # it lands) has fully landed before the reset — the
                # training thread clearing these dicts could race a
                # pending write refilling them with pre-restart gens.
                self._hashes = {}
                self._id_hash = {}
                self._prev_snap = None
            self._chain_spec = spec
            # identity shortcut: an owned jax leaf that is the SAME
            # object the previous generation hashed cannot have
            # different bytes (immutable, and _prev_snap keeps it
            # alive so the id is not recycled) — vouch for its hash
            # and skip both the D2H and the hash pass
            known: Dict[int, str] = {}
            if self.incremental:
                for i, l in enumerate(snap):
                    if l is None or isinstance(l, np.ndarray):
                        continue
                    rec = self._id_hash.get(keys[i])
                    if rec is not None and rec[0] == id(l):
                        known[i] = rec[1]
            info = write_generation(
                self.directory, gen, snap, keys, shapes, dtypes,
                step=step, rank=self.rank, nprocs=self.nprocs,
                chunk_bytes=self.chunk_bytes,
                incremental=self.incremental,
                prev_hashes=self._hashes, known_hashes=known,
                meta=meta, residual=residual)
            # adopt this generation's ownership for the next delta
            piece = info.pop("piece")
            for key, ent in piece["leaves"].items():
                self._hashes[key] = (ent["hash"], int(ent["gen"]))
            id_hash: Dict[str, Tuple[int, str]] = {}
            for i, l in enumerate(snap):
                if l is None or isinstance(l, np.ndarray):
                    continue
                ent = piece["leaves"].get(keys[i])
                if ent is not None:
                    id_hash[keys[i]] = (id(l), ent["hash"])
            self._id_hash = id_hash
            self._prev_snap = snap  # pins the ids in _id_hash
            if self.rank == 0:
                self._gc()
            self.last_save_info = info
        # the writer thread must never die silently — ANY failure is
        # recorded and re-raised at the next save()/wait()/close(); a
        # lost writer error would silently disable durability
        # kflint: disable=retry-discipline
        except BaseException as e:
            with self._mu:
                self._errors.append(e)
        finally:
            sp.__exit__(None, None, None)
            metrics.REGISTRY.set(
                "kf_ckpt_pending",
                sum(1 for f in self._pending if not f.done()))
            self._sem.release()

    def _gc(self) -> None:
        """Drop generations no retained manifest references. Runs on
        rank 0's writer thread only; never touches the newest `keep`
        complete generations or anything they chain to."""
        complete = complete_generations(self.directory)
        keep_list = complete[:self.keep]
        if not keep_list:
            return
        referenced = set(keep_list)
        for g in keep_list:
            try:
                m = load_manifest(self.directory, g)
            except CheckpointError:
                return  # racing writer: be conservative, skip GC
            referenced.update(og for _, og in m.entries.values())
        floor = min(keep_list)
        import shutil

        for g in list_generations(self.directory):
            if g >= floor or g in referenced:
                continue
            # never delete bytes GC cannot attribute to THIS format's
            # chain: a pre-upgrade (e.g. v1) generation would restore
            # nowhere after a silent fresh init, and rmtree'ing it
            # here would turn that regression into permanent loss.
            # A missing commit marker ("") is our own abandoned debris
            # (crashed mid-save) and stays collectable; an unreadable
            # or foreign-format manifest makes GC LEAVE the directory
            # for the operator (restore already rejects it loudly;
            # write_generation moves it to a .parked name only if a
            # new save collides with its number).
            fmt = _gen_format(_gen_dir(self.directory, g))
            if fmt not in ("", FORMAT):
                continue
            shutil.rmtree(_gen_dir(self.directory, g),
                          ignore_errors=True)

    # -- lifecycle ------------------------------------------------------------

    def _raise_pending_errors(self) -> None:
        with self._mu:
            if self._errors:
                e = self._errors[0]
                self._errors.clear()
                raise CheckpointError(
                    f"async checkpoint write failed: {e}") from e

    def wait(self) -> None:
        """Block until every queued generation is durable."""
        pending, self._pending = self._pending, []
        for f in pending:
            f.result()
        self._raise_pending_errors()

    def close(self) -> None:
        try:
            self.wait()
        finally:
            self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
