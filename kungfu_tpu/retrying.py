"""Unified control-plane retry policy: backoff + deadline + taxonomy.

Every control-plane HTTP/urlopen call in the runtime (config-server
fetch/put, elastic propose, HTTP self-resolve) goes through one policy
object instead of its own ad-hoc ``except Exception: retry later``. The
policy gives three things the ad-hoc forms lacked:

- an **error taxonomy**: transient faults (connection refused/reset,
  timeouts, HTTP 5xx/408/429, config server not yet seeded) are retried;
  permanent ones (malformed JSON, HTTP 4xx, bad URLs) surface
  immediately instead of burning the whole retry budget on an error that
  can never heal;
- **jittered exponential backoff** with a delay cap, so a restarting
  config server sees a spread-out trickle instead of a synchronized
  stampede from every worker at once;
- a **deadline**, so a recovery path blocked on a dead dependency fails
  fast enough for the caller's own fallback (e.g. the watcher's
  fail-fast) to still be useful.

The reference handles these with Go-side url.go retry loops and fixed
sleeps; this module is the single Python-side equivalent.
"""

from __future__ import annotations

import errno
import random
import time
import urllib.error
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional, TypeVar

T = TypeVar("T")

# HTTP statuses worth retrying: server-side failures, timeout, throttle,
# plus 404 — the config server replies 404 /get until it is seeded, and
# callers poll exactly that window.
_TRANSIENT_HTTP = {404, 408, 429, 500, 502, 503, 504}

# OSError shapes that no amount of waiting heals within a retry budget:
# a full disk (ENOSPC), a read-only remount (EROFS — the kernel's
# response to a dying device), a blown quota (EDQUOT). Retrying these
# burns the whole deadline and then fails with a misleading timeout; the
# caller (e.g. the WAL's fail-fast path) needs the real errno NOW.
_PERMANENT_ERRNO = frozenset(
    e for e in (errno.ENOSPC, errno.EROFS,
                getattr(errno, "EDQUOT", None)) if e is not None)


def _permanent_os_error(exc: BaseException) -> bool:
    # URLError wraps its cause in .reason; unwrap one level so a socket
    # layer that surfaces ENOSPC (e.g. a unix socket on a full tmpfs)
    # classifies the same as the bare OSError.
    if isinstance(exc, urllib.error.URLError) and \
            isinstance(exc.reason, OSError):
        exc = exc.reason
    return (isinstance(exc, OSError)
            and getattr(exc, "errno", None) in _PERMANENT_ERRNO)


def is_transient(exc: BaseException) -> bool:
    """True when retrying the operation can plausibly succeed."""
    if _permanent_os_error(exc):
        return False  # full/read-only disk: waiting cannot heal it
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code in _TRANSIENT_HTTP
    if isinstance(exc, urllib.error.URLError):
        return True  # DNS hiccup, refused, reset, socket timeout
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return True
    return False  # ValueError/KeyError etc.: malformed input never heals


def is_conn_failure(exc: BaseException) -> bool:
    """True for connection-LEVEL failures: refused, reset, timeout, DNS
    — the server never answered. This is the replica-failover signal
    (peer.py): when one config replica cannot be reached at all, a
    sibling may still answer, so the client rotates within the same
    attempt. An HTTP-level error (the server answered with a status) is
    NOT a failover signal — a 503 mid-election heals by *waiting* (the
    retry policy's backoff), not by asking another follower, and a 4xx
    would be identical everywhere. A permanent-errno OSError (ENOSPC,
    EROFS) is local to THIS process's disk, not the peer — rotating
    replicas cannot help either."""
    if isinstance(exc, urllib.error.HTTPError):
        return False  # must precede URLError: HTTPError subclasses it
    if _permanent_os_error(exc):
        return False
    if isinstance(exc, urllib.error.URLError):
        return True
    return isinstance(exc, (ConnectionError, TimeoutError, OSError))


@dataclass
class RetryPolicy:
    """Bounded retry loop with jittered exponential backoff.

    ``attempts`` bounds the try count; ``deadline_s`` (monotonic, from
    first try) bounds total wall time — whichever trips first ends the
    loop and re-raises the last error. ``jitter`` is the fraction of the
    delay drawn uniformly at random (0.5 => delay in [0.5d, d])."""

    attempts: int = 3
    base_ms: float = 50.0
    max_ms: float = 2000.0
    multiplier: float = 2.0
    jitter: float = 0.5
    deadline_s: Optional[float] = None
    name: str = ""
    # classifier is swappable so callers can treat e.g. a 404 as fatal
    classify: Callable[[BaseException], bool] = field(
        default=is_transient)
    # injectable for deterministic tests
    _rng: random.Random = field(default_factory=random.Random, repr=False)
    _sleep: Callable[[float], None] = field(default=time.sleep, repr=False)

    def delays_ms(self) -> Iterator[float]:
        """The backoff sequence (before jitter), one entry per retry."""
        d = self.base_ms
        for _ in range(max(0, self.attempts - 1)):
            yield min(d, self.max_ms)
            d *= self.multiplier

    def backoff_s(self, attempt: int) -> float:
        """Jittered delay (seconds) before retry number ``attempt``
        (1-based) — for callers that own their loop (deadline pollers)
        but want the shared backoff shape."""
        d = min(self.base_ms * self.multiplier ** max(0, attempt - 1),
                self.max_ms)
        return self._jittered(d) / 1e3

    def _jittered(self, ms: float) -> float:
        if self.jitter <= 0:
            return ms
        lo = ms * (1.0 - self.jitter)
        return lo + self._rng.random() * (ms - lo)

    def run(self, fn: Callable[[], T]) -> T:
        """Call ``fn`` until it returns, a fatal error raises, the
        attempt budget empties, or the deadline passes. Backoff between
        attempts is logged so a flapping dependency is visible."""
        t0 = time.monotonic()
        last: Optional[BaseException] = None
        for attempt, delay_ms in enumerate(
                list(self.delays_ms()) + [None], start=1):
            try:
                return fn()
            except BaseException as e:  # noqa: BLE001 — classified below
                last = e
                if not self.classify(e):
                    raise
                if delay_ms is None:
                    break  # attempts exhausted
                delay_ms = self._jittered(delay_ms)
                if self.deadline_s is not None and (
                        time.monotonic() - t0 + delay_ms / 1e3
                        > self.deadline_s):
                    break  # sleeping past the deadline helps nobody
                label = self.name or getattr(fn, "__name__", "call")
                print(
                    f"[kf-retry] {label} attempt {attempt}/"
                    f"{self.attempts} failed ({e}); backing off "
                    f"{delay_ms:.0f} ms",
                    flush=True,
                )
                self._sleep(delay_ms / 1e3)
        assert last is not None
        raise last

    def __call__(self, fn: Callable[[], T]) -> T:
        return self.run(fn)


def control_plane_policy(name: str = "",
                         attempts: int = 3,
                         deadline_s: Optional[float] = 10.0) -> RetryPolicy:
    """The default policy for config-server / discovery HTTP traffic.

    Env overrides (all optional): ``KF_RETRY_ATTEMPTS``,
    ``KF_RETRY_BASE_MS``, ``KF_RETRY_MAX_MS``, ``KF_RETRY_DEADLINE_MS``
    — one knob set for every adopted call site, which is the point."""
    import os

    return RetryPolicy(
        attempts=int(os.environ.get("KF_RETRY_ATTEMPTS", attempts)),
        base_ms=float(os.environ.get("KF_RETRY_BASE_MS", 50)),
        max_ms=float(os.environ.get("KF_RETRY_MAX_MS", 2000)),
        deadline_s=(
            float(os.environ["KF_RETRY_DEADLINE_MS"]) / 1e3
            if "KF_RETRY_DEADLINE_MS" in os.environ else deadline_s),
        name=name,
    )


#: One-attempt policy: for call sites that have their own outer loop
#: (e.g. the per-step resize poll, which must never stall a train step).
NO_RETRY = RetryPolicy(attempts=1, name="no-retry")
