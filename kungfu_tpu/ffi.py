"""ctypes bindings for libkf, the C++ DCN control plane.

Loads ``libkf.so`` from ``kungfu_tpu/native/`` (built by
``make -C kungfu_tpu/native``) and exposes a thin, typed wrapper. All
blocking calls release the GIL (ctypes does this for foreign calls), so
collectives can overlap with Python compute threads — the async-callback
role the reference's cgo bridge plays (reference:
srcs/go/libkufu-comm/main.go callOP) is covered here by calling into libkf
from Python threads/executors instead.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

from .plan import topology as _topology

_LIB_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.environ.get("KF_LIB", os.path.join(_LIB_DIR, "libkf.so"))

# error codes (mirror include/kf.h)
KF_OK = 0
KF_ERR = -1
KF_ERR_TIMEOUT = -2
KF_ERR_EPOCH = -3
KF_ERR_CONN = -4
KF_ERR_NOTFOUND = -5
KF_ERR_ARG = -6
# wire-frame integrity violation (torn/corrupted shm-ring frame): the
# channel is dead and the bytes untrusted — joins KF_ERR_CONN/TIMEOUT
# in the fail-fast-into-recovery taxonomy (docs/fault_tolerance.md)
KF_ERR_CORRUPT = -7

_ERR_NAMES = {
    KF_ERR: "generic failure",
    KF_ERR_TIMEOUT: "timeout",
    KF_ERR_EPOCH: "stale epoch token",
    KF_ERR_CONN: "connection failure",
    KF_ERR_NOTFOUND: "not found",
    KF_ERR_ARG: "invalid argument",
    KF_ERR_CORRUPT: "wire-frame integrity violation",
}

# strategy codes: plan.topology.STRATEGY_NAMES is the one catalog
# (docs/collectives.md); the native enum (include/kf.h) follows the
# same order, with AUTO one past the concrete shapes
STRATEGIES = {name: code
              for code, name in enumerate(_topology.STRATEGY_NAMES)}
STRATEGIES["AUTO"] = len(_topology.STRATEGY_NAMES)

#: wire link classes, in kf_link_stats order (docs/collectives.md):
#: TCP socket, AF_UNIX socket, shared-memory ring
LINK_CLASSES = ("tcp", "unix", "shm")

_NP_DTYPE_CODES = {
    np.dtype(np.uint8): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.uint16): 2,
    np.dtype(np.int16): 3,
    np.dtype(np.uint32): 4,
    np.dtype(np.int32): 5,
    np.dtype(np.uint64): 6,
    np.dtype(np.int64): 7,
    np.dtype(np.float16): 8,
    # bf16 (code 9) is registered below via ml_dtypes when available;
    # otherwise pass uint16 views with dtype_code=9
    np.dtype(np.float32): 10,
    np.dtype(np.float64): 11,
}

try:
    import ml_dtypes as _ml_dtypes

    _NP_DTYPE_CODES[np.dtype(_ml_dtypes.bfloat16)] = 9
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    pass

# sum_sat: integer dtypes clamp at the dtype bounds instead of wrapping —
# the accumulate the int8 compressed-gradient wire uses (clipping error
# is absorbed by error feedback; wraparound would flip gradient signs).
# Float dtypes: identical to sum.
_OPS = {"sum": 0, "min": 1, "max": 2, "prod": 3, "sum_sat": 4}

CONTROL_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64
)
TASK_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


class KfError(RuntimeError):
    def __init__(self, code: int, what: str):
        super().__init__(f"{what}: {_ERR_NAMES.get(code, code)} ({code})")
        self.code = code


def _check(code: int, what: str) -> int:
    if code < 0:
        raise KfError(code, what)
    return code


#: first load() can race in from the peer, metrics-tick and watcher
#: threads at once; dlopen + signature patch-up must happen exactly once
_lib_mu = threading.Lock()
_lib: Optional[ctypes.CDLL] = None  # kf: guarded_by(_lib_mu)


def load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib  # benign racy read: set once, never reset
    with _lib_mu:
        if _lib is None:
            _lib = _bind_lib()
        return _lib


def _bind_lib() -> ctypes.CDLL:
    lib = ctypes.CDLL(_LIB_PATH)
    P = ctypes.c_void_p
    i64 = ctypes.c_int64
    u32 = ctypes.c_uint32
    cs = ctypes.c_char_p
    sigs = {
        "kf_peer_new": ([cs, cs, u32, ctypes.c_int, i64], P),
        "kf_peer_start": ([P], ctypes.c_int),
        "kf_peer_stop": ([P], ctypes.c_int),
        "kf_peer_free": ([P], None),
        "kf_peer_update": ([P, cs, u32], ctypes.c_int),
        "kf_rank": ([P], ctypes.c_int),
        "kf_size": ([P], ctypes.c_int),
        "kf_local_rank": ([P], ctypes.c_int),
        "kf_local_size": ([P], ctypes.c_int),
        "kf_version": ([P], u32),
        "kf_uid": ([P], ctypes.c_uint64),
        "kf_barrier": ([P], ctypes.c_int),
        "kf_all_reduce": ([P, P, P, i64, ctypes.c_int, ctypes.c_int, cs],
                          ctypes.c_int),
        "kf_reduce": ([P, P, P, i64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                       cs], ctypes.c_int),
        "kf_broadcast": ([P, P, P, i64, ctypes.c_int, ctypes.c_int, cs],
                         ctypes.c_int),
        "kf_gather": ([P, P, i64, P, i64, ctypes.c_int, ctypes.c_int, cs],
                      ctypes.c_int),
        "kf_all_gather": ([P, P, i64, P, ctypes.c_int, cs], ctypes.c_int),
        "kf_consensus": ([P, P, i64, cs], ctypes.c_int),
        "kf_save": ([P, cs, P, i64], ctypes.c_int),
        "kf_save_version": ([P, cs, cs, P, i64], ctypes.c_int),
        "kf_request": ([P, ctypes.c_int, cs, P, i64], ctypes.c_int),
        "kf_request_version": ([P, ctypes.c_int, cs, cs, P, i64],
                               ctypes.c_int),
        "kf_set_control_handler": ([P, CONTROL_CB, P], ctypes.c_int),
        "kf_send_control": ([P, cs, cs, P, i64], ctypes.c_int),
        "kf_ping": ([P, ctypes.c_int, ctypes.POINTER(i64)], ctypes.c_int),
        "kf_stats": ([P, ctypes.POINTER(ctypes.c_uint64),
                      ctypes.POINTER(ctypes.c_uint64)], None),
        "kf_link_stats": ([P, ctypes.POINTER(ctypes.c_uint64)], None),
        "kf_shm_fallback_total": ([P], ctypes.c_uint64),
        "kf_hier": ([P], ctypes.c_int),
        "kf_version_string": ([], cs),
        "kf_accumulate": ([P, P, i64, ctypes.c_int, ctypes.c_int,
                           ctypes.c_int], ctypes.c_int),
        "kf_simd_enabled": ([ctypes.c_int], ctypes.c_int),
        "kf_trace_report": ([ctypes.c_char_p, i64], i64),
        "kf_trace_reset": ([], None),
        "kf_trace_enabled": ([], ctypes.c_int),
        "kf_order_group_new": ([ctypes.c_int, ctypes.POINTER(ctypes.c_int)],
                               P),
        "kf_order_group_start": ([P, ctypes.c_int, TASK_CB, P], ctypes.c_int),
        "kf_order_group_wait": ([P, ctypes.POINTER(ctypes.c_int)],
                                ctypes.c_int),
        "kf_order_group_free": ([P], None),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    return lib


def dtype_code(dt: np.dtype) -> int:
    try:
        return _NP_DTYPE_CODES[np.dtype(dt)]
    except KeyError:
        raise ValueError(f"unsupported dtype for control plane: {dt}")


def op_code(op: str) -> int:
    try:
        return _OPS[op]
    except KeyError:
        raise ValueError(f"unsupported reduce op: {op}")


def _buf_ptr(a: np.ndarray) -> ctypes.c_void_p:
    return ctypes.c_void_p(a.ctypes.data)


def accumulate(dst: np.ndarray, src: np.ndarray, op: str = "sum", *,
               force_scalar: bool = False) -> None:
    """In-place ``dst = dst (op) src`` via libkf's reduce kernel.

    This is the accumulate step collectives run on received chunks,
    SIMD-dispatched at runtime (AVX2/F16C with a portable fallback;
    reference: srcs/go/kungfu/base/f16.c uses the same intrinsics).
    ``force_scalar`` pins the portable path for comparison; both paths are
    bit-identical.
    """
    lib = load()
    if dst.shape != src.shape or dst.dtype != src.dtype:
        raise ValueError("dst/src must match in shape and dtype")
    if not dst.flags["C_CONTIGUOUS"] or not src.flags["C_CONTIGUOUS"]:
        raise ValueError("buffers must be C-contiguous")
    if not dst.flags.writeable:
        raise ValueError("dst must be writeable")
    _check(
        lib.kf_accumulate(_buf_ptr(dst), _buf_ptr(src), dst.size,
                          dtype_code(dst.dtype), op_code(op),
                          1 if force_scalar else 0), "accumulate")


def simd_enabled(dt) -> bool:
    """True when this process reduces `dt` with vector kernels."""
    return bool(load().kf_simd_enabled(dtype_code(np.dtype(dt))))


def trace_enabled() -> bool:
    """True when KF_TRACE=1 was set when libkf first checked."""
    return bool(load().kf_trace_enabled())


def trace_report() -> dict:
    """Scoped-timer profile of libkf hot paths, keyed by scope name.

    Each value is {"count", "total_us", "max_us"} accumulated since start
    (or the last trace_reset). Empty when KF_TRACE is off (reference:
    TRACE_SCOPE, srcs/cpp/include/kungfu/utils/trace.hpp:1-16 — logged
    per-event there, aggregated here because hot paths run millions of
    times).
    """
    buf = ctypes.create_string_buffer(16384)
    n = load().kf_trace_report(buf, len(buf))
    out = {}
    for line in buf.raw[:n].decode().splitlines():
        scope, count, total_us, max_us = line.split()
        out[scope] = {"count": int(count), "total_us": int(total_us),
                      "max_us": int(max_us)}
    return out


def trace_reset() -> None:
    load().kf_trace_reset()


class OrderGroup:
    """Run named async tasks in a fixed schedule order, recording arrival
    order — the host-side op-ordering engine (reference:
    srcs/go/ordergroup/ordergroup.go, srcs/cpp/src/python/init.cpp name-keyed
    wrapper). On TPU the XLA compiler orders on-device collectives, so this
    orders *control-plane* ops issued from multiple Python threads, which
    must hit the wire identically on every rank to avoid cross-rank
    deadlock. `schedule` is the list of task names in execution order."""

    def __init__(self, schedule):
        self._lib = load()
        self._names = list(schedule)
        self._index = {n: i for i, n in enumerate(self._names)}
        if len(self._index) != len(self._names):
            raise ValueError("duplicate names in schedule")
        self._h = self._lib.kf_order_group_new(len(self._names), None)
        if not self._h:
            raise RuntimeError("kf_order_group_new failed")
        # Callbacks must outlive their cycle: a cycle's n callbacks are
        # always a prefix of this list (every start of cycle k precedes
        # the reset that admits cycle k+1's starts), so wait() drops
        # exactly the first n without touching next-cycle registrations
        # racing in from other threads.
        self._mu = threading.Lock()
        self._cbs = []  # kf: guarded_by(_mu)
        self._errors = []  # kf: guarded_by(_mu) — raised inside tasks

    def start(self, name: str, fn):
        """Register `fn` to run (on the executor thread) at `name`'s slot."""
        if self._h is None:
            raise RuntimeError("order group is closed")

        def trampoline(_user):
            try:
                fn()
            # kflint: disable=retry-discipline
            except Exception as e:  # never let exceptions cross into C
                with self._mu:
                    self._errors.append((name, e))

        cb = TASK_CB(trampoline)
        with self._mu:
            self._cbs.append(cb)
        try:
            _check(
                self._lib.kf_order_group_start(self._h, self._index[name],
                                               cb, None),
                f"order_group start {name}",
            )
        except Exception:
            with self._mu:
                self._cbs.remove(cb)
            raise

    def wait(self):
        """Block until every scheduled task ran; return names in the order
        they arrived (the signal used to re-negotiate the schedule).
        Raises if any task of the cycle raised — a silently skipped task
        would leave peer ranks blocked on a never-issued named op."""
        if self._h is None:
            raise RuntimeError("order group is closed")
        out = (ctypes.c_int * len(self._names))()
        rc = self._lib.kf_order_group_wait(self._h, out)
        if rc < 0:
            # A failed wait means this thread did NOT consume the cycle: a
            # concurrent winner did (and owns the cycle's callbacks and
            # errors), or the group is tearing down (close() drops the
            # leftovers). Touching shared state here would steal the NEXT
            # cycle's live callbacks out from under the C executor.
            _check(rc, "order_group wait")
        # Winning waiter: consume exactly this cycle's callbacks + errors,
        # so stale callbacks never accumulate and a prior cycle's task
        # errors are never misattributed to a later wait().
        with self._mu:
            del self._cbs[:len(self._names)]
            errors, self._errors = self._errors, []
        if errors:
            err = RuntimeError(
                "order-group task(s) failed: "
                + "; ".join(f"{n}: {e}" for n, e in errors))
            # the original exception objects, for callers that must
            # type-dispatch (the gradient pipeline re-raises a KfError
            # so survivor recovery sees a peer death as itself)
            err.task_errors = errors
            raise err
        return [self._names[i] for i in out]

    def close(self):
        if getattr(self, "_h", None):
            self._lib.kf_order_group_free(self._h)  # joins the executor
            self._h = None
            # Safe only after free: no C thread can still hold the
            # trampolines. Dropping them here keeps an abandoned cycle
            # (teardown with wait() never called / failed) from leaking.
            with self._mu:
                self._cbs.clear()
                self._errors.clear()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativePeer:
    """Thin RAII handle over kf_peer. One per process, normally."""

    def __init__(
        self,
        self_spec: str,
        peers: str,
        version: int = 0,
        strategy: str = "AUTO",
        timeout_ms: int = 0,
    ):
        self._lib = load()
        self._h = self._lib.kf_peer_new(
            self_spec.encode(),
            peers.encode(),
            version,
            STRATEGIES[strategy.upper()],
            timeout_ms,
        )
        if not self._h:
            raise ValueError(
                f"kf_peer_new failed (self={self_spec!r} peers={peers!r})"
            )
        self._control_cb = None  # keep callback object alive

    def start(self):
        _check(self._lib.kf_peer_start(self._h), "peer start")

    def stop(self):
        if self._h:
            self._lib.kf_peer_stop(self._h)

    def close(self):
        if self._h:
            self._lib.kf_peer_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def update(self, peers: str, version: int):
        _check(self._lib.kf_peer_update(self._h, peers.encode(), version),
               "peer update")

    # -- introspection ------------------------------------------------------

    @property
    def rank(self) -> int:
        return self._lib.kf_rank(self._h)

    @property
    def size(self) -> int:
        return self._lib.kf_size(self._h)

    @property
    def local_rank(self) -> int:
        return self._lib.kf_local_rank(self._h)

    @property
    def local_size(self) -> int:
        return self._lib.kf_local_size(self._h)

    @property
    def version(self) -> int:
        return self._lib.kf_version(self._h)

    @property
    def uid(self) -> int:
        return self._lib.kf_uid(self._h)

    # -- collectives --------------------------------------------------------

    def barrier(self):
        _check(self._lib.kf_barrier(self._h), "barrier")

    def all_reduce(self, x: np.ndarray, op: str = "sum",
                   name: str = "") -> np.ndarray:
        x = np.ascontiguousarray(x)
        out = np.empty_like(x)
        _check(
            self._lib.kf_all_reduce(self._h, _buf_ptr(x), _buf_ptr(out),
                                    x.size, dtype_code(x.dtype), op_code(op),
                                    name.encode() or b"allreduce"),
            f"all_reduce {name}",
        )
        return out

    def all_reduce_inplace(self, x: np.ndarray, op: str = "sum",
                           name: str = "") -> np.ndarray:
        """All-reduce `x` INTO `x` — zero copies on any rank.

        Passes the same buffer as send and recv: `Session::all_reduce`
        skips its entry memcpy when the pointers alias, accumulates
        received chunks straight into `x`, and the broadcast-phase
        receive lands in place. This is the bucketed gradient-pipeline
        entry point — the allocating `all_reduce` above pays an
        `np.empty_like` landing buffer per call, which per-bucket would
        re-grow a model-sized copy per step. Returns `x`.
        """
        if not x.flags["C_CONTIGUOUS"]:
            raise ValueError("all_reduce_inplace needs a C-contiguous "
                             "buffer")
        if not x.flags.writeable:
            raise ValueError("all_reduce_inplace needs a writeable buffer")
        _check(
            self._lib.kf_all_reduce(self._h, _buf_ptr(x), _buf_ptr(x),
                                    x.size, dtype_code(x.dtype), op_code(op),
                                    name.encode() or b"allreduce"),
            f"all_reduce_inplace {name}",
        )
        return x

    def reduce(self, x: np.ndarray, op: str = "sum", root: int = 0,
               name: str = "") -> Optional[np.ndarray]:
        """Reduce to `root`; returns the result there, None elsewhere."""
        x = np.ascontiguousarray(x)
        out = np.empty_like(x)
        _check(
            self._lib.kf_reduce(self._h, _buf_ptr(x), _buf_ptr(out), x.size,
                                dtype_code(x.dtype), op_code(op), root,
                                name.encode() or b"reduce"),
            f"reduce {name}",
        )
        return out if self.rank == root else None

    def broadcast(self, x: np.ndarray, root: int = 0,
                  name: str = "") -> np.ndarray:
        x = np.ascontiguousarray(x)
        out = x.copy() if self.rank == root else np.empty_like(x)
        _check(
            self._lib.kf_broadcast(self._h, _buf_ptr(x), _buf_ptr(out),
                                   x.size, dtype_code(x.dtype), root,
                                   name.encode() or b"broadcast"),
            f"broadcast {name}",
        )
        return out

    def broadcast_inplace(self, x: np.ndarray, root: int = 0,
                          name: str = "") -> np.ndarray:
        """Broadcast `x` from `root` INTO `x` — zero copies on any rank.

        Passes the same buffer as send and recv: `Session::broadcast`
        skips its root-side memcpy when the pointers alias (root sends
        straight from `x`; receivers' chunks land in place via the
        registered `pop_into` receive). This is the streaming-resync
        entry point — the allocating `broadcast` above pays a full
        `x.copy()` on root plus an `np.empty_like` on every receiver,
        which for a 98 MiB elastic payload is two redundant model-sized
        copies (BASELINE round 6 decomposition).

        `x` must be C-contiguous, and writeable on non-root ranks (the
        received bytes overwrite it). Returns `x`.
        """
        if not x.flags["C_CONTIGUOUS"]:
            raise ValueError("broadcast_inplace needs a C-contiguous "
                             "buffer")
        if self.rank != root and not x.flags.writeable:
            raise ValueError("broadcast_inplace on a non-root rank "
                             "needs a writeable buffer")
        _check(
            self._lib.kf_broadcast(self._h, _buf_ptr(x), _buf_ptr(x),
                                   x.size, dtype_code(x.dtype), root,
                                   name.encode() or b"broadcast"),
            f"broadcast_inplace {name}",
        )
        return x

    def gather(self, x: np.ndarray, root: int = 0,
               name: str = "") -> Optional[np.ndarray]:
        x = np.ascontiguousarray(x)
        np_total = x.size * self.size
        out = np.empty((self.size,) + x.shape, dtype=x.dtype)
        _check(
            self._lib.kf_gather(self._h, _buf_ptr(x), x.size, _buf_ptr(out),
                                np_total, dtype_code(x.dtype), root,
                                name.encode() or b"gather"),
            f"gather {name}",
        )
        return out if self.rank == root else None

    def all_gather(self, x: np.ndarray, name: str = "") -> np.ndarray:
        x = np.ascontiguousarray(x)
        out = np.empty((self.size,) + x.shape, dtype=x.dtype)
        _check(
            self._lib.kf_all_gather(self._h, _buf_ptr(x), x.size,
                                    _buf_ptr(out), dtype_code(x.dtype),
                                    name.encode() or b"allgather"),
            f"all_gather {name}",
        )
        return out

    def consensus(self, data: bytes, name: str = "consensus") -> bool:
        buf = np.frombuffer(data, dtype=np.uint8)
        rc = _check(
            self._lib.kf_consensus(self._h, _buf_ptr(buf), buf.size,
                                   name.encode()),
            f"consensus {name}",
        )
        return rc == 1

    # -- store + p2p --------------------------------------------------------

    def save(self, name: str, x: np.ndarray, version: Optional[str] = None):
        x = np.ascontiguousarray(x)
        nbytes = x.size * x.itemsize
        if version is None:
            _check(self._lib.kf_save(self._h, name.encode(), _buf_ptr(x),
                                     nbytes), f"save {name}")
        else:
            _check(
                self._lib.kf_save_version(self._h, version.encode(),
                                          name.encode(), _buf_ptr(x), nbytes),
                f"save {name}@{version}",
            )

    def request(self, rank: int, name: str, like: np.ndarray,
                version: Optional[str] = None) -> np.ndarray:
        out = np.empty_like(np.ascontiguousarray(like))
        nbytes = out.size * out.itemsize
        if version is None:
            _check(
                self._lib.kf_request(self._h, rank, name.encode(),
                                     _buf_ptr(out), nbytes),
                f"request {name} from {rank}",
            )
        else:
            _check(
                self._lib.kf_request_version(self._h, rank, version.encode(),
                                             name.encode(), _buf_ptr(out),
                                             nbytes),
                f"request {name}@{version} from {rank}",
            )
        return out

    # -- control + monitoring ----------------------------------------------

    def set_control_handler(self, fn):
        """fn(name: str, payload: bytes) invoked on a server thread."""
        if fn is None:
            self._control_cb = None
            _check(self._lib.kf_set_control_handler(
                self._h, CONTROL_CB(0), None), "clear control handler")
            return

        def trampoline(_user, name, data, n):
            payload = ctypes.string_at(data, n) if n else b""
            try:
                fn(name.decode(), payload)
            # kflint: disable=retry-discipline
            except Exception as e:  # never let exceptions cross into C
                print(f"[kf] control handler error: {e}", flush=True)

        self._control_cb = CONTROL_CB(trampoline)
        _check(self._lib.kf_set_control_handler(self._h, self._control_cb,
                                                None), "set control handler")

    def send_control(self, dest: str, name: str, payload: bytes = b""):
        # chaos hook: a scheduled drop_control/delay_control fault
        # swallows or delays this control message deterministically
        # (local import: chaos is pure stdlib but ffi loads first)
        from . import chaos
        if chaos.on_control_send(name) == "drop":
            return
        buf = np.frombuffer(payload, dtype=np.uint8) if payload else None
        ptr = _buf_ptr(buf) if buf is not None else None
        _check(
            self._lib.kf_send_control(self._h, dest.encode(), name.encode(),
                                      ptr, len(payload)),
            f"send_control {name} to {dest}",
        )

    def ping(self, rank: int) -> int:
        rtt = ctypes.c_int64(0)
        _check(self._lib.kf_ping(self._h, rank, ctypes.byref(rtt)),
               f"ping {rank}")
        return rtt.value

    def stats(self):
        eg = ctypes.c_uint64(0)
        ing = ctypes.c_uint64(0)
        self._lib.kf_stats(self._h, ctypes.byref(eg), ctypes.byref(ing))
        return {"egress_bytes": eg.value, "ingress_bytes": ing.value}

    def link_stats(self):
        """Cumulative payload bytes per wire link class.

        ``{"egress": {"tcp":..,"unix":..,"shm":..}, "ingress": {...}}``
        — the attribution behind kf_wire_bytes_total{link=...}
        (docs/collectives.md). The ``stats()`` totals are always the
        sum of the classes, so "socket egress" = tcp + unix.
        """
        arr = (ctypes.c_uint64 * 6)()
        self._lib.kf_link_stats(self._h, arr)
        return {
            "egress": dict(zip(LINK_CLASSES, arr[0:3])),
            "ingress": dict(zip(LINK_CLASSES, arr[3:6])),
        }

    @property
    def shm_fallbacks(self) -> int:
        """How many per-pair shm channels degraded to the socket path
        (attach/ENOSPC/hello failures; cumulative across epochs — a
        pair retried and degraded again counts again). The native
        counter behind ``kf_link_fallback_total`` on /metrics
        (docs/collectives.md "Failure semantics")."""
        return int(self._lib.kf_shm_fallback_total(self._h))

    @property
    def hierarchical(self) -> bool:
        """True when the live session walks KF_HIER=1 hierarchical
        graphs (intra-host -> host masters -> intra-host), re-derived
        from the peer list at every epoch switch. False when there is
        no live session (kf_hier then returns a negative error code,
        which must not truthy-convert to "hierarchical")."""
        return self._lib.kf_hier(self._h) == 1
