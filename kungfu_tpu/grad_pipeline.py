"""Backward-overlapped, bucketed, compressed DCN gradient all-reduce.

The lump gradient path (`peer.all_reduce(fuse(grads))`) serializes the
whole post-backward step: every gradient byte waits for the slowest
layer's backward, then the full model crosses DCN as one synchronous
transfer. This module generalizes the elastic-resync chunk pipeline
(PR 3, `elastic/streaming.py`) to the per-step gradient path, applying
two proven ideas from related work:

- **Reverse-backward bucketing with comm/compute overlap** (PyTorch
  DDP, Li et al. 2020; Horovod tensor fusion): gradients are assigned
  to fixed-byte buckets in REVERSE leaf order — the order backward
  produces them — by `ops.collective.bucket_schedule`, and each
  bucket's all-reduce launches as soon as its last gradient
  materializes on host, while earlier layers' backward still runs
  (JAX async dispatch: `np.asarray(leaf)` blocks only until *that
  leaf* is computed, so output-side buckets hit the wire first).
- **Error-feedback gradient compression** (EF-SGD, Karimireddy et al.
  2019): per-bucket bf16 (2x fewer wire bytes) or int8 (4x) variants
  keep a local f32 residual of what compression dropped and re-inject
  it into the next step's bucket, so the quantization error is
  compensated instead of accumulated. Residual state lives in this
  object and is exposed as a pytree (`state()`/`load_state()`) so it
  sits NEXT TO optimizer state in checkpoints and elastic resync — a
  joiner adopting survivor state adopts the residuals too.

Determinism across peers: bucket contents and order are derived from
shapes/dtypes only (every rank computes the identical schedule), and
the retained `OrderGroup` engine (`ffi.kf_order_group_*` — the
reference's gradient-ordering negotiation primitive) executes the wire
ops in schedule order regardless of the order packer threads deliver
them, so named collectives hit the wire identically on every rank.
The recorded arrival order (`last_step_info["arrival"]`) is the signal
an adaptive scheduler would broadcast to re-negotiate the schedule.

Wire formats (decompress+accumulate runs in libkf's SIMD reduce
kernels, so the wire carries compressed bytes END TO END — no hop ever
re-inflates to f32):

- ``none``: dtype-native spans of the host gradient leaves, summed in
  place (`all_reduce_inplace`, send==recv aliasing — no landing copy).
  Bit-identical to the lump path.
- ``bf16``: f32 bucket + residual narrowed to bf16; summed by the
  native bf16 kernels (widen to f32, add, narrow RNE per hop).
- ``int8``: a 4-byte per-bucket scale negotiation (`max` all-reduce of
  the local amax) precedes the payload so every peer quantizes against
  the SAME scale, each into the ±(127 // np) budget so the summed
  payload fits int8 (QSGD-style range split; the traded precision is
  absorbed by the residual); the payload is summed with the saturating
  `sum_sat` kernel, so even pathological clipping degrades gracefully
  instead of wrapping into sign-flipped gradients.

See docs/grad_pipeline.md for the full protocol.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import numpy as np

from . import trace
from .env import env_choice, env_float
from .ffi import KfError, OrderGroup
from .ops.collective import bucket_schedule
from .trace import metrics

#: default bucket size (MiB). The native layer re-chunks to 1 MiB for
#: the wire, so larger buckets only delay the first launch; 1 MiB
#: matched the elastic-streaming sweep optimum on the loopback fabric.
DEFAULT_BUCKET_MB = 1.0

COMPRESSIONS = ("none", "bf16", "int8")


def grad_bucket_bytes(bucket_mb: Optional[float] = None) -> int:
    """Resolve the bucket size in bytes: explicit argument, else
    KF_GRAD_BUCKET_MB (validated at parse time), else
    `DEFAULT_BUCKET_MB`. Returns 0 when bucketing is disabled (size 0
    or negative) — callers fall back to the lump path."""
    if bucket_mb is None:
        bucket_mb = env_float("KF_GRAD_BUCKET_MB", DEFAULT_BUCKET_MB)
    if bucket_mb <= 0:
        return 0
    return max(1, int(bucket_mb * 2**20))


def grad_compression(compression: Optional[str] = None) -> str:
    """Resolve the compression mode: explicit argument, else
    KF_GRAD_COMPRESS (validated against the known modes)."""
    if compression is None:
        return env_choice("KF_GRAD_COMPRESS", "none", COMPRESSIONS)
    if compression not in COMPRESSIONS:
        raise ValueError(
            f"compression {compression!r} is not one of {COMPRESSIONS}")
    return compression


def _bf16_dtype():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


class GradBucketPipeline:
    """Bucketed, overlapped, optionally compressed gradient all-reduce.

    Built once per (model, peer) from a gradient *template* (any pytree
    with the gradients' structure/shapes/dtypes — e.g. the params) and
    reused every step::

        pipe = GradBucketPipeline(peer, params, compression="int8")
        ...
        loss, grads = loss_and_grads(params, batch)   # jitted, async
        grads = pipe.all_reduce(grads)                # mean over peers

    `all_reduce` accepts leaves as jax arrays (fetched with
    `np.asarray`, which blocks per-leaf — the overlap mechanism),
    numpy arrays, or zero-argument callables returning numpy (the
    benchmark's simulated-backward producer). Compression modes
    require float32 gradients; ``none`` carries any control-plane
    dtype.
    """

    def __init__(self, peer, grads_template, bucket_bytes: Optional[int]
                 = None, compression: Optional[str] = None,
                 name: str = "kf::grad", packers: int = 2):
        import jax

        self.peer = peer
        self.name = name
        self.compression = grad_compression(compression)
        if bucket_bytes is None:
            bucket_bytes = grad_bucket_bytes()
        if bucket_bytes <= 0:
            raise ValueError("GradBucketPipeline needs bucket_bytes > 0; "
                             "use the lump path when bucketing is "
                             "disabled")
        self.bucket_bytes = int(bucket_bytes)
        leaves, self._treedef = jax.tree_util.tree_flatten(grads_template)
        self._shapes = [np.shape(l) for l in leaves]
        self._dtypes = []
        for l in leaves:
            dt = getattr(l, "dtype", None)
            self._dtypes.append(np.dtype(dt) if dt is not None
                                else np.asarray(l).dtype)
        self._schedule = bucket_schedule(grads_template, self.bucket_bytes)
        if self.compression != "none":
            bad = sorted({str(dt) for dt, _ in self._schedule
                          if dt != np.dtype(np.float32)})
            if bad:
                raise ValueError(
                    f"{self.compression} compression needs float32 "
                    f"gradients; template has {bad} leaves")
        self._names = [f"b{k}" for k in range(len(self._schedule))]
        self._group = OrderGroup(self._names) if self._names else None
        # EF residuals: one f32 buffer per bucket, persistent across
        # steps (and across elastic epochs — the model doesn't change
        # shape on a resize, only the peer set does)
        self._residual: List[np.ndarray] = [
            np.zeros(sum(n for _, _, n in spans), np.float32)
            for _, spans in self._schedule
        ] if self.compression != "none" else []
        self._packers = max(1, packers)
        # long-lived: per-step thread churn has no place on the hot
        # path this module exists to optimize
        self._pool = ThreadPoolExecutor(max_workers=self._packers,
                                        thread_name_prefix="kf-grad-pack")
        self._round = 0
        #: diagnostics of the most recent step: wire payload bytes,
        #: per-phase times, and the true bucket arrival order (the
        #: re-negotiation signal)
        self.last_step_info: Dict = {}

    @property
    def num_buckets(self) -> int:
        return len(self._schedule)

    def close(self):
        if self._group is not None:
            self._group.close()
            self._group = None
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    # -- EF residual state (lives next to optimizer state) -------------------

    def state(self) -> Dict:
        """The error-feedback residual state as a plain pytree.

        Include this next to the optimizer state in everything that
        moves training state: checkpoints and the elastic resync
        broadcast (`resync_params((params, opt_state, pipe.state()))`)
        — a joiner that adopts survivor params without the survivors'
        residuals would silently diverge from the compensation the
        compressed stream already promised. Empty for ``none``."""
        return {"compression": self.compression,
                "residual": [r.copy() for r in self._residual]}

    def load_state(self, state: Dict):
        """Adopt residual state produced by `state()` (possibly carried
        through a resync broadcast or checkpoint restore)."""
        if state.get("compression") != self.compression:
            raise ValueError(
                f"residual state is for compression="
                f"{state.get('compression')!r}, pipeline runs "
                f"{self.compression!r}")
        res = state.get("residual", [])
        if len(res) != len(self._residual):
            raise ValueError(
                f"residual state has {len(res)} buckets, schedule has "
                f"{len(self._residual)}")
        for mine, theirs in zip(self._residual, res):
            arr = np.asarray(theirs, dtype=np.float32).reshape(-1)
            if arr.size != mine.size:
                raise ValueError("residual bucket size mismatch")
            mine[:] = arr

    # -- per-step all-reduce --------------------------------------------------

    def all_reduce(self, grads, average: bool = True,
                   step: Optional[int] = None):
        """Mean (or sum) `grads` over the cluster, bucket-pipelined.

        Wire names are tagged ``{name}:{epoch}:{step}:bK``. ELASTIC
        callers must pass the cluster-agreed `step` (e.g.
        ``elastic.state.step``): a joiner's fresh pipeline and the
        survivors' long-lived ones must produce identical names or the
        name-keyed rendezvous deadlocks. Static clusters may omit it
        (an internal counter advances identically on every rank).

        Returns a pytree with the template's structure; leaves are host
        numpy arrays (control-plane discipline: the result re-enters
        the jitted update step, which devices it once). Writeable
        contiguous numpy input leaves are CONSUMED — the reduction
        lands in their buffers (the zero-copy contract); jax leaves
        pay their one device->host copy and are never mutated."""
        import jax

        leaves = jax.tree_util.tree_flatten(grads)[0]
        if len(leaves) != len(self._shapes):
            raise ValueError(
                f"grads tree has {len(leaves)} leaves, template has "
                f"{len(self._shapes)}")
        t0 = time.perf_counter()
        if step is None:
            step = self._round
            self._round += 1
        tag = f"{self.name}:{self.peer.version}:{step}"
        size = max(1, self.peer.size)

        # per-leaf flat host buffers, fetched at most once per step.
        # np.asarray on a jax leaf blocks until THAT leaf's backward is
        # done — fetching in schedule (reverse-backward) order is what
        # lets bucket 0 hit the wire while earlier layers still compute.
        fetch_mu = threading.Lock()
        # shared by every packer thread through the fetch closure
        # kf: guarded_by(fetch_mu)
        flats: List[Optional[np.ndarray]] = [None] * len(leaves)

        def fetch(i: int) -> np.ndarray:
            with fetch_mu:
                if flats[i] is None:
                    l = leaves[i]
                    if callable(l):
                        l = l()
                    a = np.asarray(l)
                    if a.dtype != self._dtypes[i]:
                        raise ValueError(
                            f"leaf {i} dtype {a.dtype} != template "
                            f"{self._dtypes[i]}")
                    # the wire accumulates into this buffer, so it must
                    # be contiguous + writeable; jax leaves surface as
                    # read-only views and pay their one host copy here
                    if not (isinstance(a, np.ndarray)
                            and a.flags.c_contiguous
                            and a.flags.writeable):
                        buf = np.ascontiguousarray(a)
                        if not buf.flags.writeable or buf is a:
                            buf = buf.copy()
                        a = buf
                    flats[i] = a.reshape(-1)
                return flats[i]

        err_mu = threading.Lock()
        errors: List = []  # kf: guarded_by(err_mu)
        # wire_bytes/t_wire are written only inside wire slots, which
        # the OrderGroup runs sequentially on its ONE executor thread;
        # wait() is the join that publishes them to this thread — a
        # single-owner pattern, not shared state, so no lock (the same
        # argument as elastic/streaming.py's pipeline)
        wire_bytes = [0]
        t_wire = [0.0]

        def wire_clock(fn):
            t = time.perf_counter()
            fn()
            t_wire[0] += time.perf_counter() - t

        def pack(k: int):
            """Assemble bucket k and hand its wire op to the order
            group. MUST always register the slot — a missing start
            would hang every rank's wait()."""
            _, spans = self._schedule[k]
            nm = f"{tag}:b{k}"
            try:
                with trace.span("bucket.pack", cat="grad", bucket=k):
                    bufs = [fetch(i)[o:o + n] for i, o, n in spans]
                    # the _round fallback inside `tag` is for STATIC
                    # clusters only, where the internal counter
                    # advances identically on every rank; elastic
                    # callers must pass the cluster-agreed step=
                    # (all_reduce docstring; the PR 5 joiner deadlock
                    # in docs/static_analysis.md is what happens
                    # otherwise, and what kfverify flags here)
                    # kflint: disable=wire-name-determinism
                    slot = self._make_slot(k, bufs, nm, wire_bytes,
                                           wire_clock)
                if trace.enabled():
                    slot = self._traced_slot(k, slot)
            # a pack failure must not wedge THIS rank: register a no-op
            # slot so the local wait() completes and the error surfaces
            # (peers fail fast on their own collective timeout, exactly
            # as with any rank fault mid-step)
            # kflint: disable=retry-discipline
            except Exception as e:
                with err_mu:
                    errors.append((nm, e))

                def slot():
                    pass
            self._group.start(self._names[k], slot)

        futs = [self._pool.submit(pack, k)
                for k in range(len(self._schedule))]
        # drain the packers BEFORE wait(): if a start() itself failed
        # (group closed under us), its slot never registered and wait()
        # would block forever — f.result() surfaces that instead. The
        # executor runs slots as starts arrive, so waiting here costs
        # no overlap.
        for f in futs:
            f.result()
        arrival: List[str] = []
        if self._group is not None:
            try:
                arrival = self._group.wait()
            except RuntimeError as e:
                # surface a peer-death/timeout as the KfError the
                # survivor-recovery path catches, not a generic
                # order-group wrapper
                for _, te in getattr(e, "task_errors", ()):
                    if isinstance(te, KfError):
                        raise te from e
                raise
        if errors:
            raise RuntimeError(
                "gradient-pipeline pack failed: "
                + "; ".join(f"{n}: {e}" for n, e in errors))

        with trace.span("bucket.land", cat="grad"):
            out = self._land(leaves, flats, size if average else 1)
        wall = time.perf_counter() - t0
        self.last_step_info = {
            "buckets": len(self._schedule),
            "compression": self.compression,
            "payload_bytes": wire_bytes[0],
            "wire_ms": t_wire[0] * 1e3,
            "wall_ms": wall * 1e3,
            "arrival": arrival,
        }
        # /metrics families (docs/observability.md): cumulative wire
        # payload, and how long the wire executor idled waiting on
        # packer arrivals (wall - wire) — the backpressure signal an
        # adaptive bucket scheduler would consume
        metrics.REGISTRY.inc("kf_wire_bytes_total", wire_bytes[0],
                             collective="grad")
        metrics.REGISTRY.set("kf_grad_arrival_lag_ms",
                             max(0.0, (wall - t_wire[0]) * 1e3))
        # link-class attribution of the same family ({tcp, unix, shm},
        # docs/collectives.md) from the native per-link counters
        publish = getattr(self.peer, "publish_link_metrics", None)
        if publish is not None:
            publish()
        return jax.tree_util.tree_unflatten(self._treedef, out)

    # -- wire slots (run on the OrderGroup executor, schedule order) ---------

    @staticmethod
    def _traced_slot(k, slot):
        """Wrap a wire slot in a bucket.wire span (executor thread)."""
        def traced():
            with trace.span("bucket.wire", cat="grad", bucket=k):
                slot()

        return traced

    def _make_slot(self, k, bufs, nm, wire_bytes, wire_clock):
        peer = self.peer

        if self.compression == "none":
            if len(bufs) == 1:
                send = bufs[0]  # pure view: summed in place, no copy
            else:
                send = np.concatenate(bufs)

            def slot():
                wire_bytes[0] += send.nbytes
                wire_clock(lambda: peer.all_reduce_inplace(
                    send, op="sum", name=nm))
                if len(bufs) > 1:  # scatter the coalesced tail back
                    self._scatter(bufs, send)

            return slot

        # compressed: gather the bucket to f32, re-inject the residual
        x = (np.concatenate(bufs) if len(bufs) > 1
             else bufs[0].copy()).astype(np.float32, copy=False)
        res = self._residual[k]
        x += res

        if self.compression == "bf16":
            c = x.astype(_bf16_dtype())
            res[:] = x - c.astype(np.float32)

            def slot():
                wire_bytes[0] += c.nbytes
                wire_clock(lambda: peer.all_reduce_inplace(
                    c, op="sum", name=nm))
                self._scatter(bufs, c.astype(np.float32))

            return slot

        # int8: negotiate a shared scale (max of local amax), quantize
        # against it, saturating-sum the payload. Each rank's range is
        # ±(127 // np) so the SUM fits int8 without clipping (the
        # QSGD-style budget split — log2(np) bits of precision traded,
        # absorbed by the residual); sum_sat still guards the np > 127
        # pathological case. Quantization happens inside the slot
        # because it needs the negotiated scale; the residual then
        # reflects exactly what the wire dropped.
        local_amax = float(np.max(np.abs(x))) if x.size else 0.0

        def slot():
            s = np.array([local_amax], np.float32)
            wire_bytes[0] += s.nbytes
            wire_clock(lambda: peer.all_reduce_inplace(
                s, op="max", name=f"{nm}:s"))
            qmax = max(1, 127 // max(1, peer.size))
            scale = float(s[0]) / qmax or 1.0
            q = np.clip(np.rint(x / scale), -qmax, qmax).astype(np.int8)
            res[:] = x - q.astype(np.float32) * scale
            wire_bytes[0] += q.nbytes
            wire_clock(lambda: peer.all_reduce_inplace(
                q, op="sum_sat", name=f"{nm}:q"))
            self._scatter(bufs, q.astype(np.float32) * scale)

        return slot

    @staticmethod
    def _scatter(bufs, decoded: np.ndarray):
        """Land a decoded/coalesced bucket back into the leaf views."""
        o = 0
        for b in bufs:
            b[:] = decoded[o:o + b.size]
            o += b.size

    def _land(self, leaves, flats, divisor: int) -> List[np.ndarray]:
        """Reshape the summed flat buffers into output leaves, applying
        the mean divisor to float leaves (integer gradients — unusual,
        but legal under ``none`` — stay sums)."""
        out = []
        for i, shape in enumerate(self._shapes):
            dt = self._dtypes[i]
            flat = flats[i]
            if flat is None:  # zero-size leaf: no spans touched it
                out.append(np.zeros(shape, dtype=dt))
                continue
            a = flat.reshape(shape)
            if divisor != 1 and np.issubdtype(dt, np.inexact):
                a = (a / np.asarray(divisor, dtype=dt)
                     if dt != np.dtype(np.float32)
                     else a / np.float32(divisor))
            out.append(a)
        return out
