"""Lower a Scenario to the runtime's native plan objects.

`compile_scenario` turns the declarative event timeline into exactly
the artifacts the existing elastic runtime already consumes — an
`elastic/schedule.py` piecewise size schedule, a `chaos.py` fault
schedule, the env knobs that arm recovery/checkpointing — so a
scenario replays through `kfrun` **unchanged**: no scenario-aware code
in the hot path, the engine is pure trace-in.

The compiler is **schedule-only**: the plan derives from the Scenario
fields alone — no clock, env, filesystem or tensor reads — so every
rank (each worker parses the same compiled KF_CHAOS / TEST_SCHEDULE
from its environment) and every future replay derives the identical
plan. `compile_scenario` is registered with the kfverify
schedule-purity pass (analysis/protocol/schedule_purity.py) next to
chunk/bucket/shard_schedule and match_partition_rules; an impure read
feeding it is a lint failure, not a code-review hope.

Lowering rules:

- ``resize`` events -> one piecewise schedule string (durations
  between change points; the last size holds past the end) — the same
  format `step_based_schedule` has parsed since the seed.
- ``preempt`` with a pinned rank -> a ``crash_worker`` fault plus
  ``KF_RECOVER=1`` (survivor recovery adopts the shrink; the schedule
  then re-grows to target through the ordinary elastic path).
- ``preempt`` with a pinned host -> a ``crash_host`` fault plus
  ``KF_RECOVER=1``: every rank on the emulated host dies at the step
  (whole-host spot reclamation), the host's runner proposes one
  shrunken stage for the burst, and the cross-host survivors recover.
  The scenario's ``hosts`` layout lowers to the loopback-alias host
  spec (``127.0.0.1:a,127.0.0.2:b``) the multi-runner replay launches
  with.
- ``preempt`` with cluster scope -> a **phase boundary**: the phase
  ends with an unpinned ``crash_worker`` fault (every process dies =
  the allocation was reclaimed; expected exit is nonzero) and the next
  phase relaunches against the same checkpoint directory, cold-booting
  from the last complete sharded generation. ``lead_steps`` schedules
  a ``preempt_warning`` marker that many steps ahead in both shapes.
- ``straggler`` -> a ``straggler_worker`` fault whose per-process
  count equals the window length.
- ``flaky_control`` -> ``delay_http``/``refuse_http`` faults gated on
  a request-index threshold derived as ``step * np0`` (the elastic
  hook polls the server about once per step per rank — the one
  documented approximation in the lowering, recorded on the plan).
- ``kill_replica`` -> a ``kill_config_replica`` fault (permanent
  replica death, docs/control_plane.md) with the same ``step * np0``
  request-index threshold, matched on ``role``/``replica``/``path``.
- ``partition`` -> netns link-flap windows on the plan (the FakeNet
  fabric applies them by wall offset; chaos-matrix only).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .spec import Scenario, load_scenario


@dataclass(frozen=True)
class ScenarioPhase:
    """One kfrun launch of the plan. `expect_rc` is 0 or "nonzero"
    (a phase that ends in whole-cluster death exits nonzero by
    design); `cold_boot` marks relaunch phases that must restore from
    the checkpoint tier instead of fresh-initing."""

    np0: int
    schedule: str
    total_steps: int
    chaos: Optional[Dict]
    env: Dict[str, str]
    expect_rc: object = 0
    cold_boot: bool = False


@dataclass(frozen=True)
class ScenarioPlan:
    name: str
    phases: Tuple[ScenarioPhase, ...]
    netns_windows: Tuple[Tuple[str, float, float], ...]
    device_batch: int
    total_steps: int
    needs_recover: bool = False
    needs_ckpt: bool = False
    description: str = ""
    notes: Tuple[str, ...] = field(default_factory=tuple)
    # multi-host replays: "127.0.0.1:a,127.0.0.2:b" (one kfrun per
    # listed ip at replay time); "" = the single-runner launch
    hosts: str = ""
    # what the cluster runs under the churn: "train" (continuity
    # trainer) or "serve" (kfserve decode tier; steps are decode
    # iterations and the replay gates on the request ledger)
    workload: str = "train"


def _size_timeline(scenario: Scenario) -> List[Tuple[int, int]]:
    """[(change_step, size)] starting at (0, np0), resize events
    applied in step order (ties: later event in the list wins)."""
    points = [(0, scenario.np0)]
    for ev in sorted((e for e in scenario.events
                      if e["kind"] == "resize"),
                     key=lambda e: int(e["step"])):
        points.append((int(ev["step"]), int(ev["size"])))
    # collapse duplicate change steps, keep the last size per step
    out: List[Tuple[int, int]] = []
    for step, size in points:
        if out and out[-1][0] == step:
            out[-1] = (step, size)
        else:
            out.append((step, size))
    return out


def _schedule_string(scenario: Scenario) -> str:
    """The piecewise `elastic/schedule.py` spec covering the run."""
    timeline = _size_timeline(scenario)
    segments: List[str] = []
    for i, (step, size) in enumerate(timeline):
        end = (timeline[i + 1][0] if i + 1 < len(timeline)
               else max(scenario.steps, step + 1))
        if end > step:
            segments.append(f"{end - step}:{size}")
    return ",".join(segments)


def _host_spec(scenario: Scenario) -> str:
    """The scenario's emulated-host layout as the kfrun -H spec
    (loopback aliases in host-index order); "" for the default
    single-host shape. Pure: derives from the spec's `hosts` alone."""
    if len(scenario.hosts) < 2:
        return ""
    return ",".join(f"127.0.0.{i + 1}:{slots}"
                    for i, slots in enumerate(scenario.hosts))


def _size_at(scenario: Scenario, step: int) -> int:
    size = scenario.np0
    for change, s in _size_timeline(scenario):
        if step >= change:
            size = s
    return size


def compile_scenario(scenario) -> ScenarioPlan:
    """Scenario -> ScenarioPlan. Pure: the plan is a function of the
    spec alone (kfverify schedule-purity holds this module to that),
    so every rank and every replay derives the identical plan."""
    scenario = load_scenario(scenario)
    schedule = _schedule_string(scenario)
    notes: List[str] = []

    # (anchor_step, fault): the anchor is the absolute scenario step
    # the fault belongs to, so cluster preempts can split the list into
    # per-phase schedules below (a fault fires in the launch that
    # executes its step, not only in phase 0)
    faults: List[Tuple[int, Dict]] = []
    env: Dict[str, str] = dict(scenario.env)
    needs_recover = False
    netns: List[Tuple[str, float, float]] = []
    cluster_preempts: List[Dict] = []

    for ev in scenario.events:
        kind = ev["kind"]
        if kind == "resize":
            continue  # folded into the schedule string
        if kind == "preempt":
            lead = int(ev.get("lead_steps", 0))
            step = int(ev["step"])
            if lead > 0:
                warn_step = max(step - lead, 1)
                faults.append((warn_step,
                               {"type": "preempt_warning",
                                "step": warn_step,
                                "lead_steps": lead}))
            if ev.get("host") is not None:
                # whole-host spot reclamation: every colocated rank
                # consumes its own copy of the fault and dies at the
                # step boundary; survivors on other hosts recover
                faults.append((step, {
                    "type": "crash_host", "host": int(ev["host"]),
                    "step": step,
                    "signal": str(ev.get("signal", "KILL")),
                }))
                needs_recover = True
            elif ev.get("rank") is None or ev.get("scope") == "cluster":
                cluster_preempts.append(ev)
            else:
                faults.append((step, {
                    "type": "crash_worker", "rank": int(ev["rank"]),
                    "step": step,
                    "signal": str(ev.get("signal", "KILL")),
                }))
                needs_recover = True
        elif kind == "straggler":
            start = int(ev["step"])
            dur = int(ev["duration_steps"])
            faults.append((start, {
                "type": "straggler_worker", "rank": int(ev["rank"]),
                "from_step": start, "to_step": start + dur - 1,
                "ms": float(ev["ms"]), "count": dur,
            }))
        elif kind == "flaky_control":
            mode = str(ev.get("mode", "delay"))
            fault = {
                "type": ("refuse_http" if mode == "refuse"
                         else "delay_http"),
                "count": int(ev["requests"]),
                # the elastic hook polls ~once per step per rank; the
                # step coordinate lowers to a request-index threshold
                "after_requests": int(ev["step"]) * scenario.np0,
            }
            if mode == "refuse":
                fault["status"] = int(ev.get("status", 503))
            else:
                fault["ms"] = float(ev.get("ms", 100))
            faults.append((int(ev["step"]), fault))
            notes.append(
                f"flaky_control step {ev['step']} lowered to "
                f"after_requests={fault['after_requests']} "
                f"(~1 GET/step/rank)")
        elif kind in ("kill_replica", "restart_replica"):
            fault = {
                "type": ("kill_config_replica" if kind == "kill_replica"
                         else "restart_config_replica"),
                "role": str(ev.get("role", "leader")),
                "after_requests": int(ev["step"]) * scenario.np0,
            }
            if ev.get("replica") is not None:
                fault["replica"] = int(ev["replica"])
            if ev.get("path") is not None:
                fault["path"] = str(ev["path"])
            faults.append((int(ev["step"]), fault))
            fate = ("permanent {} death".format(fault["role"])
                    if kind == "kill_replica" else
                    "{} crash + WAL-replay rejoin".format(fault["role"]))
            notes.append(
                f"{kind} step {ev['step']} lowered to "
                f"after_requests={fault['after_requests']} "
                f"({fate}; fires only when "
                "the replay runs the replicated tier)")
        elif kind == "kill_router":
            fault = {
                "type": "kill_router",
                # router traffic is serve-plane: after_requests counts
                # the ROUTER'S OWN requests (chaos.on_router_request),
                # not the ~1-GET/step/rank control-plane index — the
                # step anchor is best-effort, stated in the note
                "after_requests": int(ev["step"]) * scenario.np0,
            }
            if ev.get("router") is not None:
                fault["router"] = int(ev["router"])
            if ev.get("path") is not None:
                fault["path"] = str(ev["path"])
            faults.append((int(ev["step"]), fault))
            notes.append(
                f"kill_router step {ev['step']} lowered to "
                f"after_requests={fault['after_requests']} against the "
                "router's OWN serve-plane counter (workload-dependent "
                "anchor; fires only when the replay fronts the tier "
                "with admission routers)")
        elif kind == "partition":
            netns.append((str(ev["host"]), float(ev["at_ms"]),
                          float(ev["heal_ms"])))

    if needs_recover:
        env.setdefault("KF_RECOVER", "1")
    needs_ckpt = bool(cluster_preempts)
    if needs_ckpt:
        # cold restore needs generations on disk before the kill; the
        # runner supplies KF_CKPT_DIR (a path is runtime state, not
        # plan data) — the cadence is plan data and defaults here
        env.setdefault("KF_CKPT_EVERY", "3")

    phases: List[ScenarioPhase] = []
    if not cluster_preempts:
        phases.append(ScenarioPhase(
            np0=scenario.np0, schedule=schedule,
            total_steps=scenario.steps,
            chaos=({"seed": scenario.seed,
                    "faults": [f for _, f in faults]}
                   if faults else None),
            env=env, expect_rc=0))
    else:
        # whole-allocation preemptions split the run into launches:
        # each dying phase carries the unpinned crash fault (every
        # process is a victim), each relaunch cold-boots from the
        # checkpoint tier and resumes the SAME absolute schedule (the
        # restored step indexes into it unchanged). Every other fault
        # goes to the phase whose step range executes its anchor —
        # phase i owns (bounds[i-1], bounds[i]], the final relaunch
        # owns everything past the last kill — and a straggler window
        # that crosses a kill is split so the post-restore remainder
        # still replays. (A redone step — restore point < anchor <=
        # previous kill — does NOT re-fire its fault: one spec event
        # is one occurrence.)
        bounds = sorted(int(e["step"]) for e in cluster_preempts)
        for anchor, f in faults:
            if (f["type"] in ("delay_http", "refuse_http",
                              "kill_config_replica",
                              "restart_config_replica", "kill_router")
                    and anchor > bounds[0]):
                raise ValueError(
                    f"scenario {scenario.name!r}: flaky_control at "
                    f"step {anchor} follows the whole-cluster preempt "
                    f"at step {bounds[0]} — its request-index "
                    "threshold counts from a fresh config-server "
                    "boot whose restore step is not statically "
                    "derivable; move the flap before the preemption "
                    "or into its own scenario")
        split: List[Tuple[int, Dict]] = []
        for anchor, f in faults:
            while (f["type"] == "straggler_worker"
                   and any(int(f["from_step"]) <= b < int(f["to_step"])
                           for b in bounds)):
                b = min(b for b in bounds
                        if int(f["from_step"]) <= b < int(f["to_step"]))
                head = dict(f, to_step=b,
                            count=b - int(f["from_step"]) + 1)
                split.append((int(head["from_step"]), head))
                f = dict(f, from_step=b + 1,
                         count=int(f["to_step"]) - b)
                anchor = b + 1
            split.append((anchor, f))
        ranges = [(lo, hi) for lo, hi in
                  zip([0] + bounds, bounds + [scenario.steps + 1])]
        for i, (lo, hi) in enumerate(ranges):
            dying = i < len(bounds)
            phase_faults = [f for anchor, f in split
                            if lo < anchor <= hi or (i == 0 and anchor == 0)]
            if dying:
                phase_faults.append({"type": "crash_worker",
                                     "step": hi, "signal": "KILL"})
            phases.append(ScenarioPhase(
                np0=_size_at(scenario, hi if dying else lo),
                schedule=schedule, total_steps=scenario.steps,
                chaos=({"seed": scenario.seed, "faults": phase_faults}
                       if phase_faults else None),
                env=env, expect_rc="nonzero" if dying else 0,
                cold_boot=i > 0))

    return ScenarioPlan(
        name=scenario.name,
        phases=tuple(phases),
        netns_windows=tuple(netns),
        device_batch=scenario.device_batch,
        total_steps=scenario.steps,
        needs_recover=needs_recover,
        needs_ckpt=needs_ckpt,
        description=scenario.description,
        notes=tuple(notes),
        hosts=_host_spec(scenario),
        workload=scenario.workload,
    )
