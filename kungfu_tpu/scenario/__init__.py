"""Trace-driven cluster scenarios: replayable churn for the elastic runtime.

The scenario engine closes ROADMAP item 4's loop: **trace-in** is a
declarative availability trace (`spec.Scenario` — spot preemptions with
lead-time warnings, diurnal grow/shrink curves, slow hosts, flapping
control planes and networks), **lowering** is `compiler.compile_scenario`
(a schedule-only function onto the artifacts the runtime already
consumes: the elastic piecewise size schedule, a `chaos.py` fault
schedule, env knobs, kfrun launch phases — held to purity by the
kfverify schedule-purity pass), and **replay** is `runner.run_scenario`
(the kfrun + config-server + continuity-trainer harness under
``KF_TRACE=1``). **Trace-out** is the kftrace stream the replay leaves
behind, which `trace.goodput` decomposes into the operator-facing
number: goodput = useful work / wallclock, with every non-useful
millisecond attributed to a phase (docs/observability.md).

    from kungfu_tpu.scenario import canned, run_scenario
    run = run_scenario(canned("spot_preempt", np0=2), trace_dir=d)
    # then: python -m kungfu_tpu.trace --dir d --goodput
"""

from __future__ import annotations

from .compiler import ScenarioPhase, ScenarioPlan, compile_scenario
from .runner import ScenarioRun, ScenarioUnsupported, run_scenario
from .spec import CANNED, Scenario, load_scenario

__all__ = [
    "Scenario", "load_scenario", "CANNED", "canned",
    "compile_scenario", "ScenarioPlan", "ScenarioPhase",
    "run_scenario", "ScenarioRun", "ScenarioUnsupported",
]


def canned(name: str, np0: int | None = None) -> Scenario:
    """A standard-suite scenario by name, optionally at a different
    starting cluster size (each builder is parameterized by np0)."""
    if name not in CANNED:
        raise ValueError(f"unknown canned scenario {name!r} "
                         f"(known: {sorted(CANNED)})")
    return CANNED[name]() if np0 is None else CANNED[name](np0)
