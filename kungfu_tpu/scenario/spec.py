"""Declarative cluster-availability scenarios: the trace-in format.

A **scenario** is the replayable description of what a real cluster
lived through — spot preemptions (with the fabric's lead-time
warning), diurnal grow/shrink curves, slow hosts, flaky control
planes, partitioned networks — expressed as events over a **step
timeline** (plus wall-clock offsets for the one fault class that is
genuinely wall-clock-shaped, netns link flaps). The reference's
adaptation benchmarks hand-author docker-compose churn scripts
(reference: benchmarks/adaptation/gen-compose.py); here the scenario
is data: JSON in a file or inline env, schedule-only, so every rank —
and every future replay — derives the identical plan
(`compiler.compile_scenario` is held to that by the kfverify
schedule-purity pass).

Spec format::

    {"name": "spot2", "np0": 2, "steps": 14, "device_batch": 64,
     "seed": 0, "hosts": [1, 1],
     "events": [
       {"kind": "preempt", "step": 8, "scope": "cluster",
        "lead_steps": 2},                  # spot reclaim, whole cluster
       {"kind": "preempt", "step": 5, "rank": 2},   # one worker dies
       {"kind": "preempt", "step": 6, "host": 1},   # whole host dies
       {"kind": "resize", "step": 4, "size": 3},    # diurnal points
       {"kind": "straggler", "step": 4, "rank": 1,
        "duration_steps": 4, "ms": 120},
       {"kind": "flaky_control", "step": 3, "requests": 4,
        "mode": "delay", "ms": 150},          # config server degrades
       {"kind": "kill_replica", "step": 6,
        "role": "leader"},                    # config replica dies FOREVER
       {"kind": "restart_replica", "step": 6,
        "role": "follower"},                  # crash + WAL-replay rejoin
       {"kind": "kill_router", "step": 5,
        "router": 0},                         # admission router dies
       {"kind": "partition", "host": "a", "at_ms": 3000,
        "heal_ms": 5500}                      # netns link flap
     ],
     "env": {"KF_CKPT_EVERY": "3"}}

Event kinds (each validated by `load_scenario`):

- ``preempt`` — ``scope: "cluster"`` (default when no rank/host)
  kills every worker at ``step`` (the spot-reclaim shape; the run must
  then cold-restore from the durable checkpoint tier), a pinned
  ``rank`` kills one worker (survivor recovery handles it), a pinned
  ``host`` kills EVERY worker on that emulated host (the whole-host
  spot-reclamation shape, lowered to the ``crash_host`` chaos fault;
  the cross-host survivors recover and the schedule re-grows — needs a
  multi-host ``hosts`` layout). ``lead_steps`` schedules a
  `preempt_warning` chaos marker that many steps earlier.
- ``resize`` — the cluster-size timeline changes to ``size`` at
  ``step`` (diurnal availability curves are a list of these).
- ``straggler`` — ``rank`` sleeps ``ms`` per step for
  ``duration_steps`` steps starting at ``step`` (the
  `benchmarks/straggler.py` slow-host mechanism, injected through the
  chaos engine so it rides any trainer).
- ``flaky_control`` — the config server degrades for ``requests``
  requests starting roughly at ``step``: ``mode: "delay"`` adds
  ``ms`` per request, ``mode: "refuse"`` returns ``status`` (503).
- ``kill_replica`` — one member of the REPLICATED control tier
  (docs/control_plane.md) dies permanently starting roughly at
  ``step``, matched by ``role`` ("leader" default / "follower") or a
  pinned ``replica`` index, optionally only on a specific ``path``
  (e.g. ``"/addworker"`` = mid-resize). Lowered to the
  ``kill_config_replica`` chaos fault; against a non-replicated
  single config server the fault never fires (the hook is
  replica-only), so the scenario only means something when the
  replay runs the tier.
- ``restart_replica`` — same matching as ``kill_replica`` but the
  victim crash-RESTARTS: it loses all memory, replays its
  write-ahead log, rejoins ``behind`` and is repaired by the tier
  (lowered to the ``restart_config_replica`` chaos fault; only
  meaningful when the tier runs with ``KF_CP_WAL_DIR`` set — a
  WAL-less victim has nothing to replay and dies permanently).
- ``kill_router`` — one admission router (serve/router.py) dies
  permanently starting roughly at ``step``, pinned by optional
  ``router`` index. Lowered to the ``kill_router`` chaos fault,
  whose ``after_requests`` counts the ROUTER'S OWN serve-plane
  requests — workload-dependent, so the step coordinate is a
  best-effort anchor, not the ~1-GET/step/rank mapping the
  control-plane faults enjoy.
- ``partition`` — netns link flap on fake host ``host`` between
  wall offsets ``at_ms`` and ``heal_ms`` (needs the FakeNet fabric;
  the chaos matrix runs these, everything else runs anywhere).

`CANNED` holds the standard trace suite (docs/fault_tolerance.md):
builders parameterized by cluster size so the goodput benchmark can
sweep np.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List

_EVENT_KINDS = ("preempt", "resize", "straggler", "flaky_control",
                "kill_replica", "restart_replica", "kill_router",
                "partition")

_REQUIRED = {
    "preempt": ("step",),
    "resize": ("step", "size"),
    "straggler": ("step", "rank", "duration_steps", "ms"),
    "flaky_control": ("step", "requests"),
    "kill_replica": ("step",),
    "restart_replica": ("step",),
    "kill_router": ("step",),
    "partition": ("host", "at_ms", "heal_ms"),
}


@dataclass
class Scenario:
    """A validated scenario spec. Plain data: nothing here may read
    clocks, env or tensors — the compiler derives the whole plan from
    these fields alone.

    ``hosts`` is the emulated-host layout: per-host worker-slot
    counts, in host-index order (``[2, 2]`` = two hosts of two slots —
    loopback aliases 127.0.0.1 + 127.0.0.2 at replay time). Empty =
    one host, the pre-existing single-runner shape. Host-scoped
    preempt events index into this list.

    ``workload`` selects what the cluster RUNS under the churn:
    ``"train"`` (default — the continuity trainer every pre-existing
    scenario replays) or ``"serve"`` (the kfserve decode tier,
    docs/serving.md: the replay submits live requests and gates on
    every one completing + the request-ledger invariants; a step is
    one decode iteration)."""

    name: str
    np0: int
    steps: int
    events: List[Dict] = field(default_factory=list)
    device_batch: int = 64
    seed: int = 0
    env: Dict[str, str] = field(default_factory=dict)
    description: str = ""
    hosts: List[int] = field(default_factory=list)
    workload: str = "train"

    def to_json(self) -> str:
        return json.dumps({
            "name": self.name, "np0": self.np0, "steps": self.steps,
            "events": self.events, "device_batch": self.device_batch,
            "seed": self.seed, "env": self.env,
            "description": self.description, "hosts": self.hosts,
            "workload": self.workload,
        }, sort_keys=True)


def load_scenario(spec) -> Scenario:
    """Parse + validate a scenario from a dict, JSON string, file path
    or canned name. Raises ValueError on anything malformed — a
    scenario that half-parses would replay a different trace than the
    one the operator recorded."""
    if isinstance(spec, Scenario):
        return spec
    if isinstance(spec, str):
        if spec in CANNED:
            return CANNED[spec]()
        if os.path.exists(spec):
            with open(spec, encoding="utf-8") as fh:
                spec = fh.read()
        try:
            spec = json.loads(spec)
        except ValueError as e:
            raise ValueError(
                f"scenario: not a canned name, file or JSON "
                f"({sorted(CANNED)} are canned): {e}") from e
    if not isinstance(spec, dict):
        raise ValueError(f"scenario: expected an object, got "
                         f"{type(spec).__name__}")
    name = spec.get("name")
    if not name or not isinstance(name, str):
        raise ValueError("scenario: 'name' (string) is required")
    np0 = int(spec.get("np0", 0))
    steps = int(spec.get("steps", 0))
    if np0 <= 0 or steps <= 0:
        raise ValueError(
            f"scenario {name!r}: np0 and steps must be positive "
            f"(np0={np0}, steps={steps})")
    events = spec.get("events", [])
    if not isinstance(events, list):
        raise ValueError(f"scenario {name!r}: 'events' must be a list")
    hosts = spec.get("hosts", [])
    if not isinstance(hosts, list) or not all(
            isinstance(h, int) and h > 0 for h in hosts):
        raise ValueError(
            f"scenario {name!r}: 'hosts' must be a list of positive "
            f"per-host slot counts (got {hosts!r})")
    if hosts:
        # capacity is plan data: a layout the timeline cannot fit
        # would boot the cluster and only fail mid-replay at a spawn
        peak = max([np0] + [int(e["size"]) for e in events
                            if isinstance(e, dict)
                            and e.get("kind") == "resize"
                            and "size" in e])
        if sum(hosts) < peak:
            raise ValueError(
                f"scenario {name!r}: hosts layout {hosts} has "
                f"{sum(hosts)} slot(s) but the timeline needs {peak}")
    for n, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"scenario {name!r}: event {n} is not an "
                             "object")
        kind = ev.get("kind")
        if kind not in _EVENT_KINDS:
            raise ValueError(
                f"scenario {name!r}: event {n} has unknown kind "
                f"{kind!r} (known: {_EVENT_KINDS})")
        for key in _REQUIRED[kind]:
            if key not in ev:
                raise ValueError(
                    f"scenario {name!r}: {kind} event {n} is missing "
                    f"required field {key!r}")
        if "step" in ev and not 0 <= int(ev["step"]) <= steps:
            raise ValueError(
                f"scenario {name!r}: {kind} event {n} step "
                f"{ev['step']} outside [0, {steps}]")
        if kind in ("kill_replica", "restart_replica"):
            role = str(ev.get("role", "leader"))
            if role not in ("leader", "follower"):
                raise ValueError(
                    f"scenario {name!r}: {kind} event {n} role "
                    f"{role!r} (known: leader, follower)")
            if ev.get("replica") is not None and int(ev["replica"]) < 0:
                raise ValueError(
                    f"scenario {name!r}: {kind} event {n} "
                    f"replica index must be >= 0")
        if kind == "kill_router" and ev.get("router") is not None \
                and int(ev["router"]) < 0:
            raise ValueError(
                f"scenario {name!r}: kill_router event {n} router "
                f"index must be >= 0")
        if kind == "preempt" and ev.get("host") is not None:
            if ev.get("rank") is not None:
                raise ValueError(
                    f"scenario {name!r}: preempt event {n} pins both "
                    "'rank' and 'host' — pick one scope")
            h = int(ev["host"])
            if not 0 <= h < max(len(hosts), 1):
                raise ValueError(
                    f"scenario {name!r}: preempt event {n} host {h} "
                    f"outside the declared hosts layout "
                    f"({len(hosts)} host(s)) — a half-parsed host "
                    "scope would replay a different trace")
            if len(hosts) < 2:
                raise ValueError(
                    f"scenario {name!r}: a host-scoped preempt needs "
                    "a multi-host 'hosts' layout (killing the only "
                    "host is a cluster preempt — say scope: cluster)")
    env = spec.get("env", {})
    if not isinstance(env, dict) or not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in env.items()):
        raise ValueError(f"scenario {name!r}: 'env' must map str->str")
    workload = str(spec.get("workload", "train"))
    if workload not in ("train", "serve"):
        raise ValueError(
            f"scenario {name!r}: unknown workload {workload!r} "
            "(known: train, serve)")
    if workload == "serve":
        # the serve replay is single-phase (the request ledger lives
        # in the replay process's config server): churn the decode
        # tier survives is in scope, churn that takes the control
        # plane with it is a different scenario
        for n, ev in enumerate(events):
            kind = ev.get("kind")
            if kind == "partition":
                continue  # refused at replay time like train's
            if kind == "preempt" and (
                    ev.get("rank") is None
                    or ev.get("scope") == "cluster"
                    or ev.get("host") is not None):
                raise ValueError(
                    f"scenario {name!r}: workload 'serve' supports "
                    f"rank-scoped preempts only (event {n} is "
                    "cluster/host-scoped: a whole-allocation serving "
                    "preemption needs a ledger-relaunch story that "
                    "is not modeled yet)")
    return Scenario(
        name=name, np0=np0, steps=steps,
        events=[dict(e) for e in events],
        device_batch=int(spec.get("device_batch", 64)),
        seed=int(spec.get("seed", 0)),
        env=dict(env),
        description=str(spec.get("description", "")),
        hosts=[int(h) for h in hosts],
        workload=workload,
    )


# -- the standard trace suite -------------------------------------------------

def spot_preempt(np0: int = 2) -> Scenario:
    """Spot reclaim of the whole allocation: the fabric warns 2 steps
    ahead, every worker is SIGKILLed at step 8, and the replacement
    allocation cold-boots from the durable checkpoint tier. The
    shortest canned scenario — the run-all.sh goodput gate replays it
    at np0=2. Lost work = the steps past the last complete generation,
    attributed from the victims' flight-recorder dumps."""
    return load_scenario({
        "name": "spot_preempt", "np0": np0, "steps": 12,
        "events": [
            {"kind": "preempt", "step": 8, "scope": "cluster",
             "lead_steps": 2},
        ],
        "env": {"KF_CKPT_EVERY": "3"},
        "description": "whole-allocation spot reclaim at step 8 "
                       "(2-step warning), cold restore from the "
                       "sharded checkpoint tier",
    })


def spot_kill_regrow(np0: int = 3) -> Scenario:
    """One worker preempted mid-step: survivors shrink through the
    recovery state machine, the schedule observes size < target and
    re-grows through the ordinary elastic path. Lost work = the
    survivors' discarded attempt at the failed step."""
    return load_scenario({
        "name": "spot_kill_regrow", "np0": np0, "steps": 12,
        "events": [
            {"kind": "preempt", "step": 5, "rank": np0 - 1,
             "lead_steps": 1},
        ],
        "description": "spot-preempt one worker at step 5; survivor "
                       "recovery + schedule-driven re-grow",
    })


def spot_host_kill(np0: int = 4) -> Scenario:
    """Whole-host spot reclamation: np0 ranks over two emulated hosts,
    and host 1 — master, leaves, shm rings and all — is reclaimed at
    step 6 with a 1-step warning. The cross-host survivors detect the
    burst (ring hello-EOF / socket error), ride the survivor-recovery
    path through the dead host's runner's single shrunken proposal,
    and the schedule re-grows back onto the reclaimed host. Lost work
    = the survivors' discarded attempt at the failed step, priced next
    to spot_kill_regrow's one-worker shape."""
    a = (np0 + 1) // 2
    return load_scenario({
        "name": "spot_host_kill", "np0": np0, "steps": 12,
        "hosts": [a, max(np0 - a, 1)],
        "events": [
            {"kind": "preempt", "step": 6, "host": 1, "lead_steps": 1},
        ],
        "description": "whole-host spot reclamation at step 6 "
                       "(1-step warning): every rank on host 1 dies "
                       "at once; survivor recovery + schedule-driven "
                       "re-grow onto the reclaimed host",
    })


def spot_serve_kill(np0: int = 2) -> Scenario:
    """Spot-preempt one DECODE worker mid-request (workload: serve,
    docs/serving.md): the victim's leased requests outlive it on the
    config server's ledger, survivors ride the recovery path, the
    schedule re-grows the tier, and the resumed leases finish every
    request — the serving analog of `spot_kill_regrow`, gated on the
    request-ledger invariants instead of loss continuity. Steps are
    decode iterations (fast next to train steps, hence the longer
    timeline)."""
    return load_scenario({
        "name": "spot_serve_kill", "np0": np0, "steps": 400,
        "workload": "serve",
        "events": [
            {"kind": "preempt", "step": 8, "rank": np0 - 1,
             "lead_steps": 1},
        ],
        "env": {"KF_SERVE_MAX_BATCH": "4",
                "KF_SERVE_LEASE_MS": "3000"},
        "description": "spot-preempt decode worker np0-1 at iteration "
                       "8 mid-request; lease expiry resumes its "
                       "requests on survivors, schedule re-grows the "
                       "tier, every request completes",
    })


def diurnal(np0: int = 2) -> Scenario:
    """Diurnal availability: capacity grows by one mid-run and drains
    back — the grow/shrink curve every preemptible pool walks daily.
    Pure planned resizes: the goodput decomposition prices the
    resync/adopt cost of following the curve."""
    return load_scenario({
        "name": "diurnal", "np0": np0, "steps": 15,
        "events": [
            {"kind": "resize", "step": 5, "size": np0 + 1},
            {"kind": "resize", "step": 10, "size": np0},
        ],
        "description": "grow to np0+1 at step 5, drain back at "
                       "step 10 (diurnal availability curve)",
    })


def straggler_transient(np0: int = 2) -> Scenario:
    """A transient slow host: the last rank sleeps 8x a clean CPU step
    for 4 steps, then recovers (thermal throttle / noisy neighbour
    shape). The policy question this scenario poses: pay a resize to
    shed the straggler, or ride it out? (`GoodputPolicy` vs
    `NaiveStragglerPolicy`, docs/fault_tolerance.md)."""
    return load_scenario({
        "name": "straggler_transient", "np0": np0, "steps": 14,
        "events": [
            {"kind": "straggler", "step": 5, "rank": np0 - 1,
             "duration_steps": 4, "ms": 120},
        ],
        "description": "rank np0-1 sleeps 120 ms/step for steps 5-8, "
                       "then recovers",
    })


def flaky_control(np0: int = 2) -> Scenario:
    """A flapping control plane: the config server delays then refuses
    requests mid-run. Training must ride the retry policy through it;
    goodput shows what the degradation cost."""
    return load_scenario({
        "name": "flaky_control", "np0": np0, "steps": 12,
        "events": [
            {"kind": "flaky_control", "step": 3, "requests": 4,
             "mode": "delay", "ms": 150},
            {"kind": "flaky_control", "step": 7, "requests": 2,
             "mode": "refuse", "status": 503},
        ],
        "description": "config server delays 4 requests then refuses "
                       "2 mid-run; the retry policy bridges it",
    })


def flaky_net(np0: int = 2) -> Scenario:
    """A flapping physical link: netns fake host 'a' drops its uplink
    for 2.5 s mid-run and heals inside the failure-detection deadline.
    Needs the FakeNet fabric (root + CAP_NET_ADMIN) — the chaos
    matrix member of the suite."""
    return load_scenario({
        "name": "flaky_net", "np0": np0, "steps": 40,
        "events": [
            {"kind": "partition", "host": "a", "at_ms": 3000,
             "heal_ms": 5500},
        ],
        "description": "veth link down 3.0-5.5 s into the run; TCP "
                       "retransmits bridge the flap (netns only)",
    })


#: the standard trace suite: name -> builder(np0). `benchmarks/
#: goodput.py` sweeps these across cluster sizes and publishes the
#: decomposition rows to BASELINE; run-all.sh gates on the first.
CANNED = {
    "spot_preempt": spot_preempt,
    "spot_kill_regrow": spot_kill_regrow,
    "spot_host_kill": spot_host_kill,
    "spot_serve_kill": spot_serve_kill,
    "diurnal": diurnal,
    "straggler_transient": straggler_transient,
    "flaky_control": flaky_control,
    "flaky_net": flaky_net,
}
