"""Replay a compiled scenario through the real elastic runtime.

`run_scenario` drives each `ScenarioPhase` of a `ScenarioPlan` through
the same harness every elastic e2e already uses
(`elastic.harness._run_continuity_cluster`: config server + kfrun
watcher + the continuity trainer) with ``KF_TRACE=1`` pointed at one
shared trace directory — so the replay's only artifact of record is
the kftrace stream, and `python -m kungfu_tpu.trace --dir D --goodput`
produces the scenario's goodput decomposition with zero
scenario-aware code in the hot path.

Phase mechanics:

- every phase gets a FRESH config server (a whole-allocation
  preemption takes the control plane with it; a relaunch starts its
  own) and a fresh launch of the SAME absolute schedule — a cold-boot
  phase resumes from the durable checkpoint tier, so the restored
  step indexes into the schedule unchanged.
- ``delay_http``/``refuse_http``/``die_config_server`` faults fire in
  the config-server process — which is THIS process — so the runner
  installs the phase's chaos schedule in-process (`chaos.load`)
  around the phase and disarms it after. Worker-side faults ride the
  ``KF_CHAOS`` env into the workers as usual.
- marker assertions per phase are the minimal liveness set (the
  deep continuity/recovery assertions live in the trainer itself and
  exit nonzero on violation): scheduled worker faults fired, pre-kill
  checkpoint generations landed, cold boots restored, the final phase
  completed.

``partition`` events need the netns fault fabric (root +
CAP_NET_ADMIN) and a multi-host launch — the chaos matrix's territory
(scripts/chaos.sh, tests/test_churn.py). `run_scenario` refuses them
with `ScenarioUnsupported` instead of silently replaying a different
scenario than the spec describes.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .. import chaos
from ..elastic.schedule import parse_schedule
from .compiler import ScenarioPlan, compile_scenario

#: fault types that fire inside worker processes (KF_CHAOS env path);
#: http faults fire in the config-server process instead
_WORKER_FAULTS = ("crash_worker", "crash_host", "straggler_worker",
                  "preempt_warning")
_HTTP_FAULTS = ("delay_http", "refuse_http", "die_config_server")


class ScenarioUnsupported(RuntimeError):
    """The environment cannot faithfully replay this scenario."""


@dataclass
class ScenarioRun:
    """What a replay left behind: the plan it executed, per-phase
    combined logs, the shared trace/checkpoint dirs, and wall times
    (`relaunch_gap_s` is the orchestration time BETWEEN phases — the
    operator-visible downtime a whole-allocation preemption costs on
    top of what the workers' own traces cover)."""

    plan: ScenarioPlan
    trace_dir: str
    ckpt_dir: str
    phase_logs: Tuple[str, ...]
    phase_wall_s: Tuple[float, ...]
    wall_s: float
    relaunch_gap_s: float
    policy: str = ""

    @property
    def logs(self) -> str:
        return "\n".join(self.phase_logs)


def _max_cluster_size(plan: ScenarioPlan) -> int:
    size = max((ph.np0 for ph in plan.phases), default=1)
    for ph in plan.phases:
        if ph.schedule:
            size = max(size, max(s for _, s in parse_schedule(ph.schedule)))
    return size


def _phase_markers(plan: ScenarioPlan, phase, is_last: bool
                   ) -> List[Tuple[str, str]]:
    markers: List[Tuple[str, str]] = []
    faults = (phase.chaos or {}).get("faults", [])
    if any(f.get("type") in _WORKER_FAULTS for f in faults):
        markers.append(("KF_CHAOS_FIRE",
                        "a scheduled worker fault never fired"))
    if plan.needs_ckpt and phase.expect_rc != 0:
        markers.append(("KF_CKPT_SAVED",
                        "no checkpoint generation landed before the "
                        "whole-cluster kill"))
    if phase.cold_boot:
        markers.append(("KF_RESTORE_CONTINUITY",
                        "cold boot did not restore from the "
                        "checkpoint tier"))
    if is_last and phase.expect_rc == 0:
        if plan.needs_recover:
            markers.append(("KF_RECOVERY_DONE",
                            "no survivor completed recovery"))
        markers.append(("KF_CONTINUITY_DONE",
                        "the scenario's training run did not complete"))
    return markers


def _run_serve_scenario(plan: ScenarioPlan, *, trace_dir: str,
                        logdir: Optional[str],
                        port_range: str, timeout: int,
                        extra_env: Optional[Dict[str, str]]
                        ) -> ScenarioRun:
    """Replay a workload="serve" plan through the kfserve harness:
    same compiled artifacts (schedule string, chaos schedule, env
    arming), but the cluster runs decode workers against live
    requests and the gate is the request ledger — every submitted
    request completes, zero invariant violations — instead of loss
    continuity. Single-phase by construction (spec.py refuses
    cluster/host preempts under workload serve)."""
    from ..serve.harness import SERVE_MARKERS, default_requests
    from ..serve.harness import run_serve_cluster

    assert len(plan.phases) == 1, plan
    phase = plan.phases[0]
    os.makedirs(trace_dir, exist_ok=True)
    env = {
        "KF_TRACE": "1",
        "KF_TRACE_DIR": trace_dir,
        "KF_CHAOS": (json.dumps(phase.chaos) if phase.chaos else ""),
        "KF_CHAOS_FILE": "",
        **phase.env,
        **(extra_env or {}),
    }
    faults = (phase.chaos or {}).get("faults", [])
    markers = SERVE_MARKERS
    if any(f.get("type") in _WORKER_FAULTS for f in faults):
        markers = markers + (
            ("KF_CHAOS_FIRE", "a scheduled worker fault never fired"),)
    t0 = time.perf_counter()
    out = run_serve_cluster(
        # enough in-flight tokens that the scheduled churn lands
        # mid-request (the gate below is completion, not timing)
        default_requests(5 * phase.np0, gen_len=48),
        schedule=phase.schedule,
        start_np=phase.np0,
        port_range=port_range,
        timeout=timeout,
        logdir=logdir,
        markers=markers,
        extra_env=env,
        recover=plan.needs_recover,
    )
    wall = time.perf_counter() - t0
    return ScenarioRun(
        plan=plan,
        trace_dir=trace_dir,
        ckpt_dir="",
        phase_logs=(out["logs"],),
        phase_wall_s=(round(out["wall_s"], 3),),
        wall_s=round(wall, 3),
        relaunch_gap_s=0.0,
    )


def run_scenario(scenario, *, trace_dir: str,
                 ckpt_dir: str = "",
                 logdir: Optional[str] = None,
                 policy: str = "",
                 port_range: str = "27100-27999",
                 timeout: int = 420,
                 extra_env: Optional[Dict[str, str]] = None
                 ) -> ScenarioRun:
    """Compile `scenario` (a Scenario / dict / JSON / canned name) and
    replay every phase. Raises AssertionError (phase rc or marker
    violation) or `ScenarioUnsupported` (netns windows outside the
    chaos matrix). `policy` selects the trainer's adaptation policy
    (``KF_POLICY``: "goodput" / "naive_straggler"; empty = the
    compiled schedule drives)."""
    from ..elastic.config_server import ConfigServer
    from ..elastic.harness import _run_continuity_cluster

    plan = compile_scenario(scenario)
    if plan.netns_windows:
        raise ScenarioUnsupported(
            f"scenario {plan.name!r} carries netns partition windows "
            "— replay it through the chaos matrix (scripts/chaos.sh, "
            "FakeNet), not the loopback runner")
    if plan.workload == "serve":
        return _run_serve_scenario(
            plan, trace_dir=trace_dir, logdir=logdir,
            port_range=port_range, timeout=timeout,
            extra_env=extra_env)

    os.makedirs(trace_dir, exist_ok=True)
    if plan.needs_ckpt and not ckpt_dir:
        ckpt_dir = os.path.join(trace_dir, "ckpt")
    if ckpt_dir:
        os.makedirs(ckpt_dir, exist_ok=True)

    slots = _max_cluster_size(plan)
    phase_logs: List[str] = []
    phase_wall: List[float] = []
    t_run0 = time.perf_counter()
    busy = 0.0
    for i, phase in enumerate(plan.phases):
        is_last = i == len(plan.phases) - 1
        env = {
            "KF_TRACE": "1",
            "KF_TRACE_DIR": trace_dir,
            # the trainer must train at the batch the goodput
            # decomposition will multiply useful steps by
            "TEST_DEVICE_BATCH": str(plan.device_batch),
            # explicit empties so a caller's environment cannot leak a
            # different schedule into the replay
            "KF_CHAOS": (json.dumps(phase.chaos) if phase.chaos else ""),
            "KF_CHAOS_FILE": "",
            "KF_POLICY": policy,
            **phase.env,
            **(extra_env or {}),
        }
        if ckpt_dir:
            env["KF_CKPT_DIR"] = ckpt_dir
        http_faults = any(f.get("type") in _HTTP_FAULTS
                          for f in (phase.chaos or {}).get("faults", []))
        phase_logdir = None
        if logdir is not None:
            phase_logdir = os.path.join(logdir, f"phase{i}")
            os.makedirs(phase_logdir, exist_ok=True)
        server = ConfigServer(port=0).start()
        if http_faults:
            # http faults fire in the server's handler threads — this
            # process; worker-side state is untouched (each worker
            # parses its own KF_CHAOS)
            chaos.load(phase.chaos)
        try:
            t0 = time.perf_counter()
            logs = _run_continuity_cluster(
                schedule=phase.schedule,
                total_steps=phase.total_steps,
                start_np=phase.np0,
                slots=slots,
                port_range=port_range,
                timeout=timeout,
                logdir=phase_logdir,
                markers=_phase_markers(plan, phase, is_last),
                extra_env=env,
                extra_flags=(["-recover"] if plan.needs_recover
                             else None),
                expect_rc=phase.expect_rc,
                server=server,
                # multi-host scenarios (host-scoped preempts) launch
                # one kfrun per emulated host so each host has a real
                # supervisor to detect its own deaths
                hosts=plan.hosts,
            )
        finally:
            if http_faults:
                chaos.load(None)
            server.stop()
        dt = time.perf_counter() - t0
        busy += dt
        phase_wall.append(round(dt, 3))
        phase_logs.append(logs)
    wall = time.perf_counter() - t_run0
    return ScenarioRun(
        plan=plan,
        trace_dir=trace_dir,
        ckpt_dir=ckpt_dir,
        phase_logs=tuple(phase_logs),
        phase_wall_s=tuple(phase_wall),
        wall_s=round(wall, 3),
        relaunch_gap_s=round(max(0.0, wall - busy), 3),
        policy=policy,
    )
