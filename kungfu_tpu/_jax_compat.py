"""JAX version compatibility shims.

The codebase targets modern JAX (`jax.shard_map`, whose replication
check keyword is `check_vma`); the pinned toolchain in some build
images ships 0.4.x, where the API lives at
`jax.experimental.shard_map.shard_map` and the keyword is `check_rep`.
Importing this module installs a `jax.shard_map` attribute when it is
absent, translating the keyword — so `from jax import shard_map`
works identically on both toolchains.

Kept OUT of `kungfu_tpu/__init__.py` (and the `benchmarks` package
init, whose kfrun-spawned allreduce workers are deliberately
numpy-only) on purpose: the control-plane path must stay jax-free at
import time, so this shim is imported by `parallel/__init__.py`, the
jax-facing benchmark/example entry points that touch `jax.shard_map`
before importing `parallel`, and the test conftest instead.
"""

from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    def _compat_shard_map(f, /, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, *args, **kwargs)

    jax.shard_map = _compat_shard_map

if not hasattr(jax.lax, "axis_size"):
    # modern lax.axis_size(name) returns the STATIC bound-axis size;
    # on 0.4.x the same information lives in the core axis env
    from jax._src.core import get_axis_env as _get_axis_env

    def _compat_axis_size(axis_name, /):
        return _get_axis_env().axis_size(axis_name)

    jax.lax.axis_size = _compat_axis_size
