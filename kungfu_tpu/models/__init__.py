"""Model zoo for benchmarks and examples.

The reference benchmarks synthetic training on ResNet-50 / VGG16 /
InceptionV3 / BERT tensor catalogs (reference: benchmarks/system/,
srcs/python/kungfu/tensorflow/v1/benchmarks/model_sizes.py,
tests/go/fakemodel/). Here the models are real flax modules — TPU-first:
bfloat16 activations by default, channels-last layouts, shapes aligned to
the 128x128 MXU — and the "fake model" tensor catalogs are derived from
the real modules via jax.eval_shape, so microbenchmarks and unit tests
stay in exact parity with the architectures.
"""

from .bert import BertConfig, BertEncoder
from .fake_models import fake_model_catalog, model_param_sizes
from .gpt import (GPTConfig, GPTLM, gpt_fused_loss, gpt_generate,
                  gpt_loss, gpt_loss_with_aux, gpt_pipeline_forward,
                  stack_gpt_blocks)
from .inception import InceptionV3
from .mlp import MLP, SLP
from .resnet import ResNet, ResNet18, ResNet50, ResNet101
from .vgg import VGG16

__all__ = [
    "SLP",
    "MLP",
    "ResNet",
    "ResNet18",
    "ResNet50",
    "ResNet101",
    "VGG16",
    "InceptionV3",
    "BertConfig",
    "BertEncoder",
    "GPTConfig",
    "GPTLM",
    "gpt_fused_loss",
    "gpt_generate",
    "gpt_loss",
    "gpt_loss_with_aux",
    "gpt_pipeline_forward",
    "stack_gpt_blocks",
    "fake_model_catalog",
    "model_param_sizes",
]
