"""ResNet v1.5 — the flagship benchmark model.

TPU-first flax implementation of the architecture the reference benchmarks
throughput on (reference: benchmarks/system/benchmark_kungfu.py uses
tf.keras ResNet50). Design choices for the MXU/HBM:

- bfloat16 activations and conv weights by default (`dtype`), float32
  batch-norm statistics and softmax — the standard mixed-precision recipe
  that keeps matmuls on the 128x128 MXU at full rate;
- NHWC layout (XLA:TPU's native conv layout);
- no Python-level control flow in the forward pass, so the whole model
  compiles to one XLA computation.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        # v1.5: stride lives on the 3x3, not the 1x1
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: Callable
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    # Space-to-depth stem (the MLPerf TPU ResNet trick): rearrange the
    # input [B,H,W,3] into [B,H/2,W/2,12] and run the stem conv at
    # stride 1 with a 4x4 kernel. Same receptive-field family as
    # 7x7/s2, but the input feeds the MXU 12 channels at a time instead
    # of 3, and the strided gather disappears.
    space_to_depth: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       padding="SAME")
        # BatchNorm computes in the model dtype (bf16) but keeps its
        # scale/bias/running stats in f32 (param_dtype), and flax computes
        # batch mean/var in f32 internally — the standard TPU recipe.
        # Running BN in f32 end-to-end costs ~20% step time: the whole
        # BN+relu elementwise chain then moves f32 activations through HBM
        # (63.4 ms -> 50.4 ms per b=128 step on a v5e chip; the published
        # run in docs/benchmarks.md "Single-chip roofline").
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            axis_name=None,
        )
        x = x.astype(self.dtype)
        if self.space_to_depth:
            b, h, w, c = x.shape
            x = x.reshape(b, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
                b, h // 2, w // 2, 4 * c)
            x = conv(self.num_filters, (4, 4), (1, 1),
                     name="conv_init_s2d")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                   block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckBlock)
