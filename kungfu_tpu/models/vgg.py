"""VGG16 — the bandwidth-heavy benchmark model.

VGG's ~138M parameters make it the all-reduce stress test in the
reference's scalability benchmarks (reference: benchmarks/system/
README.md). bfloat16 activations, NHWC, f32 classifier head.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class VGG16(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    stage_filters: Sequence[int] = (64, 128, 256, 512, 512)
    stage_convs: Sequence[int] = (2, 2, 3, 3, 3)

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for filters, convs in zip(self.stage_filters, self.stage_convs):
            for _ in range(convs):
                x = nn.Conv(filters, (3, 3), padding="SAME",
                            dtype=self.dtype)(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
