"""InceptionV3 — third model of the reference's headline benchmark trio.

The reference's sync-scalability plot benchmarks ResNet-50, VGG16 and
InceptionV3 (reference: README.md:197-205, benchmarks/system/result/
sync-scalability.svg, via tf.keras applications). TPU-first flax build:
bfloat16 activations/weights with float32 BatchNorm statistics, NHWC,
no Python control flow dependent on data — the same recipe as
`models/resnet.py`. Architecture per "Rethinking the Inception
Architecture" (Szegedy et al. 2015), 299x299 input, no aux head (the
benchmarks train the main loss only).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ConvBN(nn.Module):
    """conv -> BN -> relu, the basic Inception unit."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype,
                         param_dtype=jnp.float32)(x)
        return nn.relu(x)


def _maxpool(x, window, strides, padding="VALID"):
    return nn.max_pool(x, (window, window), (strides, strides), padding)


def _avgpool3(x):
    return nn.avg_pool(x, (3, 3), (1, 1), "SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        b1 = conv(64, (1, 1))(x, train)
        b5 = conv(48, (1, 1))(x, train)
        b5 = conv(64, (5, 5))(b5, train)
        b3 = conv(64, (1, 1))(x, train)
        b3 = conv(96, (3, 3))(b3, train)
        b3 = conv(96, (3, 3))(b3, train)
        bp = conv(self.pool_features, (1, 1))(_avgpool3(x), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35x35 -> 17x17."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        b3 = conv(384, (3, 3), (2, 2), padding="VALID")(x, train)
        bd = conv(64, (1, 1))(x, train)
        bd = conv(96, (3, 3))(bd, train)
        bd = conv(96, (3, 3), (2, 2), padding="VALID")(bd, train)
        bp = _maxpool(x, 3, 2)
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7x7 branches at 17x17."""

    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        c7 = self.channels_7x7
        b1 = conv(192, (1, 1))(x, train)
        b7 = conv(c7, (1, 1))(x, train)
        b7 = conv(c7, (1, 7))(b7, train)
        b7 = conv(192, (7, 1))(b7, train)
        bd = conv(c7, (1, 1))(x, train)
        bd = conv(c7, (7, 1))(bd, train)
        bd = conv(c7, (1, 7))(bd, train)
        bd = conv(c7, (7, 1))(bd, train)
        bd = conv(192, (1, 7))(bd, train)
        bp = conv(192, (1, 1))(_avgpool3(x), train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17x17 -> 8x8."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        b3 = conv(192, (1, 1))(x, train)
        b3 = conv(320, (3, 3), (2, 2), padding="VALID")(b3, train)
        b7 = conv(192, (1, 1))(x, train)
        b7 = conv(192, (1, 7))(b7, train)
        b7 = conv(192, (7, 1))(b7, train)
        b7 = conv(192, (3, 3), (2, 2), padding="VALID")(b7, train)
        bp = _maxpool(x, 3, 2)
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """Expanded-filter-bank blocks at 8x8."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        b1 = conv(320, (1, 1))(x, train)
        b3 = conv(384, (1, 1))(x, train)
        b3 = jnp.concatenate([conv(384, (1, 3))(b3, train),
                              conv(384, (3, 1))(b3, train)], axis=-1)
        bd = conv(448, (1, 1))(x, train)
        bd = conv(384, (3, 3))(bd, train)
        bd = jnp.concatenate([conv(384, (1, 3))(bd, train),
                              conv(384, (3, 1))(bd, train)], axis=-1)
        bp = conv(192, (1, 1))(_avgpool3(x), train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        x = jnp.asarray(x, self.dtype)
        # stem: 299 -> 35x35x192
        x = conv(32, (3, 3), (2, 2), padding="VALID")(x, train)
        x = conv(32, (3, 3), padding="VALID")(x, train)
        x = conv(64, (3, 3))(x, train)
        x = _maxpool(x, 3, 2)
        x = conv(80, (1, 1), padding="VALID")(x, train)
        x = conv(192, (3, 3), padding="VALID")(x, train)
        x = _maxpool(x, 3, 2)
        # 3x A (35x35) -> B -> 4x C (17x17) -> D -> 2x E (8x8)
        x = InceptionA(32, self.dtype)(x, train)
        x = InceptionA(64, self.dtype)(x, train)
        x = InceptionA(64, self.dtype)(x, train)
        x = InceptionB(self.dtype)(x, train)
        x = InceptionC(128, self.dtype)(x, train)
        x = InceptionC(160, self.dtype)(x, train)
        x = InceptionC(160, self.dtype)(x, train)
        x = InceptionC(192, self.dtype)(x, train)
        x = InceptionD(self.dtype)(x, train)
        x = InceptionE(self.dtype)(x, train)
        x = InceptionE(self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        # classifier in f32 for a numerically stable softmax
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
