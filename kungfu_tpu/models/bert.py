"""BERT encoder — the transformer benchmark model.

The reference benchmarks all-reduce over BERT's tensor catalog
(reference: srcs/python/kungfu/tensorflow/v1/benchmarks/model_sizes.py,
tests/cpp/integration/bert.hpp). Here it is a real flax encoder:
bfloat16 matmuls sized for the MXU (hidden 768 = 6x128, heads 12x64);
layernorms compute in bf16 with f32 scale/bias (flax reduces LN mean/var
in f32 internally), so residual-stream activations stay 2 bytes/elem in
HBM; only the logits head is f32.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    dtype: Any = jnp.bfloat16


class TransformerLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask=None):
        c = self.config
        y = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        y = nn.MultiHeadDotProductAttention(
            num_heads=c.num_heads,
            dtype=c.dtype,
            qkv_features=c.hidden_size,
        )(y, y, mask=mask)
        x = x + y
        y = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        y = nn.Dense(c.intermediate_size, dtype=c.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(c.hidden_size, dtype=c.dtype)(y)
        return x + y


class BertEncoder(nn.Module):
    """Token ids -> contextual embeddings [+ MLM-style logits head]."""

    config: BertConfig = BertConfig()  # frozen dataclass: hashable default

    @nn.compact
    def __call__(self, token_ids, mask=None):
        c = self.config
        pos = jnp.arange(token_ids.shape[-1])[None, :]
        x = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype)(token_ids)
        x = x + nn.Embed(c.max_position, c.hidden_size,
                         dtype=c.dtype)(pos)
        x = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        for _ in range(c.num_layers):
            x = TransformerLayer(c)(x, mask=mask)
        x = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        return nn.Dense(c.vocab_size, dtype=jnp.float32)(x)
