"""BERT encoder — the transformer benchmark model.

The reference benchmarks all-reduce over BERT's tensor catalog
(reference: srcs/python/kungfu/tensorflow/v1/benchmarks/model_sizes.py,
tests/cpp/integration/bert.hpp). Here it is a real flax encoder:
bfloat16 matmuls sized for the MXU (hidden 768 = 6x128, heads 12x64);
layernorms compute in bf16 with f32 scale/bias (flax reduces LN mean/var
in f32 internally), so residual-stream activations stay 2 bytes/elem in
HBM; only the logits head is f32.

Long context: `BertConfig(attention="ring"|"ulysses", seq_axis=...)`
swaps the attention mixer for a sequence-parallel one from
`kungfu_tpu.parallel.sequence` — the encoder then expects to run INSIDE
`shard_map` with the sequence axis sharded over `seq_axis` (token_ids
are the LOCAL shard; positions are computed globally via the axis
index). Padding masks are unsupported in the sequence-parallel modes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    dtype: Any = jnp.bfloat16
    attention: str = "local"  # local | ring | ulysses
    seq_axis: str = "seq"     # mesh axis for the sequence-parallel modes
    # run the sharded mixer's local step through the Pallas flash
    # kernel (ring: flash per hop; ulysses: flash over the head subset)
    use_flash: bool = False

    def __post_init__(self):
        if self.attention not in ("local", "ring", "ulysses"):
            raise ValueError(
                f"attention must be local|ring|ulysses, got "
                f"{self.attention!r}")
        if self.use_flash and self.attention == "local":
            raise ValueError(
                "use_flash modifies the 'ring'/'ulysses' mixers; it "
                "does nothing for attention='local'")


class SeqParallelAttention(nn.Module):
    """Multi-head attention whose position mixing runs across the mesh's
    sequence axis (ring or Ulysses), bidirectional like BERT."""

    config: BertConfig

    @nn.compact
    def __call__(self, x):
        from ..parallel.sequence import ring_attention, ulysses_attention

        c = self.config
        h, d = c.num_heads, c.hidden_size // c.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (h, d), dtype=c.dtype, name=name)
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        mixer = (ring_attention if c.attention == "ring"
                 else ulysses_attention)
        out = mixer(q, k, v, c.seq_axis, causal=False,
                    use_flash=c.use_flash)
        return nn.DenseGeneral(c.hidden_size, axis=(-2, -1), dtype=c.dtype,
                               name="out")(out)


class TransformerLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask=None):
        c = self.config
        y = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        if c.attention == "local":
            y = nn.MultiHeadDotProductAttention(
                num_heads=c.num_heads,
                dtype=c.dtype,
                qkv_features=c.hidden_size,
            )(y, y, mask=mask)
        else:
            if mask is not None:
                raise ValueError(
                    "padding masks are unsupported with sequence-parallel "
                    f"attention ({c.attention})")
            y = SeqParallelAttention(c)(y)
        x = x + y
        y = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        y = nn.Dense(c.intermediate_size, dtype=c.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(c.hidden_size, dtype=c.dtype)(y)
        return x + y


class BertEncoder(nn.Module):
    """Token ids -> contextual embeddings [+ MLM-style logits head]."""

    config: BertConfig = BertConfig()  # frozen dataclass: hashable default

    @nn.compact
    def __call__(self, token_ids, mask=None):
        c = self.config
        local_len = token_ids.shape[-1]
        if c.attention == "local":
            pos = jnp.arange(local_len)[None, :]
        else:
            # sequence-sharded: this device holds positions
            # [rank*local_len, (rank+1)*local_len)
            global_len = local_len * lax.axis_size(c.seq_axis)
            if global_len > c.max_position:
                # nn.Embed would silently clamp the tail positions
                raise ValueError(
                    f"global sequence {global_len} exceeds max_position "
                    f"{c.max_position}; raise BertConfig.max_position")
            rank = lax.axis_index(c.seq_axis)
            pos = (rank * local_len + jnp.arange(local_len))[None, :]
        x = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype)(token_ids)
        x = x + nn.Embed(c.max_position, c.hidden_size,
                         dtype=c.dtype)(pos)
        x = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        for _ in range(c.num_layers):
            x = TransformerLayer(c)(x, mask=mask)
        x = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        return nn.Dense(c.vocab_size, dtype=jnp.float32)(x)
