"""MNIST-scale models: the reference's example workloads.

SLP matches the single-layer perceptron of the reference's MNIST examples
(reference: examples/tf2_mnist_gradient_tape.py — the v0 end-to-end
slice); MLP is the deeper variant used in convergence tests.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class SLP(nn.Module):
    """Single-layer perceptron: flatten -> dense softmax head."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class MLP(nn.Module):
    features: Sequence[int] = (128, 128)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = nn.relu(nn.Dense(f)(x))
        return nn.Dense(self.num_classes)(x)
