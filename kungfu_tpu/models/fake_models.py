"""Fake-model tensor catalogs for ML-free communication benchmarks.

The reference registers hand-written tensor-size lists per architecture
(reference: tests/go/fakemodel/fakemodel.go:12-17, resnet50-imagenet.go,
vgg16-imagenet.go, bert.go). Here the catalogs are *derived* from the
real flax modules with jax.eval_shape — zero FLOPs, no weights
materialized — so the microbenchmark traffic pattern is exactly the real
model's parameter set and can never drift from the architecture.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp


@lru_cache(maxsize=None)
def model_param_sizes(name: str) -> List[Tuple[str, Tuple[int, ...]]]:
    """[(param_path, shape), ...] for a named catalog model."""
    from . import (MLP, SLP, BertConfig, BertEncoder, InceptionV3,
                   ResNet50, VGG16)

    def shapes_of(module, sample):
        variables = jax.eval_shape(
            lambda: module.init(jax.random.PRNGKey(0), sample))
        out = []
        flat = jax.tree_util.tree_flatten_with_path(variables["params"])[0]
        for path, leaf in flat:
            key = "/".join(str(p.key) for p in path
                           if hasattr(p, "key"))
            out.append((key, tuple(leaf.shape)))
        return out

    img = jnp.zeros((1, 224, 224, 3), jnp.float32)
    if name == "resnet50-imagenet":
        return shapes_of(ResNet50(num_classes=1000), img)
    if name == "vgg16-imagenet":
        return shapes_of(VGG16(num_classes=1000), img)
    if name == "inception3-imagenet":
        return shapes_of(InceptionV3(num_classes=1000),
                         jnp.zeros((1, 299, 299, 3), jnp.float32))
    if name == "bert-base":
        cfg = BertConfig(num_layers=12)
        return shapes_of(BertEncoder(cfg),
                         jnp.zeros((1, 128), jnp.int32))
    if name == "mlp-mnist":
        return shapes_of(MLP(), jnp.zeros((1, 28, 28, 1), jnp.float32))
    if name == "slp-mnist":
        return shapes_of(SLP(), jnp.zeros((1, 28, 28, 1), jnp.float32))
    raise ValueError(f"unknown fake model: {name}")


CATALOG = ["resnet50-imagenet", "vgg16-imagenet", "inception3-imagenet",
           "bert-base", "mlp-mnist", "slp-mnist"]


def fake_model_catalog(name: str, fuse: bool = False) -> Dict[str, int]:
    """{tensor_name: element_count}; fuse=True packs everything into one
    buffer like the reference's fused mode (fakemodel.go:53-57)."""
    sizes = model_param_sizes(name)
    counts = {}
    for key, shape in sizes:
        n = 1
        for d in shape:
            n *= d
        counts[key] = n
    if fuse:
        return {f"{name}-fused": sum(counts.values())}
    return counts
