"""GPT — decoder-only transformer LM, the composed-parallelism flagship.

Beyond the reference's scope (SURVEY §2.9: the reference trains
data-parallel only and ships no language models), this model is built
to exercise every parallel axis the framework provides, composed:

- **dp x tp** (the Megatron recipe): shard the parameters with
  `parallel.tensor.shard_params(params, mesh, gpt_tp_rules())` over a
  ("data", "model") mesh and jit the train step — GSPMD inserts the
  all-gathers/reduce-scatters on ICI. Attention projections and the MLP
  use fixed module names (query/key/value/out, Dense_0/Dense_1 inside
  `Block`) so the sharding rules match by path.
- **sequence parallelism**: `GPTConfig(attention="ring"|"ulysses")`
  swaps the mixer for the causal sequence-parallel ones in
  `parallel.sequence`; the model then runs INSIDE `shard_map` with
  token shards, like `models/bert.py`.
- **flash**: `GPTConfig(attention="flash")` runs the Pallas kernel
  (`ops/flash.py`) for the local causal mixer — O(T) HBM both
  directions, for contexts whose [T, T] scores don't fit.

Norm/dtype conventions follow `models/bert.py`: bf16 matmuls and
residual stream, f32 LayerNorm scale/bias, f32 logits head.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

_ATTN_MODES = ("local", "flash", "ring", "ulysses")


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 1024
    dtype: Any = jnp.bfloat16
    attention: str = "local"  # local | flash | ring | ulysses
    seq_axis: str = "seq"     # mesh axis for the sequence-parallel modes
    # Mixture-of-experts FFN (Switch top-1): 0 = dense MLP. Expert
    # stacks are GLOBAL arrays [E, H, F]; shard them over a mesh axis
    # with `parallel.tensor.gpt_moe_rules` and GSPMD lowers the
    # dispatch/combine einsums to all-to-alls — no shard_map needed.
    num_experts: int = 0
    moe_capacity_factor: float = 1.25
    # Router training signals (sown into the "losses" collection by
    # MoEMLP; `gpt_loss_with_aux` folds them into the objective). The
    # Switch load-balance loss keeps expert load near-uniform — without
    # it a top-1 router collapses onto few experts and the capacity drop
    # eats the tokens; the z-loss keeps router logits small. Defaults
    # follow Switch/ST-MoE (1e-2, 1e-3).
    moe_aux_coef: float = 1e-2
    moe_z_coef: float = 1e-3
    # attention="ulysses"|"ring": run the sharded mixer's local step
    # through the Pallas flash kernel. Ulysses trains end-to-end via
    # tiled=True all-to-alls (docs/long_context.md has the upstream-bug
    # repro the layout sidesteps); ring runs flash per hop with a
    # hand-written global-lse backward (parallel/sequence.py).
    use_flash: bool = False
    # routing group size (GShard/Switch): tokens route within fixed-size
    # groups so dispatch/combine tensors stay LINEAR in total tokens
    # (~cf * group entries per token) instead of quadratic. 0 = auto
    # (512, shrunk to fit); groups that don't divide B*T fall back to
    # one group per batch row.
    moe_group_size: int = 0
    # storage dtype of the expert stacks (w_up/w_down). None keeps f32
    # master weights and casts to `dtype` in apply — the safe default.
    # bfloat16 stores them in compute precision: at 8 experts/layer the
    # f32 stacks are 8x the dense FFN's, and the per-step f32 read
    # (+ cast) is pure HBM traffic the MXU never needed. NOTE: optax
    # moments follow the UPDATE dtype, so bf16 grads give bf16 mu AND
    # nu, and a bf16 nu freezes once 0.001*g^2 rounds below bf16's 8
    # mantissa bits — upcast gradients to f32 before adam (see
    # benchmarks/lm.py) to keep both moments f32 while params stay
    # bf16.
    moe_param_dtype: Any = None
    # rematerialize each Block in the backward (jax.checkpoint via
    # nn.remat): trades one extra forward's FLOPs per block for not
    # storing its activations — the knob to try before concluding a
    # batch size is HBM-capacity-bound (gpt2-medium b=16 diagnosis,
    # round-5). decode/prefill are static so the KV-cache paths are
    # unaffected.
    remat: bool = False

    def __post_init__(self):
        if self.attention not in _ATTN_MODES:
            raise ValueError(
                f"attention must be one of {_ATTN_MODES}, got "
                f"{self.attention!r}")
        if self.hidden_size % self.num_heads:
            raise ValueError(
                f"hidden {self.hidden_size} % heads {self.num_heads} != 0")
        if self.use_flash and self.attention not in ("ulysses", "ring",
                                                     "flash"):
            raise ValueError(
                "use_flash modifies the 'ulysses' and 'ring' mixers; "
                f"for attention={self.attention!r} use attention='flash' "
                "instead (the non-sharded flash mode, where the flag is "
                "redundant but accepted)")


class CausalSelfAttention(nn.Module):
    """Multi-head causal self-attention with a pluggable mixer.

    Projection modules are named (query/key/value/out) so
    `parallel.tensor.gpt_tp_rules` can target them by path.
    """

    config: GPTConfig

    @nn.compact
    def __call__(self, x, decode: bool = False, prefill: bool = False):
        c = self.config
        h, d = c.num_heads, c.hidden_size // c.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (h, d), dtype=c.dtype, name=name)
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        if prefill:
            # one batched causal pass over the whole prompt that ALSO
            # fills the KV cache — time-to-first-token is one forward,
            # not T0 sequential decode steps
            is_initialized = self.has_variable("cache", "k")
            b, t0 = x.shape[0], x.shape[1]
            ck = self.variable("cache", "k", jnp.zeros,
                               (b, c.max_position, h, d), c.dtype)
            cv = self.variable("cache", "v", jnp.zeros,
                               (b, c.max_position, h, d), c.dtype)
            idx = self.variable("cache", "index",
                                lambda: jnp.zeros((), jnp.int32))
            if is_initialized:
                ck.value = lax.dynamic_update_slice(ck.value, k,
                                                    (0, 0, 0, 0))
                cv.value = lax.dynamic_update_slice(cv.value, v,
                                                    (0, 0, 0, 0))
                idx.value = jnp.asarray(t0, jnp.int32)
            mask = nn.make_causal_mask(jnp.zeros((1, t0)))
            out = nn.dot_product_attention(q, k, v, mask=mask,
                                           dtype=c.dtype)
        elif decode:
            # KV-cached single-token decode: x is [B, 1, H]; append this
            # step's k/v at the cache cursor and attend over the filled
            # prefix. Cache layout [B, max_position, H, D] — static
            # shapes, so the per-token step jits once. Note the
            # has_variable check BEFORE creating the variables: init()
            # also executes this body, and without the guard it would
            # pollute the fresh cache with the init params' k/v and a
            # bumped cursor (flax's own decode path uses the same
            # idiom).
            is_initialized = self.has_variable("cache", "k")
            b = x.shape[0]
            ck = self.variable("cache", "k", jnp.zeros,
                               (b, c.max_position, h, d), c.dtype)
            cv = self.variable("cache", "v", jnp.zeros,
                               (b, c.max_position, h, d), c.dtype)
            idx = self.variable("cache", "index",
                                lambda: jnp.zeros((), jnp.int32))
            if not is_initialized:
                out = v  # init pass: only shapes matter
            else:
                i = idx.value
                ck.value = lax.dynamic_update_slice(ck.value, k,
                                                    (0, i, 0, 0))
                cv.value = lax.dynamic_update_slice(cv.value, v,
                                                    (0, i, 0, 0))
                idx.value = i + 1
                # only positions <= cursor are visible
                visible = (jnp.arange(c.max_position)
                           <= i)[None, None, None]
                s = jnp.einsum("bqhd,bkhd->bhqk",
                               q.astype(jnp.float32),
                               ck.value.astype(jnp.float32)) * (d ** -0.5)
                s = jnp.where(visible, s, jnp.finfo(jnp.float32).min)
                w = jax.nn.softmax(s, axis=-1)
                out = jnp.einsum("bhqk,bkhd->bqhd", w,
                                 cv.value.astype(jnp.float32)
                                 ).astype(c.dtype)
        elif c.attention == "local":
            t = x.shape[-2]
            mask = nn.make_causal_mask(jnp.zeros((1, t)))
            out = nn.dot_product_attention(q, k, v, mask=mask,
                                           dtype=c.dtype)
        elif c.attention == "flash":
            from ..ops.flash import flash_attention

            out = flash_attention(q, k, v, causal=True)
        else:
            from ..parallel.sequence import (
                ring_attention,
                ulysses_attention,
            )

            if c.attention == "ring":
                out = ring_attention(q, k, v, c.seq_axis, causal=True,
                                     use_flash=c.use_flash)
            else:
                out = ulysses_attention(q, k, v, c.seq_axis,
                                        causal=True,
                                        use_flash=c.use_flash)
        return nn.DenseGeneral(c.hidden_size, axis=(-2, -1),
                               dtype=c.dtype, name="out")(out)


def effective_moe_group(cfg: GPTConfig, b: int, t: int) -> int:
    """The routing group size `MoEMLP` actually runs for a [b, t]
    batch: the configured size (auto 512) clamped to b*t, falling back
    to one group per batch row when it doesn't divide b*t. Benchmarks
    report this, not the requested size."""
    group = min(cfg.moe_group_size or 512, b * t)
    if (b * t) % group:
        group = t
    return group


class MoEMLP(nn.Module):
    """Switch top-1 MoE feed-forward in the einsum dispatch formulation.

    Unlike `parallel.expert.moe_mlp` (shard_map, per-device shards),
    this module's expert stacks are GLOBAL parameters [E, H, F] — the
    idiomatic GSPMD form: annotate w_up/w_down with
    PartitionSpec("expert"|"model", ...) (`gpt_moe_rules`) and the
    compiler turns the dispatch/combine einsums into all-to-alls over
    ICI. Routing math is f32; expert matmuls run in the param dtype.
    """

    config: GPTConfig

    @nn.compact
    def __call__(self, x):
        from ..parallel.expert import dispatch_tensors, moe_capacity

        c = self.config
        b, t, h = x.shape
        e, f = c.num_experts, c.intermediate_size
        router = self.param(
            "router", nn.initializers.normal(h ** -0.5), (h, e),
            jnp.float32)
        pdt = c.moe_param_dtype or jnp.float32
        w_up = self.param(
            "w_up", nn.initializers.normal(h ** -0.5), (e, h, f),
            pdt).astype(c.dtype)
        w_down = self.param(
            "w_down", nn.initializers.normal(f ** -0.5), (e, f, h),
            pdt).astype(c.dtype)
        # GShard-style grouped routing: dispatch/combine are
        # [G, E, C, group] with C = ceil(group*cf/E), so total entries
        # are ~cf * group per token — linear in B*T, bounded by the
        # group size — instead of the quadratic [E, ceil(B*T*cf/E), B*T]
        # a single global group would cost.
        group = effective_moe_group(c, b, t)
        n_groups = (b * t) // group
        tokens = x.reshape(n_groups, group, h)
        capacity = moe_capacity(group, c.moe_capacity_factor, e)
        dispatch, combine, aux = jax.vmap(
            lambda tg: dispatch_tensors(tg, router, e, capacity,
                                        return_aux=True))(
            tokens)                                  # [G, E, C, g] f32
        # router training signals, averaged over routing groups; the
        # "losses" collection is folded into the objective by
        # `gpt_loss_with_aux` — without the load-balance term a top-1
        # router collapses (see parallel/expert.py:dispatch_tensors)
        self.sow("losses", "moe_load_balance", aux["load_balance"].mean())
        self.sow("losses", "moe_z_loss", aux["z_loss"].mean())
        self.sow("losses", "moe_dropped_frac", aux["dropped_frac"].mean())
        self.sow("losses", "moe_expert_load",
                 aux["expert_load"].mean(axis=0))  # [E]
        # gather in the param dtype (dispatch entries are exact 0/1);
        # gate-weighted combine stays f32 like parallel.expert.moe_mlp
        slots = jnp.einsum("gect,gth->gech", dispatch.astype(c.dtype),
                           tokens)                    # [G, E, C, H]
        up = jnp.einsum("gech,ehf->gecf", slots, w_up)
        act = nn.gelu(up)
        out = jnp.einsum("gecf,efh->gech", act,
                         w_down).astype(jnp.float32)
        y = jnp.einsum("gect,gech->gth", combine, out)
        return y.reshape(b, t, h).astype(x.dtype)


class Block(nn.Module):
    """Pre-LN transformer block (GPT-2 style); dense or MoE FFN."""

    config: GPTConfig

    @nn.compact
    def __call__(self, x, decode: bool = False, prefill: bool = False):
        c = self.config
        y = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        x = x + CausalSelfAttention(c)(y, decode=decode,
                                       prefill=prefill)
        y = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        if c.num_experts:
            y = MoEMLP(c, name="moe")(y)
        else:
            y = nn.Dense(c.intermediate_size, dtype=c.dtype)(y)
            y = nn.gelu(y)
            y = nn.Dense(c.hidden_size, dtype=c.dtype)(y)
        return x + y


class GPTLM(nn.Module):
    """Token ids [B, T] -> next-token logits [B, T, vocab] (f32)."""

    config: GPTConfig = GPTConfig()  # frozen dataclass: hashable default

    @nn.compact
    def __call__(self, token_ids, decode: bool = False,
                 prefill: bool = False, return_hidden: bool = False):
        c = self.config
        local_len = token_ids.shape[-1]
        if prefill:
            # batched prompt pass that fills the KV caches: normal
            # causal positions, cursor jumps to the prompt length
            if local_len > c.max_position:
                raise ValueError(
                    f"prompt {local_len} exceeds max_position "
                    f"{c.max_position}")
            initialized = self.has_variable("cache", "position")
            pos_var = self.variable("cache", "position",
                                    lambda: jnp.zeros((), jnp.int32))
            pos = jnp.arange(local_len)[None, :]
            if initialized:
                pos_var.value = jnp.asarray(local_len, jnp.int32)
        elif decode:
            # KV-cached decode: one token per call; the position cursor
            # lives in the cache collection next to each layer's k/v
            if local_len != 1:
                raise ValueError(
                    f"decode processes one token per call, got "
                    f"{local_len}")
            initialized = self.has_variable("cache", "position")
            pos_var = self.variable("cache", "position",
                                    lambda: jnp.zeros((), jnp.int32))
            pos = pos_var.value[None, None]
            if initialized:  # init() must return a pristine cursor
                pos_var.value = pos_var.value + 1
        elif c.attention in ("ring", "ulysses"):
            # sequence-sharded: this device holds positions
            # [rank*local_len, (rank+1)*local_len)
            global_len = local_len * lax.axis_size(c.seq_axis)
            if global_len > c.max_position:
                raise ValueError(
                    f"global sequence {global_len} exceeds max_position "
                    f"{c.max_position}; raise GPTConfig.max_position")
            rank = lax.axis_index(c.seq_axis)
            pos = (rank * local_len + jnp.arange(local_len))[None, :]
        else:
            if local_len > c.max_position:
                raise ValueError(
                    f"sequence {local_len} exceeds max_position "
                    f"{c.max_position}; raise GPTConfig.max_position")
            pos = jnp.arange(local_len)[None, :]
        x = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype,
                     name="wte")(token_ids)
        x = x + nn.Embed(c.max_position, c.hidden_size, dtype=c.dtype,
                         name="wpe")(pos)
        # static_argnums index flax's inner core_fn, whose args are
        # (module, x, decode, prefill) -> decode=2, prefill=3; the
        # bools select traced branches and must stay static under
        # checkpointing. Explicit Block_{i} names keep the param tree
        # identical to the uncheckpointed model (flax would otherwise
        # name these CheckpointBlock_{i}), so checkpoints and
        # stack_gpt_blocks see one layout.
        block_cls = (nn.remat(Block, static_argnums=(2, 3))
                     if c.remat else Block)
        for i in range(c.num_layers):
            x = block_cls(c, name=f"Block_{i}")(x, decode, prefill)
        x = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        if return_hidden:
            # training fast path: the caller feeds these states to
            # ops.fused_ce.fused_cross_entropy with params["lm_head"],
            # so the [B, T, vocab] f32 logits are never materialized
            return x
        return nn.Dense(c.vocab_size, dtype=jnp.float32,
                        name="lm_head")(x)


def gpt_loss(logits, token_ids):
    """Mean next-token cross entropy: logits[t] predicts token[t+1].

    The last position has no target and is dropped; caller-side masking
    is unnecessary for the synthetic/benchmark corpora this framework
    trains on.
    """
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1].astype(jnp.float32), token_ids[:, 1:]).mean()


def _head_ce(hidden, params, token_ids, interpret, residual, mesh,
             data_axis, model_axis):
    """Shared head dispatch for the fused losses: single-device (or
    shard_map-per-shard) kernel without `mesh`; the vocab-sharded
    shard_map path (parallel/vocab_ce.py) when a real mesh is given."""
    b, t, h = hidden.shape
    flat_h = hidden[:, :-1].reshape(b * (t - 1), h)
    flat_t = token_ids[:, 1:].reshape(-1)
    kernel = params["lm_head"]["kernel"]
    bias = params["lm_head"]["bias"]
    if mesh is not None and mesh.size > 1:
        from ..parallel.vocab_ce import vocab_sharded_fused_ce

        return vocab_sharded_fused_ce(
            flat_h, kernel, bias, flat_t, mesh=mesh,
            data_axis=data_axis, model_axis=model_axis,
            residual=residual, interpret=interpret)
    from ..ops.fused_ce import fused_cross_entropy

    return fused_cross_entropy(flat_h, kernel, bias, flat_t,
                               interpret=interpret, residual=residual)


def gpt_fused_loss(model: GPTLM, params, token_ids,
                   interpret: bool | None = None,
                   residual: bool = True,
                   mesh=None, data_axis: str = "data",
                   model_axis: str = "model"):
    """`gpt_loss`, but through `ops.fused_ce.fused_cross_entropy`.

    Runs the trunk with `return_hidden=True` and applies the lm_head
    inside the fused Pallas kernel, so the [B, T, vocab] f32 logits are
    never materialized in HBM and all three head matmuls (logits, dW,
    dx) run bf16 with f32 accumulation. Same math as
    ``gpt_loss(model.apply(...), tokens)`` up to bf16 rounding of the
    head weights; use this for training, `gpt_loss` for eval paths
    that want the raw logits.

    With `mesh` (a multi-device (data, model) Mesh) the head runs
    VOCAB-SHARDED through `parallel.vocab_ce.vocab_sharded_fused_ce`:
    shard_map keeps the Pallas kernel per-shard (the GSPMD partitioner
    has no rule for pallas_call) and a psum-logsumexp combine recovers
    the exact loss — this is how tp>1 / multi-chip keeps the
    [B, T, V]-free loss. Without `mesh` the single-device kernel runs
    directly (also correct inside an enclosing shard_map region, e.g.
    `build_dp_replicated_train_step`).

    `interpret=None` auto-selects Pallas interpreter mode off-TPU from
    the DEFAULT backend (from the MESH devices when `mesh` is given);
    pass `interpret=True` explicitly when the step is jitted onto CPU
    devices while a TPU owns the default backend (the driver's dryrun
    environment).
    """
    hidden = model.apply({"params": params}, token_ids,
                         return_hidden=True)
    return _head_ce(hidden, params, token_ids, interpret, residual,
                    mesh, data_axis, model_axis)


def gpt_loss_with_aux(model: GPTLM, params, token_ids,
                      fused: bool = True,
                      interpret: bool | None = None,
                      mesh=None, data_axis: str = "data",
                      model_axis: str = "model"):
    """(total_loss, metrics): cross entropy + the MoE router losses.

    Runs the model with the "losses" collection mutable, averages each
    sown signal over layers, and returns
    ``ce + moe_aux_coef * load_balance + moe_z_coef * z_loss`` plus a
    metrics dict (ce / load_balance / z_loss / dropped_frac). For dense
    configs (num_experts=0) this reduces to `gpt_loss`. Use this — not
    bare `gpt_loss` — when training an MoE config, or the router
    collapses onto few experts.

    `interpret` is forwarded to the fused head (fused=True only): None
    auto-selects Pallas interpreter mode off the default backend (the
    MESH devices when `mesh` is given); pass True explicitly when
    jitting onto CPU devices while a TPU owns the default backend (the
    driver's dryrun environment), mirroring `gpt_fused_loss`.

    With `mesh` (a multi-device (data, model) Mesh) the fused head runs
    VOCAB-SHARDED (`parallel.vocab_ce.vocab_sharded_fused_ce`), so
    multi-chip MoE keeps the [B, T, V]-free loss — the GSPMD-sharded
    expert stacks and the shard_map'd head compose inside one jitted
    step.
    """
    c = model.config
    if fused:
        # fused head+CE (ops/fused_ce.py): bf16 head matmuls with f32
        # accumulation, no [B, T, vocab] f32 logits. `fused=False`
        # keeps the f32 Dense head for when f32 head numerics are
        # required.
        hidden, mutated = model.apply({"params": params}, token_ids,
                                      mutable=["losses"],
                                      return_hidden=True)
        ce = _head_ce(hidden, params, token_ids, interpret, True,
                      mesh, data_axis, model_axis)
    else:
        logits, mutated = model.apply({"params": params}, token_ids,
                                      mutable=["losses"])
        ce = gpt_loss(logits, token_ids)
    metrics = {"ce": ce}
    total = ce
    if c.num_experts:
        from flax import traverse_util

        flat = traverse_util.flatten_dict(mutated.get("losses", {}))

        def layer_mean(name):
            vals = [v for k, vs in flat.items() if k[-1] == name
                    for v in vs]  # sow stores a tuple per call site
            return jnp.mean(jnp.stack(vals), axis=0)

        metrics["load_balance"] = layer_mean("moe_load_balance")
        metrics["z_loss"] = layer_mean("moe_z_loss")
        metrics["dropped_frac"] = layer_mean("moe_dropped_frac")
        metrics["expert_load"] = layer_mean("moe_expert_load")  # [E]
        total = (ce + c.moe_aux_coef * metrics["load_balance"]
                 + c.moe_z_coef * metrics["z_loss"])
    return total, metrics


def gpt_generate(model: GPTLM, params, prompt, num_steps: int,
                 rng=None, temperature: float = 0.0):
    """Autoregressive generation with a KV cache.

    `prompt` [B, T0] int tokens; returns [B, T0 + num_steps]. The cache
    holds [B, max_position, H, D] per layer, so every decode step is the
    SAME jitted program (static shapes, one compile) — the standard TPU
    serving pattern. `temperature=0` is greedy argmax; otherwise sample
    with `rng` (required).
    """
    c = model.config
    b, t0 = prompt.shape
    if num_steps <= 0:
        raise ValueError(f"num_steps must be >= 1, got {num_steps}")
    if t0 + num_steps > c.max_position:
        raise ValueError(
            f"prompt {t0} + steps {num_steps} exceeds max_position "
            f"{c.max_position}")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if temperature != 0.0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")

    # the cache is all zeros with statically-known shapes — build it
    # from eval_shape instead of paying a full (discarded) param init
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), prompt[:, :1],
                           decode=True))
    cache = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), abstract["cache"])

    def sample(logits, key):  # [B, V] -> [B]
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1)
        return jax.random.categorical(key, logits / temperature,
                                      axis=-1)

    # batched prefill: ONE causal forward over the prompt fills every
    # layer's cache and yields the first new-token logits
    logits, mut = model.apply(
        {"params": params, "cache": cache}, prompt, prefill=True,
        mutable=["cache"])
    cache = mut["cache"]
    keys = jax.random.split(rng if rng is not None
                            else jax.random.PRNGKey(0), num_steps)
    tok0 = sample(logits[:, -1], keys[0])

    def gen(carry, key):
        cache, tok = carry
        logits, mut = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            decode=True, mutable=["cache"])
        nxt = sample(logits[:, 0], key)
        return (mut["cache"], nxt), nxt

    _, toks = lax.scan(gen, (cache, tok0), keys[1:])
    return jnp.concatenate([prompt, tok0[:, None], toks.T], axis=1)


def stack_gpt_blocks(params, num_stages: int):
    """Host-side prep for pipeline parallelism: split a GPTLM param tree
    into (outer, stacked) where `stacked` carries every Block's params
    under a leading [num_stages, layers_per_stage] axis pair (shard the
    first over the pipe mesh axis) and `outer` is everything else
    (embeddings, final LayerNorm, lm_head — replicated; they run outside
    the pipe)."""
    from ..parallel.pipeline import stack_stage_params

    names = sorted((k for k in params if k.startswith("Block_")),
                   key=lambda k: int(k.split("_")[1]))
    if len(names) % num_stages:
        raise ValueError(
            f"{len(names)} blocks do not divide {num_stages} stages")
    per = len(names) // num_stages
    blocks = [params[k] for k in names]
    stacked = stack_stage_params(
        [stack_stage_params(blocks[s * per:(s + 1) * per])
         for s in range(num_stages)])
    outer = {k: v for k, v in params.items()
             if not k.startswith("Block_")}
    return outer, stacked


def gpt_pipeline_forward(cfg: GPTConfig, outer, stage_blocks, tokens,
                         axis_name: str, num_microbatches: int):
    """GPipe forward for GPT: runs INSIDE `shard_map` over `axis_name`.

    - `outer`: the non-Block params from `stack_gpt_blocks`, replicated
      (in_specs P()).
    - `stage_blocks`: THIS stage's [layers_per_stage, ...] Block params
      (in_specs P('pipe') on the stacked tree's leading axis).
    - `tokens`: [B, T] with B % num_microbatches == 0, replicated.

    Embeddings and the head run replicated on every device (cheap);
    the Block stack streams microbatches stage-to-stage over ICI via
    `parallel.pipeline.pipeline_apply`. Returns [B, T, vocab] logits,
    replicated — differentiate the shard_mapped caller as usual.
    """
    from ..parallel.pipeline import pipeline_apply

    b, t = tokens.shape
    m = num_microbatches
    if b % m:
        raise ValueError(f"batch {b} % microbatches {m} != 0")
    if t > cfg.max_position:
        raise ValueError(f"sequence {t} exceeds max_position "
                         f"{cfg.max_position}")
    embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype)
    pos_embed = nn.Embed(cfg.max_position, cfg.hidden_size,
                         dtype=cfg.dtype)
    x = embed.apply({"params": outer["wte"]}, tokens)
    x = x + pos_embed.apply({"params": outer["wpe"]},
                            jnp.arange(t)[None, :])
    x = x.reshape(m, b // m, t, cfg.hidden_size)

    def stage_fn(stacked, h):
        def body(h, layer_params):
            return Block(cfg).apply({"params": layer_params}, h), None

        h, _ = lax.scan(body, h, stacked)
        return h

    x = pipeline_apply(stage_fn, stage_blocks, x, axis_name,
                       num_microbatches=m)
    x = x.reshape(b, t, cfg.hidden_size)
    x = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32).apply(
        {"params": outer["LayerNorm_0"]}, x)
    return nn.Dense(cfg.vocab_size, dtype=jnp.float32).apply(
        {"params": outer["lm_head"]}, x)


def gpt_pipeline_train_step(cfg: GPTConfig, outer, stage_blocks, tokens,
                            axis_name: str, num_microbatches: int):
    """1F1B pipelined loss + gradients for GPT; runs INSIDE `shard_map`
    over `axis_name`.

    The full training composition (`parallel.pipeline.
    pipeline_train_step_1f1b`): embedding is stage 0's entry edge, the
    final LayerNorm + lm_head + cross entropy are stage P-1's exit edge,
    and the Block trunk streams microbatches with one forward and one
    backward in flight per device after warmup. Only the int32 `tokens`
    are replicated across stages; activations live on exactly one stage
    each and in-flight storage is 2P microbatches regardless of M.

    - `outer` / `stage_blocks`: from `stack_gpt_blocks`; pass
      `stage_blocks` with in_specs P('pipe') (leading singleton stage
      axis per device).
    - `tokens`: [B, T], B % num_microbatches == 0, in_specs P().

    Returns `(loss, g_outer, g_stage)` for out_specs
    `(P(), P(), P('pipe'))`: scalar mean loss, replicated edge grads,
    and the stage-stacked Block grads matching `stage_blocks`' layout —
    feed them straight to the same optimizer layout as the params.
    """
    from ..parallel.pipeline import pipeline_train_step_1f1b

    b, t = tokens.shape
    m = num_microbatches
    if b % m:
        raise ValueError(f"batch {b} % microbatches {m} != 0")
    if t > cfg.max_position:
        raise ValueError(f"sequence {t} exceeds max_position "
                         f"{cfg.max_position}")
    micro = tokens.reshape(m, b // m, t)
    embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype)
    pos_embed = nn.Embed(cfg.max_position, cfg.hidden_size,
                         dtype=cfg.dtype)
    ln = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32)

    def enter_fn(op, mb_tokens):
        x = embed.apply({"params": op["wte"]}, mb_tokens)
        return x + pos_embed.apply({"params": op["wpe"]},
                                   jnp.arange(t)[None, :])

    def stage_fn(stacked, h):
        def body(h, layer_params):
            return Block(cfg).apply({"params": layer_params}, h), None

        h, _ = lax.scan(body, h, stacked)
        return h

    def exit_fn(op, h, mb_tokens):
        from ..ops.fused_ce import fused_cross_entropy

        x = ln.apply({"params": op["LayerNorm_0"]}, h)
        mb, tt, hd = x.shape
        # fused head+CE per microbatch: no [mb, T, vocab] f32 logits
        # (configs whose hidden doesn't tile fall back to the dense
        # head inside fused_cross_entropy's reference path)
        return fused_cross_entropy(
            x[:, :-1].reshape(mb * (tt - 1), hd),
            op["lm_head"]["kernel"], op["lm_head"]["bias"],
            mb_tokens[:, 1:].reshape(-1))

    loss, g_outer, g_stage = pipeline_train_step_1f1b(
        stage_fn, enter_fn, exit_fn,
        jax.tree_util.tree_map(lambda l: l[0], stage_blocks),
        outer, micro, axis_name)
    return loss, g_outer, jax.tree_util.tree_map(
        lambda g: g[None], g_stage)
