"""GPT — decoder-only transformer LM, the composed-parallelism flagship.

Beyond the reference's scope (SURVEY §2.9: the reference trains
data-parallel only and ships no language models), this model is built
to exercise every parallel axis the framework provides, composed:

- **dp x tp** (the Megatron recipe): shard the parameters with
  `parallel.tensor.shard_params(params, mesh, gpt_tp_rules())` over a
  ("data", "model") mesh and jit the train step — GSPMD inserts the
  all-gathers/reduce-scatters on ICI. Attention projections and the MLP
  use fixed module names (query/key/value/out, Dense_0/Dense_1 inside
  `Block`) so the sharding rules match by path.
- **sequence parallelism**: `GPTConfig(attention="ring"|"ulysses")`
  swaps the mixer for the causal sequence-parallel ones in
  `parallel.sequence`; the model then runs INSIDE `shard_map` with
  token shards, like `models/bert.py`.
- **flash**: `GPTConfig(attention="flash")` runs the Pallas kernel
  (`ops/flash.py`) for the local causal mixer — O(T) HBM both
  directions, for contexts whose [T, T] scores don't fit.

Norm/dtype conventions follow `models/bert.py`: bf16 matmuls and
residual stream, f32 LayerNorm scale/bias, f32 logits head.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

_ATTN_MODES = ("local", "flash", "ring", "ulysses")


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 1024
    dtype: Any = jnp.bfloat16
    attention: str = "local"  # local | flash | ring | ulysses
    seq_axis: str = "seq"     # mesh axis for the sequence-parallel modes

    def __post_init__(self):
        if self.attention not in _ATTN_MODES:
            raise ValueError(
                f"attention must be one of {_ATTN_MODES}, got "
                f"{self.attention!r}")
        if self.hidden_size % self.num_heads:
            raise ValueError(
                f"hidden {self.hidden_size} % heads {self.num_heads} != 0")


class CausalSelfAttention(nn.Module):
    """Multi-head causal self-attention with a pluggable mixer.

    Projection modules are named (query/key/value/out) so
    `parallel.tensor.gpt_tp_rules` can target them by path.
    """

    config: GPTConfig

    @nn.compact
    def __call__(self, x):
        c = self.config
        h, d = c.num_heads, c.hidden_size // c.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (h, d), dtype=c.dtype, name=name)
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        if c.attention == "local":
            t = x.shape[-2]
            mask = nn.make_causal_mask(jnp.zeros((1, t)))
            out = nn.dot_product_attention(q, k, v, mask=mask,
                                           dtype=c.dtype)
        elif c.attention == "flash":
            from ..ops.flash import flash_attention

            out = flash_attention(q, k, v, causal=True)
        else:
            from ..parallel.sequence import (
                ring_attention,
                ulysses_attention,
            )

            mixer = (ring_attention if c.attention == "ring"
                     else ulysses_attention)
            out = mixer(q, k, v, c.seq_axis, causal=True)
        return nn.DenseGeneral(c.hidden_size, axis=(-2, -1),
                               dtype=c.dtype, name="out")(out)


class Block(nn.Module):
    """Pre-LN transformer block (GPT-2 style)."""

    config: GPTConfig

    @nn.compact
    def __call__(self, x):
        c = self.config
        y = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        x = x + CausalSelfAttention(c)(y)
        y = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        y = nn.Dense(c.intermediate_size, dtype=c.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(c.hidden_size, dtype=c.dtype)(y)
        return x + y


class GPTLM(nn.Module):
    """Token ids [B, T] -> next-token logits [B, T, vocab] (f32)."""

    config: GPTConfig = GPTConfig()  # frozen dataclass: hashable default

    @nn.compact
    def __call__(self, token_ids):
        c = self.config
        local_len = token_ids.shape[-1]
        if c.attention in ("ring", "ulysses"):
            # sequence-sharded: this device holds positions
            # [rank*local_len, (rank+1)*local_len)
            global_len = local_len * lax.axis_size(c.seq_axis)
            if global_len > c.max_position:
                raise ValueError(
                    f"global sequence {global_len} exceeds max_position "
                    f"{c.max_position}; raise GPTConfig.max_position")
            rank = lax.axis_index(c.seq_axis)
            pos = (rank * local_len + jnp.arange(local_len))[None, :]
        else:
            if local_len > c.max_position:
                raise ValueError(
                    f"sequence {local_len} exceeds max_position "
                    f"{c.max_position}; raise GPTConfig.max_position")
            pos = jnp.arange(local_len)[None, :]
        x = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype,
                     name="wte")(token_ids)
        x = x + nn.Embed(c.max_position, c.hidden_size, dtype=c.dtype,
                         name="wpe")(pos)
        for _ in range(c.num_layers):
            x = Block(c)(x)
        x = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32)(x)
        return nn.Dense(c.vocab_size, dtype=jnp.float32,
                        name="lm_head")(x)


def gpt_loss(logits, token_ids):
    """Mean next-token cross entropy: logits[t] predicts token[t+1].

    The last position has no target and is dropped; caller-side masking
    is unnecessary for the synthetic/benchmark corpora this framework
    trains on.
    """
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1].astype(jnp.float32), token_ids[:, 1:]).mean()
