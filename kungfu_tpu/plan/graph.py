"""Communication DAG over ranks.

Nodes are ranks 0..n-1; each node has an optional self-loop flag (meaning
"accumulate into own buffer" in reduce graphs). Used by the DCN control
plane's CPU collectives and by elasticity bookkeeping — on the TPU data plane
XLA chooses the collective algorithm itself.
(Reference behavior: srcs/go/plan/graph.go.)
"""

from __future__ import annotations

from typing import List, Sequence


class Graph:
    def __init__(self, n: int):
        self.n = n
        self._next: List[List[int]] = [[] for _ in range(n)]
        self._prev: List[List[int]] = [[] for _ in range(n)]
        self.self_loop: List[bool] = [False] * n

    def add_edge(self, i: int, j: int) -> None:
        if i == j:
            self.self_loop[i] = True
            return
        self._next[i].append(j)
        self._prev[j].append(i)

    def nexts(self, i: int) -> Sequence[int]:
        return self._next[i]

    def prevs(self, i: int) -> Sequence[int]:
        return self._prev[i]

    def reverse(self) -> "Graph":
        g = Graph(self.n)
        g.self_loop = list(self.self_loop)
        for i in range(self.n):
            for j in self._next[i]:
                g.add_edge(j, i)
        return g

    def is_self_loop(self, i: int) -> bool:
        return self.self_loop[i]

    def edges(self) -> List[tuple]:
        return [(i, j) for i in range(self.n) for j in self._next[i]]

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Graph):
            return NotImplemented
        return (
            self.n == other.n
            and self.self_loop == other.self_loop
            and [sorted(x) for x in self._next] == [sorted(x) for x in other._next]
        )

    def __repr__(self) -> str:
        parts = []
        for i in range(self.n):
            loop = "*" if self.self_loop[i] else ""
            parts.append(f"{i}{loop}->{self._next[i]}")
        return f"Graph({'; '.join(parts)})"
