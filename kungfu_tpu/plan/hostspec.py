"""Host capacity specs and peer/runner list generation.

``-H ip:slots[:public_addr]`` parsing and deterministic rank assignment:
peers fill hosts in declaration order, one port per slot drawn from the port
range. On TPU hosts a "slot" is a worker process (which may own one or more
TPU chips via the launcher's chip-assignment — see kungfu_tpu/run/job.py);
the reference's GPU slots map 1:1. (Reference behavior:
srcs/go/plan/hostspec.go:101-184.)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List, Tuple

from .addr import PeerID, format_ipv4, parse_ipv4
from .peerlist import PeerList


@dataclass(frozen=True)
class PortRange:
    begin: int
    end: int  # inclusive

    @classmethod
    def parse(cls, s: str) -> "PortRange":
        begin_s, _, end_s = s.partition("-")
        begin, end = int(begin_s), int(end_s)
        if end < begin:
            raise ValueError(f"invalid port range: {s!r}")
        return cls(begin, end)

    @property
    def cap(self) -> int:
        return self.end - self.begin + 1

    def __str__(self) -> str:
        return f"{self.begin}-{self.end}"


DEFAULT_PORT_RANGE = PortRange(10000, 11000)
DEFAULT_RUNNER_PORT = 38080


def split_host_entry(spec: str) -> "tuple[str, int, str]":
    """'host[:slots[:public]]' -> (host, slots, public). The single
    grammar for -H entries; `host` may still be a hostname here (the
    runner's discovery layer resolves it, reference: discovery.go:195)."""
    parts = spec.split(":")
    if not parts or not parts[0] or len(parts) > 3:
        raise ValueError(f"invalid host spec: {spec!r}")
    host = parts[0]
    slots = int(parts[1]) if len(parts) >= 2 else 1
    public = parts[2] if len(parts) == 3 else host
    return host, slots, public


@dataclass(frozen=True)
class HostSpec:
    ipv4: int
    slots: int
    public_addr: str

    @classmethod
    def parse(cls, spec: str) -> "HostSpec":
        host, slots, public = split_host_entry(spec)
        return cls(parse_ipv4(host), slots, public)

    def __str__(self) -> str:
        return f"{format_ipv4(self.ipv4)}:{self.slots}:{self.public_addr}"


class HostList(Tuple[HostSpec, ...]):
    def __new__(cls, hosts: Iterable[HostSpec] = ()) -> "HostList":
        return super().__new__(cls, tuple(hosts))

    @classmethod
    def parse(cls, s: str) -> "HostList":
        if not s:
            return cls()
        return cls(HostSpec.parse(h) for h in s.split(","))

    @classmethod
    def single_host(cls, slots: int, host: str = "127.0.0.1") -> "HostList":
        return cls([HostSpec(parse_ipv4(host), slots, host)])

    @property
    def cap(self) -> int:
        return sum(h.slots for h in self)

    def slots_of(self, ipv4: int) -> int:
        for h in self:
            if h.ipv4 == ipv4:
                return h.slots
        return 0

    def gen_peer_list(
        self, np: int, port_range: PortRange = DEFAULT_PORT_RANGE
    ) -> PeerList:
        """Assign np ranks across hosts in order; slot j gets port begin+j.

        Raises if the host list or port range cannot hold np workers. The
        result fixes the global rank order for the job.
        """
        if self.cap < np:
            raise ValueError(f"not enough capacity: {self.cap} < {np}")
        for h in self:
            if port_range.cap < h.slots:
                raise ValueError(
                    f"port range {port_range} smaller than slots on {h}"
                )
        peers: List[PeerID] = []
        for h in self:
            for j in range(h.slots):
                if len(peers) >= np:
                    return PeerList(peers)
                peers.append(PeerID(h.ipv4, port_range.begin + j))
        return PeerList(peers)

    def gen_runner_list(self, port: int = DEFAULT_RUNNER_PORT) -> PeerList:
        return PeerList(PeerID(h.ipv4, port) for h in self)

    def __str__(self) -> str:
        return ",".join(str(h) for h in self)
