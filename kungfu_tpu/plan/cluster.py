"""Cluster = (runners, workers) membership pair with resize logic.

A cluster snapshot is what the config server stores and what consensus agrees
on; its canonical byte digest fences every membership change. Resize grows
onto the least-loaded host (reference behavior: srcs/go/plan/cluster.go).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, Optional

from .addr import PeerID
from .hostspec import DEFAULT_PORT_RANGE
from .peerlist import PeerList


@dataclass(frozen=True)
class Cluster:
    runners: PeerList
    workers: PeerList

    def to_bytes(self) -> bytes:
        return self.runners.to_bytes() + self.workers.to_bytes()

    def validate(self) -> Optional[str]:
        """Return an error string, or None if the cluster is well-formed."""
        seen_ids = set()
        runner_hosts = set()
        for r in self.runners:
            if r in seen_ids:
                return f"duplicated port: {r}"
            seen_ids.add(r)
            if r.ipv4 in runner_hosts:
                return f"duplicated runner on host {r.host}"
            runner_hosts.add(r.ipv4)
        for w in self.workers:
            if w in seen_ids:
                return f"duplicated port: {w}"
            seen_ids.add(w)
            if w.ipv4 not in runner_hosts:
                return f"missing runner for worker {w}"
        return None

    def _grow_one(self) -> "Cluster":
        used: Dict[int, int] = {r.ipv4: 0 for r in self.runners}
        for w in self.workers:
            used[w.ipv4] = used.get(w.ipv4, 0) + 1
        target = min(self.runners, key=lambda r: used[r.ipv4]).ipv4
        port = 0
        for w in self.workers:
            if w.ipv4 == target and port <= w.port:
                port = w.port + 1
        if port == 0:
            # empty target host: stay inside the port range the job is
            # actually using (visible from the other workers) rather than
            # falling back to the default range
            port = min((w.port for w in self.workers),
                       default=DEFAULT_PORT_RANGE.begin)
        return Cluster(
            runners=self.runners,
            workers=PeerList([*self.workers, PeerID(target, port)]),
        )

    def resize(self, new_size: int) -> "Cluster":
        """Shrink by truncation / grow onto the least-loaded runner host."""
        c = self
        if len(c.workers) > new_size:
            c = Cluster(runners=c.runners, workers=PeerList(c.workers[:new_size]))
        while len(c.workers) < new_size:
            c = c._grow_one()
        return c

    # -- JSON codec: the config-server wire format --------------------------

    def to_json(self) -> str:
        return json.dumps(
            {
                "runners": [str(r) for r in self.runners],
                "workers": [str(w) for w in self.workers],
            }
        )

    @classmethod
    def from_json(cls, s: str) -> "Cluster":
        d = json.loads(s)
        return cls(
            runners=PeerList(PeerID.parse(r) for r in d.get("runners", [])),
            workers=PeerList(PeerID.parse(w) for w in d.get("workers", [])),
        )

    def __str__(self) -> str:
        return f"[{len(self.workers)}@{len(self.runners)}]{{{self.workers}}}@{{{self.runners}}}"
