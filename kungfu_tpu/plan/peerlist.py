"""Ordered peer membership list.

The order *is* the rank assignment: ``rank = index``. Local rank/size are
derived from colocation (same IPv4). The canonical byte encoding feeds the
digest consensus that guards elastic membership changes.
(Reference behavior: srcs/go/plan/peerlist.go.)
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Tuple

from .addr import PeerID


class PeerList(Tuple[PeerID, ...]):
    """Immutable ordered list of peers; index == rank."""

    def __new__(cls, peers: Iterable[PeerID] = ()) -> "PeerList":
        return super().__new__(cls, tuple(peers))

    @classmethod
    def parse(cls, s: str) -> "PeerList":
        if not s:
            return cls()
        return cls(PeerID.parse(p) for p in s.split(","))

    def to_bytes(self) -> bytes:
        return b"".join(p.to_bytes() for p in self)

    def rank(self, q: PeerID) -> Optional[int]:
        for i, p in enumerate(self):
            if p == q:
                return i
        return None

    def local_size(self, q: PeerID) -> int:
        return sum(1 for p in self if p.colocated_with(q))

    def local_rank(self, q: PeerID) -> Optional[int]:
        i = 0
        for p in self:
            if p == q:
                return i
            if p.colocated_with(q):
                i += 1
        return None

    def hosts(self) -> Tuple[int, ...]:
        """Distinct host IPv4s in first-seen order."""
        seen: dict = {}
        for p in self:
            seen.setdefault(p.ipv4, None)
        return tuple(seen.keys())

    def on_host(self, ipv4: int) -> "PeerList":
        return PeerList(p for p in self if p.ipv4 == ipv4)

    def others(self, self_id: PeerID) -> "PeerList":
        return PeerList(p for p in self if p != self_id)

    def select(self, ranks: Iterable[int]) -> "PeerList":
        return PeerList(self[r] for r in ranks)

    def intersection(self, other: "PeerList") -> "PeerList":
        s = set(other)
        return PeerList(p for p in self if p in s)

    def disjoint(self, other: "PeerList") -> bool:
        return not self.intersection(other)

    def diff(self, other: "PeerList") -> Tuple["PeerList", "PeerList"]:
        """(in self but not other, in other but not self)."""
        a = set(other)
        b = set(self)
        return (
            PeerList(p for p in self if p not in a),
            PeerList(p for p in other if p not in b),
        )

    def __str__(self) -> str:
        return ",".join(str(p) for p in self)

    def __iter__(self) -> Iterator[PeerID]:  # narrow the type for checkers
        return super().__iter__()
