"""Communication-topology generators.

Each generator yields broadcast graphs (and, via `gen_default_reduce_graph`,
their matching reduce graphs = reverse + self-loops). The host-aware shapes
(tree, binary-tree-star, multi-binary-tree-star) put one "master" rank per
host so cross-host traffic only flows between masters — the same
locality trick the reference uses for its TCP all-reduce
(reference: srcs/go/plan/topology.go:15-113). In the TPU build these feed the
DCN control plane's CPU collectives; ICI data-plane collectives are compiled
by XLA and need no explicit graphs.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .graph import Graph
from .peerlist import PeerList


def _local_masters(peers: PeerList) -> Tuple[List[int], Dict[int, int]]:
    """First rank seen on each host, in rank order; and host -> master map."""
    masters: List[int] = []
    host_master: Dict[int, int] = {}
    for rank, p in enumerate(peers):
        if p.ipv4 not in host_master:
            host_master[p.ipv4] = rank
            masters.append(rank)
    return masters, host_master


def gen_star_bcast_graph(k: int, root: int) -> Graph:
    """Star centered at `root`: root sends to everyone directly."""
    g = Graph(k)
    for i in range(k):
        if i != root:
            g.add_edge(root, i)
    return g


def gen_tree(peers: PeerList) -> Graph:
    """Two-level tree: host masters form a star under rank of first host;
    each master fans out to its local peers."""
    g = Graph(len(peers))
    masters, host_master = _local_masters(peers)
    for rank, p in enumerate(peers):
        if host_master[p.ipv4] != rank:
            g.add_edge(host_master[p.ipv4], rank)
    for m in masters[1:]:
        g.add_edge(masters[0], m)
    return g


def gen_binary_tree(k: int) -> Graph:
    """Heap-shaped binary tree over ranks 0..k-1."""
    g = Graph(k)
    for i in range(k):
        for j in (2 * i + 1, 2 * i + 2):
            if j < k:
                g.add_edge(i, j)
    return g


def _binary_tree_star(peers: PeerList, offset: int) -> Graph:
    g = Graph(len(peers))
    masters, host_master = _local_masters(peers)
    for rank, p in enumerate(peers):
        if host_master[p.ipv4] != rank:
            g.add_edge(host_master[p.ipv4], rank)
    k = len(masters)
    if k > 1:
        for i in range(k):
            for j in (2 * i + 1, 2 * i + 2):
                if j < k:
                    g.add_edge(masters[(i + offset) % k], masters[(j + offset) % k])
    return g


def gen_binary_tree_star(peers: PeerList) -> Graph:
    """Intra-host star + inter-host binary tree over masters."""
    return _binary_tree_star(peers, 0)


def gen_multi_binary_tree_star(peers: PeerList) -> List[Graph]:
    """One rotated binary-tree-star per host master: multiple roots let
    chunked traffic use every master's uplink concurrently."""
    masters, _ = _local_masters(peers)
    return [_binary_tree_star(peers, i) for i in range(len(masters))]


def gen_circular_graph_pair(k: int, r: int) -> Tuple[Graph, Graph]:
    """Ring (reduce, bcast) pair rotated to start at rank r.

    The reduce graph carries partial sums around the ring ending at the
    ring's last node; the bcast graph pushes the final value the rest of the
    way around.
    """
    reduce_g = Graph(k)
    for i in range(k):
        reduce_g.add_edge(i, i)
    bcast_g = Graph(k)
    for i in range(1, k):
        reduce_g.add_edge((r + i) % k, (r + i + 1) % k)
        bcast_g.add_edge((r + i - 1) % k, (r + i) % k)
    return reduce_g, bcast_g


def gen_default_reduce_graph(bcast: Graph) -> Graph:
    """Reduce graph matching a bcast graph: reversed edges + self-loops."""
    g = bcast.reverse()
    for i in range(g.n):
        g.add_edge(i, i)
    return g


#: the full strategy catalog (PAPER.md §strategy); AUTO resolves via
#: `resolve_auto` before any graph is built
STRATEGY_NAMES = (
    "STAR",
    "RING",
    "CLIQUE",
    "TREE",
    "BINARY_TREE",
    "BINARY_TREE_STAR",
    "MULTI_BINARY_TREE_STAR",
)


def resolve_auto(strategy: str, peers: PeerList) -> str:
    """AUTO -> concrete strategy for this peer list (star on one host,
    binary-tree-star across hosts); identity otherwise. Mirrors native
    `resolve_auto` (core.cpp)."""
    if strategy != "AUTO":
        return strategy
    masters, _ = _local_masters(peers)
    return "STAR" if len(masters) <= 1 else "BINARY_TREE_STAR"


def gen_strategy_pairs(strategy: str,
                       peers: PeerList) -> List[Tuple[Graph, Graph]]:
    """(reduce, bcast) graph pairs of a named strategy over `peers` —
    the Python mirror of native `build_strategy` (core.cpp), byte-for-
    byte in edge order. Chunked traffic round-robins across the pairs
    by stable name hash, so every rank MUST derive the identical list
    from its own replica of the PeerList (the schedule-only discipline
    kfverify's strategy-graph pass checks)."""
    k = len(peers)
    s = resolve_auto(strategy.upper(), peers)
    pairs: List[Tuple[Graph, Graph]] = []

    def from_bcast(b: Graph) -> None:
        pairs.append((gen_default_reduce_graph(b), b))

    if s == "STAR":
        from_bcast(gen_star_bcast_graph(k, 0))
    elif s == "RING":
        for r in range(k):
            reduce_g, bcast_g = gen_circular_graph_pair(k, r)
            pairs.append((reduce_g, bcast_g))
    elif s == "CLIQUE":
        for r in range(k):
            from_bcast(gen_star_bcast_graph(k, r))
    elif s == "TREE":
        from_bcast(gen_tree(peers))
    elif s == "BINARY_TREE":
        from_bcast(gen_binary_tree(k))
    elif s == "BINARY_TREE_STAR":
        from_bcast(gen_binary_tree_star(peers))
    elif s == "MULTI_BINARY_TREE_STAR":
        for g in gen_multi_binary_tree_star(peers):
            from_bcast(g)
    else:
        raise ValueError(
            f"unknown strategy {strategy!r}; valid: "
            f"{STRATEGY_NAMES + ('AUTO',)}")
    return pairs


def gen_hierarchy_pairs(strategy: str,
                        peers: PeerList) -> List[Tuple[Graph, Graph]]:
    """hier(strategy): the KF_HIER=1 decomposition, mirroring native
    `build_hierarchical` (core.cpp).

    Every (reduce, bcast) pair composes three stages in the full rank
    space: intra-host reduce (each leaf -> its host master, the edges
    the shm rings carry), the *configured* strategy's graphs restricted
    to the masters for the inter-host stage, and intra-host broadcast
    (master -> leaves). With no colocation (every rank its own host)
    hier(S) == S exactly. Pure function of (strategy, PeerList): it is
    re-derived from the live PeerList at every epoch switch/recovery,
    which is what makes the hierarchy elastically re-plannable.
    """
    n = len(peers)
    masters, host_master = _local_masters(peers)
    if len(masters) == n:
        return gen_strategy_pairs(strategy, peers)
    mpeers = PeerList(peers[m] for m in masters)
    out: List[Tuple[Graph, Graph]] = []
    for rg_m, bg_m in gen_strategy_pairs(strategy, mpeers):
        rg, bg = Graph(n), Graph(n)
        for g_m, g in ((rg_m, rg), (bg_m, bg)):
            for i in range(g_m.n):
                if g_m.is_self_loop(i):
                    g.add_edge(masters[i], masters[i])
                for j in g_m.nexts(i):
                    g.add_edge(masters[i], masters[j])
        for rank, p in enumerate(peers):
            m = host_master[p.ipv4]
            if m == rank:
                continue
            rg.add_edge(rank, m)  # intra-host reduce: leaf -> master
            bg.add_edge(m, rank)  # intra-host bcast: master -> leaves
        out.append((rg, bg))
    return out
