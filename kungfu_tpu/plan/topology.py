"""Communication-topology generators.

Each generator yields broadcast graphs (and, via `gen_default_reduce_graph`,
their matching reduce graphs = reverse + self-loops). The host-aware shapes
(tree, binary-tree-star, multi-binary-tree-star) put one "master" rank per
host so cross-host traffic only flows between masters — the same
locality trick the reference uses for its TCP all-reduce
(reference: srcs/go/plan/topology.go:15-113). In the TPU build these feed the
DCN control plane's CPU collectives; ICI data-plane collectives are compiled
by XLA and need no explicit graphs.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .graph import Graph
from .peerlist import PeerList


def _local_masters(peers: PeerList) -> Tuple[List[int], Dict[int, int]]:
    """First rank seen on each host, in rank order; and host -> master map."""
    masters: List[int] = []
    host_master: Dict[int, int] = {}
    for rank, p in enumerate(peers):
        if p.ipv4 not in host_master:
            host_master[p.ipv4] = rank
            masters.append(rank)
    return masters, host_master


def gen_star_bcast_graph(k: int, root: int) -> Graph:
    """Star centered at `root`: root sends to everyone directly."""
    g = Graph(k)
    for i in range(k):
        if i != root:
            g.add_edge(root, i)
    return g


def gen_tree(peers: PeerList) -> Graph:
    """Two-level tree: host masters form a star under rank of first host;
    each master fans out to its local peers."""
    g = Graph(len(peers))
    masters, host_master = _local_masters(peers)
    for rank, p in enumerate(peers):
        if host_master[p.ipv4] != rank:
            g.add_edge(host_master[p.ipv4], rank)
    for m in masters[1:]:
        g.add_edge(masters[0], m)
    return g


def gen_binary_tree(k: int) -> Graph:
    """Heap-shaped binary tree over ranks 0..k-1."""
    g = Graph(k)
    for i in range(k):
        for j in (2 * i + 1, 2 * i + 2):
            if j < k:
                g.add_edge(i, j)
    return g


def _binary_tree_star(peers: PeerList, offset: int) -> Graph:
    g = Graph(len(peers))
    masters, host_master = _local_masters(peers)
    for rank, p in enumerate(peers):
        if host_master[p.ipv4] != rank:
            g.add_edge(host_master[p.ipv4], rank)
    k = len(masters)
    if k > 1:
        for i in range(k):
            for j in (2 * i + 1, 2 * i + 2):
                if j < k:
                    g.add_edge(masters[(i + offset) % k], masters[(j + offset) % k])
    return g


def gen_binary_tree_star(peers: PeerList) -> Graph:
    """Intra-host star + inter-host binary tree over masters."""
    return _binary_tree_star(peers, 0)


def gen_multi_binary_tree_star(peers: PeerList) -> List[Graph]:
    """One rotated binary-tree-star per host master: multiple roots let
    chunked traffic use every master's uplink concurrently."""
    masters, _ = _local_masters(peers)
    return [_binary_tree_star(peers, i) for i in range(len(masters))]


def gen_circular_graph_pair(k: int, r: int) -> Tuple[Graph, Graph]:
    """Ring (reduce, bcast) pair rotated to start at rank r.

    The reduce graph carries partial sums around the ring ending at the
    ring's last node; the bcast graph pushes the final value the rest of the
    way around.
    """
    reduce_g = Graph(k)
    for i in range(k):
        reduce_g.add_edge(i, i)
    bcast_g = Graph(k)
    for i in range(1, k):
        reduce_g.add_edge((r + i) % k, (r + i + 1) % k)
        bcast_g.add_edge((r + i - 1) % k, (r + i) % k)
    return reduce_g, bcast_g


def gen_default_reduce_graph(bcast: Graph) -> Graph:
    """Reduce graph matching a bcast graph: reversed edges + self-loops."""
    g = bcast.reverse()
    for i in range(g.n):
        g.add_edge(i, i)
    return g
