"""Cluster plan: identity, membership, topology.

Pure-logic layer describing *who* is in the training cluster and *how*
control-plane traffic flows between them. This is the TPU-native rebuild of
the reference's plan package (reference: srcs/go/plan/). It is deliberately
framework-free: no JAX, no sockets — just data.

On TPU the *data plane* (gradient all-reduce) is compiled by XLA over the ICI
mesh, so the communication graphs generated here (`topology`) are used by the
DCN control plane (consensus, elastic membership, P2P model requests) and by
the CPU fallback collectives, not by the hot training path.
"""

from .addr import PeerID, format_ipv4, parse_ipv4
from .cluster import Cluster
from .graph import Graph
from .hostspec import (
    DEFAULT_PORT_RANGE,
    DEFAULT_RUNNER_PORT,
    HostList,
    HostSpec,
    PortRange,
)
from .interval import even_partition
from .peerlist import PeerList
from .topology import (
    STRATEGY_NAMES,
    gen_binary_tree,
    gen_binary_tree_star,
    gen_circular_graph_pair,
    gen_default_reduce_graph,
    gen_hierarchy_pairs,
    gen_multi_binary_tree_star,
    gen_star_bcast_graph,
    gen_strategy_pairs,
    gen_tree,
    resolve_auto,
)

__all__ = [
    "PeerID",
    "PeerList",
    "HostSpec",
    "HostList",
    "PortRange",
    "Cluster",
    "Graph",
    "parse_ipv4",
    "format_ipv4",
    "DEFAULT_PORT_RANGE",
    "DEFAULT_RUNNER_PORT",
    "even_partition",
    "gen_tree",
    "gen_binary_tree",
    "gen_binary_tree_star",
    "gen_multi_binary_tree_star",
    "gen_star_bcast_graph",
    "gen_circular_graph_pair",
    "gen_default_reduce_graph",
    "gen_strategy_pairs",
    "gen_hierarchy_pairs",
    "resolve_auto",
    "STRATEGY_NAMES",
]
