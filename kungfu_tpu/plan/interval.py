"""Even chunk partitioning for buffer splitting.

Splits [0, n) into k near-equal contiguous intervals; used by the control
plane to shard a blob across concurrent strategy graphs.
(Reference behavior: srcs/go/plan/interval.go.)
"""

from __future__ import annotations

from typing import List, Tuple


def even_partition(begin: int, end: int, k: int) -> List[Tuple[int, int]]:
    n = end - begin
    if k <= 0 or n < 0:
        raise ValueError(f"invalid partition: [{begin},{end}) into {k}")
    base, extra = divmod(n, k)
    out: List[Tuple[int, int]] = []
    lo = begin
    for i in range(k):
        hi = lo + base + (1 if i < extra else 0)
        out.append((lo, hi))
        lo = hi
    return out
