"""Peer identity.

A peer is identified by (IPv4 as u32, port as u16) — the same compact,
hashable identity the reference uses (reference: srcs/go/plan/addr.go:10-59,
srcs/go/plan/id.go). The identity doubles as the wire address of the peer's
control-plane server and as the key for consensus digests, so it must have a
canonical binary encoding: 6 bytes little-endian (u32 ipv4, u16 port).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

_ID_STRUCT = struct.Struct("<IH")  # (ipv4: u32, port: u16) little-endian


def parse_ipv4(s: str) -> int:
    """Parse dotted-quad IPv4 into a host-order u32."""
    parts = s.split(".")
    if len(parts) != 4:
        raise ValueError(f"invalid IPv4: {s!r}")
    value = 0
    for p in parts:
        if not p.isdigit():  # reject whitespace, '+', '_' forms int() allows
            raise ValueError(f"invalid IPv4: {s!r}")
        b = int(p)
        if not 0 <= b <= 255:
            raise ValueError(f"invalid IPv4: {s!r}")
        value = (value << 8) | b
    return value


def format_ipv4(ipv4: int) -> str:
    return ".".join(str((ipv4 >> shift) & 0xFF) for shift in (24, 16, 8, 0))


@dataclass(frozen=True, order=True)
class PeerID:
    """Identity and control-plane address of one worker or runner process."""

    ipv4: int
    port: int

    def __post_init__(self):
        if not 0 <= self.port <= 0xFFFF:
            raise ValueError(f"invalid port: {self.port}")

    @classmethod
    def parse(cls, s: str) -> "PeerID":
        host, _, port = s.rpartition(":")
        if not host or not port:
            raise ValueError(f"invalid peer id: {s!r}")
        return cls(ipv4=parse_ipv4(host), port=int(port))

    @classmethod
    def from_host(cls, host: str, port: int) -> "PeerID":
        return cls(ipv4=parse_ipv4(host), port=port)

    @property
    def host(self) -> str:
        return format_ipv4(self.ipv4)

    def colocated_with(self, other: "PeerID") -> bool:
        return self.ipv4 == other.ipv4

    def to_bytes(self) -> bytes:
        return _ID_STRUCT.pack(self.ipv4, self.port)

    @classmethod
    def from_bytes(cls, b: bytes) -> "PeerID":
        ipv4, port = _ID_STRUCT.unpack(b)
        return cls(ipv4=ipv4, port=port)

    def uid(self, init_version: int = 0) -> int:
        """Pack identity + first-seen cluster version into a u64.

        Mirrors the reference's peer UID scheme (srcs/go/kungfu/peer/peer.go:
        114-118) so a restarted process at the same address is distinguishable.
        """
        return (self.ipv4 << 32) | (self.port << 16) | (init_version & 0xFFFF)

    def sock_file(self) -> str:
        """Per-port unix socket path for colocated fast transport."""
        return f"/tmp/kungfu-tpu-{self.port}.sock"

    def __str__(self) -> str:
        return f"{self.host}:{self.port}"
