"""Process-level Peer: control-plane lifecycle + elastic membership.

Wraps the native libkf peer with the cluster-level logic the reference keeps
in Go (reference: srcs/go/kungfu/peer/peer.go): lazy session, digest
consensus before any membership switch, runner notification, and the
config-server-driven resize loop. The TPU data plane (JAX mesh) is layered
separately in kungfu_tpu.parallel — this class is pure DCN control.
"""

from __future__ import annotations

import json
import os
import time
import urllib.request
from typing import Optional, Tuple

from . import env as kfenv
from .ffi import NativePeer
from .plan import Cluster, PeerID, PeerList


class Stage:
    """A versioned cluster snapshot — the config-server wire unit
    (reference: srcs/go/kungfu/runner/handler.go:18-36)."""

    def __init__(self, version: int, cluster: Cluster):
        self.version = version
        self.cluster = cluster

    def to_json(self) -> str:
        return json.dumps(
            {
                "version": self.version,
                "cluster": json.loads(self.cluster.to_json()),
            }
        )

    @classmethod
    def from_json(cls, s: str) -> "Stage":
        d = json.loads(s)
        return cls(
            version=int(d["version"]),
            cluster=Cluster.from_json(json.dumps(d["cluster"])),
        )

    def digest(self) -> bytes:
        return self.version.to_bytes(4, "little") + self.cluster.to_bytes()


def fetch_url(url: str, timeout: float = 5.0) -> str:
    """GET text from http(s):// or file:// URLs (tests use file://)."""
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def put_url(url: str, body: str, timeout: float = 5.0) -> None:
    req = urllib.request.Request(
        url, data=body.encode(), method="PUT",
        headers={"Content-Type": "application/json"},
    )
    urllib.request.urlopen(req, timeout=timeout).read()


class Peer:
    """One worker's control-plane endpoint.

    Usually constructed from the KF_* env protocol (`Peer()`), which the
    kfrun launcher populates; without it the process is a standalone
    single-worker cluster.
    """

    def __init__(self, config: Optional[kfenv.Config] = None):
        self.config = config or kfenv.from_env()
        self._workers = self.config.init_peers
        self._version = self.config.version
        self._started = False
        self._metrics = None
        if self.config.single_process:
            self._native = None
        else:
            self._native = NativePeer(
                str(self.config.self_id),
                str(self._workers),
                version=self._version,
                strategy=self.config.strategy,
                timeout_ms=self.config.timeout_ms or 300_000,
            )

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Peer":
        if self._started:
            return self
        if self._native is not None:
            self._native.start()
            # reference blocks in updateTo's Barrier until the whole
            # cluster is up (peer.go:137-159)
            self._native.barrier()
        if os.environ.get("KF_ENABLE_MONITORING"):
            # reference serves /metrics on peer port + 10000
            # (monitor/server.go:15-25, peer.go:89-97)
            from .monitor import METRICS_PORT_OFFSET, MetricsServer
            port = self.config.self_id.port + METRICS_PORT_OFFSET
            if port > 65535:
                print(f"[kf] monitoring disabled: metrics port {port} "
                      "out of range (peer port too high)", flush=True)
            else:
                try:
                    self._metrics = MetricsServer(self, port).start()
                except OSError as e:
                    print(f"[kf] monitoring disabled: {e}", flush=True)
        self._started = True
        return self

    def stop(self):
        if self._metrics is not None:
            self._metrics.stop()
            self._metrics = None
        if self._native is not None:
            self._native.stop()
        self._started = False

    def close(self):
        if self._native is not None:
            self._native.close()
            self._native = None

    # -- introspection ------------------------------------------------------

    @property
    def rank(self) -> int:
        return 0 if self._native is None else self._native.rank

    @property
    def size(self) -> int:
        return 1 if self._native is None else self._native.size

    @property
    def local_rank(self) -> int:
        return 0 if self._native is None else self._native.local_rank

    @property
    def local_size(self) -> int:
        return 1 if self._native is None else self._native.local_size

    @property
    def version(self) -> int:
        return self._version

    @property
    def uid(self) -> int:
        return self.config.self_id.uid(self.config.version)

    @property
    def workers(self) -> PeerList:
        return self._workers

    # -- collectives / store (control plane) --------------------------------

    def barrier(self):
        if self._native is not None:
            self._native.barrier()

    def all_reduce(self, x, op="sum", name=""):
        return x.copy() if self._native is None else self._native.all_reduce(
            x, op=op, name=name)

    def broadcast(self, x, root=0, name=""):
        return x.copy() if self._native is None else self._native.broadcast(
            x, root=root, name=name)

    def all_gather(self, x, name=""):
        if self._native is None:
            return x[None, ...].copy()
        return self._native.all_gather(x, name=name)

    def reduce(self, x, op="sum", root=0, name=""):
        """Reduce to `root`; returns the result there, None elsewhere."""
        if self._native is None:
            return x.copy()
        return self._native.reduce(x, op=op, root=root, name=name)

    def gather(self, x, root=0, name=""):
        """Gather shards to `root`; stacked array there, None elsewhere."""
        if self._native is None:
            return x[None, ...].copy()
        return self._native.gather(x, root=root, name=name)

    def consensus(self, data: bytes, name: str = "consensus") -> bool:
        return True if self._native is None else self._native.consensus(
            data, name=name)

    def save(self, name, x, version=None):
        if self._native is not None:
            self._native.save(name, x, version=version)

    def request(self, rank, name, like, version=None):
        if self._native is None:
            raise RuntimeError("request() needs a multi-process cluster")
        return self._native.request(rank, name, like, version=version)

    def ping(self, rank) -> int:
        return 0 if self._native is None else self._native.ping(rank)

    def stats(self):
        if self._native is None:
            return {"egress_bytes": 0, "ingress_bytes": 0}
        return self._native.stats()

    def latencies(self):
        """RTT (us) to every peer; 0 for self. (reference:
        srcs/go/kungfu/session/monitoring.go)"""
        return [0 if r == self.rank else self.ping(r)
                for r in range(self.size)]

    # -- elastic membership --------------------------------------------------

    def resize_from_url(self, url: str = "") -> Tuple[bool, bool]:
        """Poll the config server and, on an agreed new cluster, switch epoch.

        Returns (changed, keep): `changed` = a new epoch was adopted;
        `keep` = this worker remains a member (if False the caller should
        exit and let the runner reap it). Mirrors the reference's
        ResizeClusterFromURL consensus-retry loop (peer.go:208-233).
        """
        url = url or self.config.config_server
        if not url:
            return False, True
        if self._native is None:
            return False, True
        # Every member runs this consensus loop once per call — even when
        # its own fetch shows no change. Skipping the round when the local
        # fetch looks current would desynchronize against a peer that just
        # fetched a *newer* stage (it would block in consensus forever
        # while we run training collectives). The FIXED channel name keeps
        # retry attempts FIFO-paired across peers even when they observe
        # the config server at different moments (reference:
        # peer.go:208-233 consensus-retry loop).
        while True:
            try:
                stage = Stage.from_json(fetch_url(url))
            except Exception:
                # transient config-server error: still take part in the
                # consensus round (peers are gated on it), voting with the
                # current membership so the round resolves as "no change"
                # or "disagree -> retry" (the reference likewise tolerates
                # fetch hiccups rather than dying)
                stage = Stage(self._version,
                              Cluster(runners=PeerList(),
                                      workers=self._workers))
            if self.consensus(stage.digest(), name="kf::resize"):
                break
            time.sleep(0.05)
        if stage.version == self._version:
            return False, True
        return self._propose(stage)

    def _propose(self, stage: Stage) -> Tuple[bool, bool]:
        new_workers = stage.cluster.workers
        keep = new_workers.rank(self.config.self_id) is not None
        if self._workers.disjoint(new_workers):
            print("[kf] WARNING: new cluster disjoint from old; "
                  "training state will be lost", flush=True)
        # tell every runner to reconcile its local workers for this stage
        payload = stage.to_json().encode()
        for runner in stage.cluster.runners:
            try:
                self._native.send_control(str(runner), "update", payload)
            except Exception as e:  # a dead runner must not block resize
                print(f"[kf] notify runner {runner} failed: {e}", flush=True)
        old_workers = self._workers
        # adopt the epoch in Python state only once the native switch (and
        # the join barrier) succeeded — otherwise a failed/timed-out join
        # would leave this worker believing it reached an epoch it never
        # entered, wedging every later resize poll
        if keep:
            self._native.update(str(new_workers), stage.version)
            self._native.barrier()
        else:
            # fence: leave the old epoch so stale sends fail fast
            self._native.update(str(PeerList([self.config.self_id])),
                                stage.version)
        self._version = stage.version
        self._workers = new_workers
        changed = not old_workers == new_workers
        return changed, keep

    def propose_new_size(self, new_size: int, url: str = ""):
        """Resize the current cluster spec and PUT it to the config server
        (reference: srcs/go/kungfu/peer/legacy.go:19-45)."""
        url = url or self.config.config_server
        if not url:
            raise RuntimeError("no config server configured")
        get_url = url
        put_target = url.replace("/get", "/put")
        stage = Stage.from_json(fetch_url(get_url))
        new_cluster = stage.cluster.resize(new_size)
        new_stage = Stage(version=stage.version + 1, cluster=new_cluster)
        put_url(put_target, new_stage.to_json())
