"""Process-level Peer: control-plane lifecycle + elastic membership.

Wraps the native libkf peer with the cluster-level logic the reference keeps
in Go (reference: srcs/go/kungfu/peer/peer.go): lazy session, digest
consensus before any membership switch, runner notification, and the
config-server-driven resize loop. The TPU data plane (JAX mesh) is layered
separately in kungfu_tpu.parallel — this class is pure DCN control.
"""

from __future__ import annotations

import http.client
import io
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Tuple

from . import env as kfenv
from . import ffi
from . import retrying
from .ffi import NativePeer
from .plan import Cluster, PeerList


class Stage:
    """A versioned cluster snapshot — the config-server wire unit
    (reference: srcs/go/kungfu/runner/handler.go:18-36)."""

    def __init__(self, version: int, cluster: Cluster):
        self.version = version
        self.cluster = cluster

    def to_json(self) -> str:
        return json.dumps(
            {
                "version": self.version,
                "cluster": json.loads(self.cluster.to_json()),
            }
        )

    @classmethod
    def from_json(cls, s: str) -> "Stage":
        d = json.loads(s)
        return cls(
            version=int(d["version"]),
            cluster=Cluster.from_json(json.dumps(d["cluster"])),
        )

    def digest(self) -> bytes:
        return self.version.to_bytes(4, "little") + self.cluster.to_bytes()


# -- replica-aware keep-alive HTTP verbs (docs/control_plane.md) --------------
#
# With KF_CONFIG_SERVERS set, every consumer of fetch_url/put_url/
# post_url — resize polls, watcher recovery proposals, serve workers,
# TraceShipper, SLOPolicy stats — gains replica failover WITHOUT
# per-call-site changes: a URL whose scheme://netloc matches one of the
# listed replica bases is retargeted across the tier. KF_SERVE_ROUTERS
# gets the same treatment for the admission-router front door. Three
# mechanisms, all inside one HTTP *attempt* (the caller's RetryPolicy
# still owns backoff between attempts):
#
# - **307 following**: a follower redirects writes to the leader; the
#   hop is followed manually (bounded), preserving method + body. When
#   a redirect points at a corpse (a follower vouching for a just-dead
#   leader), the hop re-resolves across KF_CONFIG_SERVERS instead of
#   burning the whole attempt on one dead address.
# - **candidate rotation**: a connection-LEVEL failure (refused/reset/
#   timeout — retrying.is_conn_failure) moves to the next replica; an
#   HTTP-level error (e.g. 503 mid-election) raises to the retry
#   policy, whose backoff is the right medicine for "no leader yet".
# - **connection pooling**: requests ride per-(scheme, host, port)
#   keep-alive connections, so the per-iteration serve traffic
#   (append_batch, resize polls) stops paying TCP connect + a fresh
#   server-side handler thread per call. A reused connection the
#   server idled out gets ONE transparent resend on a fresh socket.
#
# The last replica that actually answered (post-redirect, so usually
# the leader) is remembered and tried first next time; the leader
# learned from a write (direct 200 or a 307 Location) is additionally
# pinned first for subsequent writes.

_MAX_REDIRECT_HOPS = 4
_POOL_MAX_PER_HOST = 4
_replica_mu = threading.Lock()
_preferred_replica = ""  # kf: guarded_by(_replica_mu)
_leader_hint = ""  # kf: guarded_by(_replica_mu)
_pool_mu = threading.Lock()
_pool: Dict[str, List[http.client.HTTPConnection]] = {}  # kf: guarded_by(_pool_mu)
_pool_stats = {"opened": 0, "reused": 0}  # kf: guarded_by(_pool_mu)


def _replica_bases() -> tuple:
    """The configured replica tier (validated bases), or ()."""
    return kfenv.env_server_list(kfenv.CONFIG_SERVERS)


def _router_bases() -> tuple:
    """The configured admission-router tier (validated bases), or ()."""
    return kfenv.env_server_list("KF_SERVE_ROUTERS")


def _url_base(url: str) -> str:
    parts = urllib.parse.urlsplit(url)
    return f"{parts.scheme}://{parts.netloc}"


def _failover_candidates(url: str, write: bool = False) -> list:
    """URLs to try for one attempt, best-guess base first. A URL
    outside both configured tiers (file://, a worker's own front-end)
    passes through untouched. Routers are stateless, so router URLs
    just rotate; replica URLs are additionally ordered leader-first
    for writes (the leader hint) and last-responder-first otherwise."""
    base = _url_base(url)
    routers = _router_bases()
    if base in routers:
        order = [base] + [b for b in routers if b != base]
        suffix = url[len(base):]
        return [b + suffix for b in order]
    bases = _replica_bases()
    if not bases or base not in bases:
        return [url]
    with _replica_mu:
        preferred = _preferred_replica
        leader = _leader_hint
    order = [base] + [b for b in bases if b != base]
    for hint in (preferred, leader if write else ""):
        if hint in order and hint != order[0]:
            order.remove(hint)
            order.insert(0, hint)
    suffix = url[len(base):]
    return [b + suffix for b in order]


def _remember_replica(url: str, write: bool = False) -> None:
    global _preferred_replica, _leader_hint
    base = _url_base(url)
    if base in _replica_bases():
        with _replica_mu:
            _preferred_replica = base
            if write:  # a write only succeeds at the leader
                _leader_hint = base


def _forget_leader(base: str) -> None:
    global _leader_hint
    with _replica_mu:
        if _leader_hint == base:
            _leader_hint = ""


def _pool_take(key: str) -> Optional[http.client.HTTPConnection]:
    with _pool_mu:
        conns = _pool.get(key)
        if conns:
            _pool_stats["reused"] += 1
            return conns.pop()
    return None


def _pool_put(key: str, conn: http.client.HTTPConnection) -> None:
    with _pool_mu:
        conns = _pool.setdefault(key, [])
        if len(conns) < _POOL_MAX_PER_HOST:
            conns.append(conn)
            return
    conn.close()


def pool_stats() -> dict:
    with _pool_mu:
        return dict(_pool_stats)


def reset_transport() -> None:
    """Close every pooled connection and drop cached hints (tests)."""
    global _preferred_replica, _leader_hint
    with _pool_mu:
        drained = [c for conns in _pool.values() for c in conns]
        _pool.clear()
        _pool_stats["opened"] = 0
        _pool_stats["reused"] = 0
    for conn in drained:
        try:
            conn.close()
        except OSError:
            pass
    with _replica_mu:
        _preferred_replica = ""
        _leader_hint = ""


def _request_once(target: str, method: str, body: Optional[bytes],
                  timeout: float) -> Tuple[int, bytes, "http.client.HTTPMessage"]:
    """One HTTP exchange over a pooled keep-alive connection.

    Returns (status, body_bytes, headers) for EVERY status — HTTP-level
    errors are classified by the caller, not raised here. Connection-
    level failures raise OSError subclasses (retrying.is_conn_failure's
    class). A reused connection that the server closed while idle gets
    one transparent resend on a fresh socket — safe because the request
    demonstrably never reached a handler (the stale-FIN race)."""
    parts = urllib.parse.urlsplit(target)
    key = f"{parts.scheme}://{parts.netloc}"
    path = parts.path or "/"
    if parts.query:
        path += "?" + parts.query
    headers = {"Content-Type": "application/json"} \
        if body is not None else {}
    conn_cls = http.client.HTTPSConnection if parts.scheme == "https" \
        else http.client.HTTPConnection
    for attempt in (0, 1):
        conn = _pool_take(key) if attempt == 0 else None
        reused = conn is not None
        if conn is None:
            conn = conn_cls(parts.hostname, parts.port, timeout=timeout)
            with _pool_mu:
                _pool_stats["opened"] += 1
            try:
                # connect eagerly to disable Nagle: a keep-alive
                # request is a small write-write-read, and Nagle +
                # delayed ACK turns every round trip into a ~40 ms
                # stall (one-shot urlopen never noticed — the close
                # flushed it)
                conn.connect()
                conn.sock.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_NODELAY, 1)
            except OSError:
                conn.close()
                raise
        else:
            conn.timeout = timeout
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
        except (http.client.RemoteDisconnected, ConnectionResetError,
                BrokenPipeError):
            conn.close()
            if reused:
                continue  # idle keep-alive conn died under us; resend fresh
            raise
        except Exception:
            conn.close()
            raise
        if resp.will_close:
            conn.close()
        else:
            _pool_put(key, conn)
        return resp.status, data, resp.headers
    raise http.client.RemoteDisconnected("pooled connection resend failed")


def _open_following_redirects(url: str, method: str,
                              body: Optional[bytes],
                              timeout: float) -> Tuple[str, str]:
    """Keep-alive request that follows same-method 307/308 hops (the
    follower→leader write-redirect contract) and re-resolves from
    KF_CONFIG_SERVERS when a redirect targets a dead address. Returns
    (final_url, response_text); statuses >= 400 raise HTTPError so the
    retrying taxonomy sees the same exception shapes as urllib."""
    target = url
    suffix = url[len(_url_base(url)):]
    redirected = False
    dead: set = set()
    tried = {_url_base(url)}
    for _ in range(_MAX_REDIRECT_HOPS):
        try:
            status, data, hdrs = _request_once(target, method, body, timeout)
        except Exception as e:  # noqa: BLE001 — split below
            base = _url_base(target)
            if not (redirected and retrying.is_conn_failure(e)):
                raise
            # the redirect pointed at a corpse: forget the hint and
            # re-resolve across the tier instead of failing the attempt.
            # Each base is re-resolved to at most once — when they're
            # exhausted the conn failure raises, and the caller's
            # candidate rotation / retry policy takes over.
            _forget_leader(base)
            dead.add(base)
            alt = [b for b in _replica_bases()
                   if b not in dead and b not in tried]
            if not alt:
                raise
            tried.add(alt[0])
            target = alt[0] + suffix
            redirected = False
            continue
        if status in (307, 308) and hdrs.get("Location"):
            target = urllib.parse.urljoin(target, hdrs["Location"])
            if method != "GET":  # the redirect target IS the leader
                _remember_replica(target, write=True)
            redirected = True
            continue
        if status >= 400:
            raise urllib.error.HTTPError(
                target, status, data.decode(errors="replace")[:200],
                hdrs, io.BytesIO(data))
        return target, data.decode()
    raise urllib.error.HTTPError(
        target, 508, "redirect loop across config replicas", None, None)


def _control_request(url: str, method: str = "GET",
                     body: Optional[str] = None,
                     timeout: float = 5.0) -> str:
    """ONE attempt against the config tier: rotate candidates on
    connection-level failure, follow write redirects, remember who
    answered. Raises the last error when every replica is down — the
    caller's RetryPolicy classifies and backs off from there."""
    if url.startswith("file://"):  # tests feed stages from disk
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    data = body.encode() if body is not None else None
    write = method != "GET"
    candidates = _failover_candidates(url, write=write)
    last: Optional[BaseException] = None
    for i, candidate in enumerate(candidates):
        try:
            final, out = _open_following_redirects(
                candidate, method, data, timeout)
            _remember_replica(final, write=write)
            return out
        except Exception as e:  # noqa: BLE001 — split below
            if i + 1 < len(candidates) and retrying.is_conn_failure(e):
                _forget_leader(_url_base(candidate))
                last = e
                continue  # this replica is unreachable; try a sibling
            raise
    assert last is not None
    raise last


def fetch_url(url: str, timeout: float = 5.0,
              retry: Optional[retrying.RetryPolicy] = None) -> str:
    """GET text from http(s):// or file:// URLs (tests use file://).

    Goes through the shared control-plane retry policy (transient
    faults backed off and logged, permanent ones raised immediately);
    pass ``retrying.NO_RETRY`` for single-shot semantics when the
    caller owns its own poll loop. Replica-aware when
    KF_CONFIG_SERVERS is set (see above)."""
    if retry is None:
        retry = retrying.control_plane_policy(name=f"GET {url}")

    def _get() -> str:
        return _control_request(url, "GET", None, timeout)

    return retry.run(_get)


def put_url(url: str, body: str, timeout: float = 5.0,
            retry: Optional[retrying.RetryPolicy] = None) -> None:
    if retry is None:
        retry = retrying.control_plane_policy(name=f"PUT {url}")

    def _put() -> None:
        _control_request(url, "PUT", body, timeout)

    retry.run(_put)


def post_url(url: str, body: str, timeout: float = 5.0,
             retry: Optional[retrying.RetryPolicy] = None) -> str:
    """POST a JSON body, returning the response text — the serve
    front-end's ingest verb (kungfu_tpu/serve/frontend.py). Same
    shared retry policy as fetch_url/put_url: transient faults
    (incl. 429 admission backpressure) back off and retry, permanent
    ones (400 malformed submit) raise immediately."""
    if retry is None:
        retry = retrying.control_plane_policy(name=f"POST {url}")

    def _post() -> str:
        return _control_request(url, "POST", body, timeout)

    return retry.run(_post)


class Peer:
    """One worker's control-plane endpoint.

    Usually constructed from the KF_* env protocol (`Peer()`), which the
    kfrun launcher populates; without it the process is a standalone
    single-worker cluster.
    """

    def __init__(self, config: Optional[kfenv.Config] = None):
        self.config = config or kfenv.from_env()
        self._workers = self.config.init_peers
        self._version = self.config.version
        self._started = False
        self._metrics = None
        # per-phase wall times (ms) of the most recent epoch switch —
        # the decomposition the MTTR/adaptation benchmarks publish
        self.last_resize_phases: dict = {}
        if self.config.single_process:
            self._native = None
        else:
            self._native = NativePeer(
                str(self.config.self_id),
                str(self._workers),
                version=self._version,
                strategy=self.config.strategy,
                timeout_ms=self.config.timeout_ms or 300_000,
            )

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Peer":
        if self._started:
            return self
        if self._native is not None:
            self._native.start()
            # reference blocks in updateTo's Barrier until the whole
            # cluster is up (peer.go:137-159)
            self._native.barrier()
        if os.environ.get("KF_ENABLE_MONITORING"):
            # reference serves /metrics on peer port + 10000
            # (monitor/server.go:15-25, peer.go:89-97)
            from .monitor import METRICS_PORT_OFFSET, MetricsServer
            port = self.config.self_id.port + METRICS_PORT_OFFSET
            if port > 65535:
                print(f"[kf] monitoring disabled: metrics port {port} "
                      "out of range (peer port too high)", flush=True)
            else:
                try:
                    self._metrics = MetricsServer(self, port).start()
                except OSError as e:
                    print(f"[kf] monitoring disabled: {e}", flush=True)
        self._started = True
        return self

    def stop(self):
        if self._metrics is not None:
            self._metrics.stop()
            self._metrics = None
        if self._native is not None:
            self._native.stop()
        self._started = False

    def close(self):
        if self._native is not None:
            self._native.close()
            self._native = None

    # -- introspection ------------------------------------------------------

    @property
    def rank(self) -> int:
        return 0 if self._native is None else self._native.rank

    @property
    def size(self) -> int:
        return 1 if self._native is None else self._native.size

    @property
    def local_rank(self) -> int:
        return 0 if self._native is None else self._native.local_rank

    @property
    def local_size(self) -> int:
        return 1 if self._native is None else self._native.local_size

    @property
    def version(self) -> int:
        return self._version

    @property
    def uid(self) -> int:
        return self.config.self_id.uid(self.config.version)

    @property
    def workers(self) -> PeerList:
        return self._workers

    @property
    def host_index(self) -> int:
        """Index of this worker's host among the CURRENT membership's
        distinct hosts, in first-seen rank order — the coordinate the
        ``crash_host`` chaos fault matches on (every rank derives the
        same host numbering from its replica of the PeerList, so a
        host-scoped fault fires on exactly the colocated set)."""
        hosts = self._workers.hosts()
        try:
            return hosts.index(self.config.self_id.ipv4)
        except ValueError:
            return 0  # single-process / not in list: degenerate host 0

    # -- collectives / store (control plane) --------------------------------

    def barrier(self):
        if self._native is not None:
            self._native.barrier()

    def all_reduce(self, x, op="sum", name=""):
        return x.copy() if self._native is None else self._native.all_reduce(
            x, op=op, name=name)

    def all_reduce_inplace(self, x, op="sum", name=""):
        """All-reduce INTO `x` (no landing copy; see
        `NativePeer.all_reduce_inplace`). Single-process: no-op.
        Returns `x`."""
        if self._native is not None:
            self._native.all_reduce_inplace(x, op=op, name=name)
        return x

    def broadcast(self, x, root=0, name=""):
        return x.copy() if self._native is None else self._native.broadcast(
            x, root=root, name=name)

    def broadcast_inplace(self, x, root=0, name=""):
        """Broadcast from `root` INTO `x` (no copies; see
        `NativePeer.broadcast_inplace`). Single-process: no-op.
        Returns `x`."""
        if self._native is not None:
            self._native.broadcast_inplace(x, root=root, name=name)
        return x

    def all_gather(self, x, name=""):
        if self._native is None:
            return x[None, ...].copy()
        return self._native.all_gather(x, name=name)

    def reduce(self, x, op="sum", root=0, name=""):
        """Reduce to `root`; returns the result there, None elsewhere."""
        if self._native is None:
            return x.copy()
        return self._native.reduce(x, op=op, root=root, name=name)

    def gather(self, x, root=0, name=""):
        """Gather shards to `root`; stacked array there, None elsewhere."""
        if self._native is None:
            return x[None, ...].copy()
        return self._native.gather(x, root=root, name=name)

    def consensus(self, data: bytes, name: str = "consensus") -> bool:
        return True if self._native is None else self._native.consensus(
            data, name=name)

    def save(self, name, x, version=None):
        if self._native is not None:
            self._native.save(name, x, version=version)

    def request(self, rank, name, like, version=None):
        if self._native is None:
            raise RuntimeError("request() needs a multi-process cluster")
        return self._native.request(rank, name, like, version=version)

    def ping(self, rank) -> int:
        return 0 if self._native is None else self._native.ping(rank)

    def stats(self):
        if self._native is None:
            return {"egress_bytes": 0, "ingress_bytes": 0}
        return self._native.stats()

    def link_stats(self):
        """Cumulative payload bytes per wire link class
        ({tcp, unix, shm}; docs/collectives.md)."""
        if self._native is None:
            zero = {c: 0 for c in ffi.LINK_CLASSES}
            return {"egress": dict(zero), "ingress": dict(zero)}
        return self._native.link_stats()

    @property
    def hierarchical(self) -> bool:
        """True when collectives run the KF_HIER=1 hierarchical
        decomposition (intra-host -> masters -> intra-host)."""
        return (self._native is not None
                and self._native.hierarchical)

    @property
    def shm_fallbacks(self) -> int:
        """Per-pair shm→socket degradations (docs/collectives.md)."""
        return 0 if self._native is None else self._native.shm_fallbacks

    def publish_link_metrics(self) -> None:
        """Incrementally publish kf_wire_bytes_total{link=...} and
        kf_link_fallback_total from the native per-link-class counters.
        Called by the data paths (gradient pipeline, streaming resync)
        after their wire work so /metrics attributes traffic to
        {tcp, unix, shm} — and makes the degraded-transport mode
        visible on /metrics, not just in logs."""
        from .trace import metrics

        egress = self.link_stats()["egress"]
        last = getattr(self, "_last_link_egress", {})
        for cls, total in egress.items():
            delta = total - last.get(cls, 0)
            if delta > 0:
                metrics.REGISTRY.inc("kf_wire_bytes_total", delta,
                                     link=cls)
        self._last_link_egress = egress
        fallbacks = self.shm_fallbacks
        delta = fallbacks - getattr(self, "_last_shm_fallbacks", 0)
        if delta > 0:
            metrics.REGISTRY.inc("kf_link_fallback_total", delta)
        self._last_shm_fallbacks = fallbacks

    def latencies(self):
        """RTT (us) to every peer; 0 for self. (reference:
        srcs/go/kungfu/session/monitoring.go)"""
        return [0 if r == self.rank else self.ping(r)
                for r in range(self.size)]

    # -- elastic membership --------------------------------------------------

    def resize_from_url(self, url: str = "") -> Tuple[bool, bool]:
        """Poll the config server and, on an agreed new cluster, switch epoch.

        Returns (changed, keep): `changed` = a new epoch was adopted;
        `keep` = this worker remains a member (if False the caller should
        exit and let the runner reap it). Mirrors the reference's
        ResizeClusterFromURL consensus-retry loop (peer.go:208-233).
        """
        url = url or self.config.config_server
        if not url:
            return False, True
        if self._native is None:
            return False, True
        # Every member runs this consensus loop once per call — even when
        # its own fetch shows no change. Skipping the round when the local
        # fetch looks current would desynchronize against a peer that just
        # fetched a *newer* stage (it would block in consensus forever
        # while we run training collectives). The FIXED channel name keeps
        # retry attempts FIFO-paired across peers even when they observe
        # the config server at different moments (reference:
        # peer.go:208-233 consensus-retry loop).
        t0 = time.perf_counter()
        fetch_s = 0.0
        while True:
            t_round = time.perf_counter()
            try:
                # single-shot fetch: this poll runs after EVERY training
                # step, and the consensus round below already tolerates a
                # missed fetch — backing off here would stall the step
                stage = Stage.from_json(fetch_url(url,
                                                  retry=retrying.NO_RETRY))
            except (OSError, ValueError, KeyError, TypeError):
                # the taxonomy's transient faults (HTTP/socket are all
                # OSError) plus a torn/malformed stage mid-write
                # transient config-server error: still take part in the
                # consensus round (peers are gated on it), voting with the
                # current membership so the round resolves as "no change"
                # or "disagree -> retry" (the reference likewise tolerates
                # fetch hiccups rather than dying)
                stage = Stage(self._version,
                              Cluster(runners=PeerList(),
                                      workers=self._workers))
            fetch_s += time.perf_counter() - t_round
            if self.consensus(stage.digest(), name="kf::resize"):
                break
            time.sleep(0.05)
        t_consensus = time.perf_counter()
        if stage.version == self._version:
            return False, True
        phases = {
            # per-round fetch time vs everything else in the loop:
            # failed rounds and the inter-round sleeps are part of the
            # agreement wait, not of fetching
            "fetch_ms": fetch_s * 1e3,
            "consensus_ms": (t_consensus - t0 - fetch_s) * 1e3,
        }
        out = self._propose(stage)
        self.last_resize_phases = {**phases, **self.last_resize_phases}
        return out

    def _propose(self, stage: Stage) -> Tuple[bool, bool]:
        t0 = time.perf_counter()
        new_workers = stage.cluster.workers
        keep = new_workers.rank(self.config.self_id) is not None
        if self._workers.disjoint(new_workers):
            print("[kf] WARNING: new cluster disjoint from old; "
                  "training state will be lost", flush=True)
        # tell every runner to reconcile its local workers for this stage
        payload = stage.to_json().encode()
        for runner in stage.cluster.runners:
            try:
                self._native.send_control(str(runner), "update", payload)
            except (RuntimeError, OSError) as e:
                # KfError is a RuntimeError; a dead runner must not
                # block resize
                print(f"[kf] notify runner {runner} failed: {e}", flush=True)
        t_notify = time.perf_counter()
        old_workers = self._workers
        # adopt the epoch in Python state only once the native switch (and
        # the join barrier) succeeded — otherwise a failed/timed-out join
        # would leave this worker believing it reached an epoch it never
        # entered, wedging every later resize poll
        if keep:
            self._native.update(str(new_workers), stage.version)
            self._native.barrier()
        else:
            # fence: leave the old epoch so stale sends fail fast
            self._native.update(str(PeerList([self.config.self_id])),
                                stage.version)
        t_adopt = time.perf_counter()
        self._version = stage.version
        self._workers = new_workers
        changed = not old_workers == new_workers
        self.last_resize_phases = {
            "notify_ms": (t_notify - t0) * 1e3,
            "adopt_barrier_ms": (t_adopt - t_notify) * 1e3,
        }
        return changed, keep

    # -- survivor-driven failure recovery ------------------------------------

    def recover_from_url(self, url: str = "", deadline_s: float = 30.0,
                         poll=None) -> Tuple[bool, bool]:
        """Adopt a recovery stage after a collective failed with a peer
        death (KF_ERR_CONN) or stall-deadline trip (KF_ERR_TIMEOUT).

        The normal resize path (`resize_from_url`) runs a full-cluster
        consensus round before every switch — a dead member can never
        vote, so that path wedges exactly when it is needed most. Here
        the config server's monotonically versioned stage IS the
        agreement point: the detecting runner proposes a shrunken
        PeerList (watch.py `_propose_shrink`), every survivor polls
        until a newer stage that still contains it appears, and adopts
        it directly; the join barrier inside `_propose` is the fence
        proving all survivors reached the new epoch. Deterministic
        because the config server serializes proposals by version.

        Returns (recovered, keep): `recovered` False after `deadline_s`
        of polling (caller falls back to fail-fast); `keep` False when
        the recovery stage evicted this worker."""
        url = url or self.config.config_server
        if not url or self._native is None:
            return False, True
        if poll is None:
            poll = retrying.control_plane_policy(name="recover-poll",
                                                 deadline_s=None)
        deadline = time.monotonic() + deadline_s
        attempt = 0
        failed_version = None
        while time.monotonic() < deadline:
            try:
                stage = Stage.from_json(
                    fetch_url(url, retry=retrying.NO_RETRY))
            except (OSError, ValueError, KeyError, TypeError):
                stage = None  # server itself may be mid-restart
            if (stage is not None and stage.version > self._version
                    and stage.version != failed_version):
                # _propose handles both outcomes: survivors adopt the
                # epoch and barrier; an evicted worker fences itself.
                # The clock-bounded poll is deliberately OUTSIDE the
                # lockstep protocol: recovery runs when lockstep is
                # already broken (a peer died mid-collective), each
                # survivor polls independently, and _propose's join
                # barrier is the fence proving every survivor reached
                # the new epoch before any wire op runs in it
                try:
                    # kflint: disable=collective-order
                    _, keep = self._propose(stage)
                    return True, keep
                # the whole point of this loop is surviving ANY propose
                # failure mode (native KfError, barrier timeout, HTTP,
                # torn stage) by polling for the NEXT version — a missed
                # exception type here would kill recovery outright
                # kflint: disable=retry-discipline
                except Exception as e:
                    # the newer stage may still CONTAIN the dead peer (a
                    # planned resize published just before the death) —
                    # its join barrier can never complete. Don't retry
                    # that version; keep polling for the detecting
                    # runner's shrunken successor
                    failed_version = stage.version
                    print(
                        f"[kf-recover] adopt of stage "
                        f"v{stage.version} failed ({e}); polling on",
                        flush=True,
                    )
            attempt += 1
            time.sleep(min(poll.backoff_s(attempt),
                           max(0.0, deadline - time.monotonic())))
        return False, True

    def propose_new_size(self, new_size: int, url: str = ""):
        """Resize the current cluster spec and PUT it to the config server
        (reference: srcs/go/kungfu/peer/legacy.go:19-45)."""
        url = url or self.config.config_server
        if not url:
            raise RuntimeError("no config server configured")
        get_url = url
        put_target = url.replace("/get", "/put")
        stage = Stage.from_json(fetch_url(get_url))
        new_cluster = stage.cluster.resize(new_size)
        new_stage = Stage(version=stage.version + 1, cluster=new_cluster)
        try:
            put_url(put_target, new_stage.to_json())
        except Exception:
            # the PUT may have been applied with its response lost — the
            # retry layer then replays it and the replay is rejected as
            # stale — so refetch to see whether the resize actually took
            # before reporting failure
            cur = Stage.from_json(fetch_url(get_url))
            if cur.version >= new_stage.version and \
                    len(cur.cluster.workers) == new_size:
                return
            raise
