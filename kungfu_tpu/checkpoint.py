"""Checkpointing: pytree <-> .npz, dtype-exact.

The reference's elastic hook dumps every variable to
`variables-<idx>.npz` at end of run (reference: srcs/python/kungfu/
tensorflow/hooks/elastic.py:70-77). Here any JAX pytree round-trips:
leaves are flattened under their tree paths, dtypes (bf16 included, via
a view) and shapes survive exactly, and `load_checkpoint` can either
rebuild the flat dict or restore into the structure of a template tree.

Live joiner state transfer is separate (elastic/hooks.py resync_params
streams over DCN); this is durable on-disk state for restart-from-zero
— the complement the elastic runtime needs when the whole cluster dies.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

_BF16_SUFFIX = "::bf16"  # np.savez cannot store bfloat16 natively


def fsync_dir(path: str) -> None:
    """fsync a directory so a completed rename survives power loss —
    the shared half of every durable-write sequence here and in
    checkpoint_async (one copy, so the two tiers cannot drift)."""
    fd = os.open(path or ".", os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _path_str(path) -> str:
    out = []
    for p in path:
        if hasattr(p, "key"):
            out.append(str(p.key))
        elif hasattr(p, "idx"):
            out.append(str(p.idx))
        else:
            out.append(str(p))
    return "/".join(out)


def flatten_tree(tree) -> Dict[str, np.ndarray]:
    """{tree/path: host array}; bfloat16 leaves stored as a u16 view.

    Raises on key names the flat encoding cannot represent ('/' inside a
    component, the reserved bf16 suffix, '__step__') — a clear error
    beats a silently corrupted checkpoint.
    """
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in flat:
        for p in path:
            name = str(getattr(p, "key", getattr(p, "idx", p)))
            if "/" in name:
                raise ValueError(
                    f"cannot checkpoint key {name!r}: '/' collides with "
                    "the flat path separator")
        key = _path_str(path)
        if key == "__step__" or key.endswith(_BF16_SUFFIX):
            raise ValueError(f"cannot checkpoint reserved key {key!r}")
        a = np.asarray(jax.device_get(leaf))
        if a.dtype == jax.numpy.bfloat16:
            key += _BF16_SUFFIX
            a = a.view(np.uint16)
        if key in out:
            raise ValueError(f"duplicate flat key {key!r}")
        out[key] = a
    return out


def save_checkpoint(path: str, tree, step: Optional[int] = None) -> str:
    """Write a pytree to `path` (.npz appended if missing); returns the
    final filename. `step` is stored under the reserved key `__step__`."""
    if not path.endswith(".npz"):
        path += ".npz"
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = flatten_tree(tree)
    if step is not None:
        payload["__step__"] = np.asarray(step, np.int64)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        # durability, not just atomicity: os.replace alone protects
        # against torn files, but without fsync a power loss can drop
        # the data blocks (or the rename itself) after save_checkpoint
        # returned success — flush the file, then persist the rename
        # by fsyncing the containing directory
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic: a crash never leaves a torn file
    fsync_dir(d)
    return path


def load_checkpoint(path: str, like: Any = None):
    """Read a checkpoint.

    Returns `(tree_or_dict, step)` — `step` is None when absent. With
    `like`, values are restored into that pytree's structure (paths must
    match); without it, the flat {path: array} dict is returned.
    """
    flat: Dict[str, np.ndarray] = {}
    step = None
    with np.load(path) as loaded:
        for key in loaded.files:
            if key == "__step__":
                step = int(loaded[key])
                continue
            a = loaded[key]
            if key.endswith(_BF16_SUFFIX):
                key = key[: -len(_BF16_SUFFIX)]
                a = a.view(jax.numpy.bfloat16)
            flat[key] = a
    if like is None:
        return flat, step
    paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for path, leaf in paths:
        key = _path_str(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        a = flat[key]
        if tuple(a.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"shape mismatch for {key!r}: checkpoint {a.shape} vs "
                f"template {np.shape(leaf)}")
        leaves.append(a)
    return jax.tree_util.tree_unflatten(treedef, leaves), step


class OrbaxCheckpointManager:
    """Durable checkpoints via orbax: async saves, sharded restores.

    The npz path above is the dependency-free restart-from-zero format
    (one host, host memory); this manager is the production path for
    GSPMD state: saves happen in a background thread (training continues
    through the write), arrays land in orbax's sharded on-disk format,
    and `restore(..., like=sharded_tree)` materializes leaves DIRECTLY
    with the target `NamedSharding`s — no host-memory round trip, which
    matters when the state doesn't fit one host.

    Usage:
        mgr = OrbaxCheckpointManager(dir, max_to_keep=3)
        mgr.save(step, {"params": params, "opt": opt_state})
        tree, step = mgr.restore(like={"params": params_sharded, ...})
        mgr.close()   # drain pending async writes
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, tree) -> None:
        """Queue (async) or write (sync) checkpoint for `step`."""
        self._mgr.save(step, args=self._ocp.args.StandardSave(tree))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None, like: Any = None):
        """Returns (tree, step). `like` (a pytree of arrays, possibly
        sharded) restores each leaf with its template's sharding and
        dtype; without it, arrays arrive as orbax defaults them."""
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint steps under {self._dir}")
        if like is None:
            restored = self._mgr.restore(step)
        else:
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    jax.numpy.shape(x), x.dtype,
                    sharding=getattr(x, "sharding", None)),
                like)
            restored = self._mgr.restore(
                step, args=self._ocp.args.StandardRestore(abstract))
        return restored, step

    def wait(self) -> None:
        """Block until queued async saves hit disk."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
