"""Checkpointing: pytree <-> .npz, dtype-exact.

The reference's elastic hook dumps every variable to
`variables-<idx>.npz` at end of run (reference: srcs/python/kungfu/
tensorflow/hooks/elastic.py:70-77). Here any JAX pytree round-trips:
leaves are flattened under their tree paths, dtypes (bf16 included, via
a view) and shapes survive exactly, and `load_checkpoint` can either
rebuild the flat dict or restore into the structure of a template tree.

Live joiner state transfer is separate (elastic/hooks.py resync_params
streams over DCN); this is durable on-disk state for restart-from-zero
— the complement the elastic runtime needs when the whole cluster dies.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

_BF16_SUFFIX = "::bf16"  # np.savez cannot store bfloat16 natively


def _path_str(path) -> str:
    out = []
    for p in path:
        if hasattr(p, "key"):
            out.append(str(p.key))
        elif hasattr(p, "idx"):
            out.append(str(p.idx))
        else:
            out.append(str(p))
    return "/".join(out)


def flatten_tree(tree) -> Dict[str, np.ndarray]:
    """{tree/path: host array}; bfloat16 leaves stored as a u16 view.

    Raises on key names the flat encoding cannot represent ('/' inside a
    component, the reserved bf16 suffix, '__step__') — a clear error
    beats a silently corrupted checkpoint.
    """
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in flat:
        for p in path:
            name = str(getattr(p, "key", getattr(p, "idx", p)))
            if "/" in name:
                raise ValueError(
                    f"cannot checkpoint key {name!r}: '/' collides with "
                    "the flat path separator")
        key = _path_str(path)
        if key == "__step__" or key.endswith(_BF16_SUFFIX):
            raise ValueError(f"cannot checkpoint reserved key {key!r}")
        a = np.asarray(jax.device_get(leaf))
        if a.dtype == jax.numpy.bfloat16:
            key += _BF16_SUFFIX
            a = a.view(np.uint16)
        if key in out:
            raise ValueError(f"duplicate flat key {key!r}")
        out[key] = a
    return out


def save_checkpoint(path: str, tree, step: Optional[int] = None) -> str:
    """Write a pytree to `path` (.npz appended if missing); returns the
    final filename. `step` is stored under the reserved key `__step__`."""
    if not path.endswith(".npz"):
        path += ".npz"
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = flatten_tree(tree)
    if step is not None:
        payload["__step__"] = np.asarray(step, np.int64)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)  # atomic: a crash never leaves a torn file
    return path


def load_checkpoint(path: str, like: Any = None):
    """Read a checkpoint.

    Returns `(tree_or_dict, step)` — `step` is None when absent. With
    `like`, values are restored into that pytree's structure (paths must
    match); without it, the flat {path: array} dict is returned.
    """
    flat: Dict[str, np.ndarray] = {}
    step = None
    with np.load(path) as loaded:
        for key in loaded.files:
            if key == "__step__":
                step = int(loaded[key])
                continue
            a = loaded[key]
            if key.endswith(_BF16_SUFFIX):
                key = key[: -len(_BF16_SUFFIX)]
                a = a.view(jax.numpy.bfloat16)
            flat[key] = a
    if like is None:
        return flat, step
    paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for path, leaf in paths:
        key = _path_str(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        a = flat[key]
        if tuple(a.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"shape mismatch for {key!r}: checkpoint {a.shape} vs "
                f"template {np.shape(leaf)}")
        leaves.append(a)
    return jax.tree_util.tree_unflatten(treedef, leaves), step
