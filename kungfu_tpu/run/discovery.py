"""Host discovery: NIC subnets, DNS resolution, HTTP self-resolve.

Rebuild of the reference runner's discovery layer (reference:
srcs/go/kungfu/runner/discovery.go:157-306): `-H` entries may be
hostnames, which are resolved through DNS and filtered to the subnet of
the chosen NIC (a pod host has several interfaces; only the cluster
fabric's counts), and — when DNS is absent or ambiguous — runners
resolve each other through an HTTP handshake: every runner serves its
canonical cluster IPv4 at /resolve and polls the others by hostname.

Linux-only NIC introspection via SIOCGIFADDR/SIOCGIFNETMASK ioctls
(stdlib-only; the reference uses Go's net.Interfaces).
"""

from __future__ import annotations

import fcntl
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterable, List, Optional, Tuple

from ..plan import HostList, HostSpec, format_ipv4, parse_ipv4
from ..plan.hostspec import split_host_entry

SIOCGIFADDR = 0x8915
SIOCGIFNETMASK = 0x891B


def _ifreq_ipv4(sock: socket.socket, ioctl_no: int, nic: str) -> int:
    ifreq = struct.pack("256s", nic.encode()[:255])
    out = fcntl.ioctl(sock.fileno(), ioctl_no, ifreq)
    return struct.unpack("!I", out[20:24])[0]


def nic_ipv4_net(nic: str) -> Tuple[int, int]:
    """(address, netmask) of a NIC, both as host-order u32.

    Raises OSError for an unknown or address-less interface.
    """
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        return (_ifreq_ipv4(s, SIOCGIFADDR, nic),
                _ifreq_ipv4(s, SIOCGIFNETMASK, nic))


def list_nics() -> List[str]:
    return [name for _, name in socket.if_nameindex()]


def default_route_ipv4() -> Optional[int]:
    """Source address of the default route (UDP-connect probe), if any."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return parse_ipv4(s.getsockname()[0])
    except OSError:
        return None


def default_nic() -> Optional[str]:
    """The NIC owning the default-route source address, if any."""
    route_ip = default_route_ipv4()
    if route_ip is None:
        return None
    for nic in list_nics():
        try:
            addr, _ = nic_ipv4_net(nic)
        except OSError:
            continue
        if addr == route_ip:
            return nic
    return None


def in_subnet(ipv4: int, net_addr: int, net_mask: int) -> bool:
    return (ipv4 & net_mask) == (net_addr & net_mask)


def resolve_ipv4(name: str, subnet: Optional[Tuple[int, int]] = None) -> int:
    """IPv4 (host-order u32) of a hostname-or-dotted-quad.

    A literal IPv4 passes through. A hostname goes through DNS
    (getaddrinfo); with `subnet`, only addresses inside it count, and
    exactly ONE must remain (reference: resolveIPv4,
    discovery.go:157-178 — zero or multiple matches are errors because
    the wrong fabric would silently misroute all traffic).
    """
    try:
        return parse_ipv4(name)
    except ValueError:
        pass
    try:
        infos = socket.getaddrinfo(name, None, socket.AF_INET,
                                   socket.SOCK_STREAM)
    except socket.gaierror as e:
        raise ValueError(f"cannot resolve {name!r}: {e}") from None
    addrs = sorted({parse_ipv4(info[4][0]) for info in infos})
    if subnet is not None:
        addrs = [a for a in addrs if in_subnet(a, *subnet)]
    if len(addrs) != 1:
        where = f" in {format_ipv4(subnet[0])}/{bin(subnet[1]).count('1')}" \
            if subnet else ""
        raise ValueError(
            f"{name!r} resolves to {len(addrs)} addresses{where}; "
            "need exactly 1 (pass -nic to pick the cluster fabric)")
    return addrs[0]


# single -H grammar lives in plan.hostspec; re-exported here because the
# discovery layer is where hostname entries become legal
parse_host_entry = split_host_entry


def resolve_host_list(spec: str, nic: str = "") -> HostList:
    """Parse `-H`, resolving hostname entries through DNS.

    IPv4-only lists parse exactly like HostList.parse. With hostnames, a
    `nic` (or the default-route NIC) scopes DNS answers to that
    interface's subnet (reference: ResolveHostList, discovery.go:199-215).
    """
    if not spec:
        return HostList()
    entries = [parse_host_entry(h) for h in spec.split(",")]
    if all(_is_ipv4(h) for h, _, _ in entries):
        return HostList.parse(spec)
    subnet: Optional[Tuple[int, int]] = None
    chosen = nic or default_nic()
    if chosen:
        try:
            subnet = nic_ipv4_net(chosen)
        except OSError as e:
            if nic:  # explicit NIC must exist
                raise ValueError(f"bad -nic {nic!r}: {e}") from None

    def resolve(host: str) -> int:
        if nic or subnet is None:
            return resolve_ipv4(host, subnet)
        try:
            return resolve_ipv4(host, subnet)
        except ValueError:
            # the guessed default-route NIC is not the cluster fabric;
            # an unambiguous DNS answer is still safe to use
            return resolve_ipv4(host, None)

    return HostList(
        HostSpec(resolve(host), slots, public)
        for host, slots, public in entries
    )


def _is_ipv4(s: str) -> bool:
    try:
        parse_ipv4(s)
        return True
    except ValueError:
        return False


def resolve_peers_via_http(
    self_ipv4: int,
    self_port: int,
    hosts: Iterable[Tuple[str, int]],
    timeout_s: float = 60.0,
    poll_s: float = 0.25,
) -> Dict[str, int]:
    """Mutual HTTP self-resolve: every runner serves its canonical
    cluster IPv4 at /resolve and polls each (hostname, port) until all
    answer (reference: resolvePeerListViaHTTP, discovery.go:239-303).
    Used when hosts can reach each other by name (orchestrator DNS,
    /etc/hosts) but DNS does not expose the fabric IPv4s.

    The server stays up until every peer has fetched OUR address too —
    finishing one's own polls first must not strand the others (the
    reference's second wg.Add(len(hosts)), discovery.go:247-259).

    Returns {hostname: ipv4}. Raises TimeoutError if any host stays
    silent past `timeout_s`.
    """
    body = format_ipv4(self_ipv4).encode()
    hosts = dict(hosts)
    served = threading.Semaphore(0)  # one release per /resolve served

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib casing)
            payload = body if self.path == "/resolve" else b""
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
            if self.path == "/resolve":
                served.release()

        def log_message(self, *a):  # quiet
            pass

    srv = ThreadingHTTPServer(("0.0.0.0", self_port), Handler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        from ..peer import fetch_url
        from ..retrying import NO_RETRY

        from ..retrying import RetryPolicy

        out: Dict[str, int] = {}
        deadline = time.monotonic() + timeout_s
        pending = dict(hosts)
        # shared control-plane backoff shape: early rounds poll fast
        # (peers usually boot within ~1s of each other), later rounds
        # back off toward 2s so a large host list doesn't hammer a
        # still-booting peer
        backoff = RetryPolicy(base_ms=poll_s * 1e3, max_ms=2000.0,
                              jitter=0.25, name="self-resolve")
        attempt = 0
        bad_answers: Dict[str, int] = {}
        while pending:
            for host, port in list(pending.items()):
                try:
                    # single-shot fetch (this loop owns the backoff);
                    # the shared wrapper keeps the taxonomy in one
                    # place. NOT named `body`: that closure variable is
                    # what our own /resolve handler serves, and
                    # rebinding it here to the fetched str made the
                    # handler crash mid-reply (bytes expected) for any
                    # peer polling us AFTER our first successful fetch
                    # — the load-dependent ordering behind the flaky
                    # two-runner test (regression-pinned in
                    # tests/test_discovery.py).
                    answer = fetch_url(f"http://{host}:{port}/resolve",
                                       timeout=2, retry=NO_RETRY)
                    out[host] = parse_ipv4(answer.strip())
                    del pending[host]
                except OSError:  # URLError/HTTPError both subclass it
                    pass
                except ValueError as e:
                    # a truncated/empty reply from a peer killed or
                    # restarting mid-write (exactly churn) heals on the
                    # next round — only REPEATED garbage from a live
                    # peer is fatal, so it surfaces before burning the
                    # whole deadline
                    bad_answers[host] = bad_answers.get(host, 0) + 1
                    if bad_answers[host] >= 3:
                        raise ValueError(
                            f"self-resolve: bad /resolve answer from "
                            f"{host}:{port} ({bad_answers[host]} in a "
                            f"row): {e}") from None
            if pending:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"self-resolve: no answer from {sorted(pending)}")
                attempt += 1
                time.sleep(backoff.backoff_s(attempt))
        # our answers are in; keep serving until each peer fetched ours
        # (best-effort: a peer that died is its own resolve failure)
        for _ in hosts:
            if not served.acquire(timeout=max(deadline - time.monotonic(),
                                              0.0)):
                break
        return out
    finally:
        srv.shutdown()
        srv.server_close()
