"""kfrun CLI: `python -m kungfu_tpu.run [flags] -- prog args...`

Flag set mirrors the reference launcher (reference: srcs/go/kungfu/runner/
flags.go:60-89): -np, -H, -self, -port-range, -strategy, -w (watch/elastic
mode), -config-server, -logdir, -q, -keep, -timeout-ms.
"""

from __future__ import annotations

import argparse
import os
import sys
import urllib.error

from ..peer import Stage, fetch_url, put_url
from ..plan import (
    DEFAULT_RUNNER_PORT,
    Cluster,
    HostList,
    PeerID,
    PortRange,
)
from .watch import simple_run, watch_run


def infer_self_ipv4() -> str:
    """Best-effort local IP discovery (reference: runner/discovery.go).
    Single-host and loopback-cluster runs just use 127.0.0.1."""
    from ..plan import format_ipv4

    from .discovery import default_route_ipv4

    ip = default_route_ipv4()
    return format_ipv4(ip) if ip is not None else "127.0.0.1"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kfrun", description=__doc__)
    ap.add_argument("-np", type=int, default=1, help="total workers")
    ap.add_argument("-H", dest="hosts", default="",
                    help="host list host:slots[:pub],... (hostnames are "
                         "DNS-resolved, scoped to -nic's subnet)")
    ap.add_argument("-self", dest="self_ip", default="",
                    help="this runner's IPv4")
    ap.add_argument("-nic", default="",
                    help="network interface of the cluster fabric "
                         "(scopes hostname resolution + self detection)")
    ap.add_argument("-port-range", dest="port_range", default="10000-11000")
    ap.add_argument("-strategy", default="AUTO")
    ap.add_argument("-w", dest="watch", action="store_true",
                    help="watch mode (elastic)")
    ap.add_argument("-config-server", dest="config_server", default="",
                    help="config server /get URL")
    ap.add_argument("-runner-port", type=int, default=DEFAULT_RUNNER_PORT)
    ap.add_argument("-logdir", default=".kfrun-logs")
    ap.add_argument("-q", dest="quiet", action="store_true",
                    help="don't mirror worker output to console")
    ap.add_argument("-keep", action="store_true",
                    help="watch mode: stay alive at 0 local workers")
    ap.add_argument("-recover", action="store_true",
                    default=os.environ.get("KF_RECOVER", "0") == "1",
                    help="watch mode: on an unexpected worker death, "
                         "propose a shrunken membership through the "
                         "config server so survivors keep training "
                         "(default from KF_RECOVER)")
    ap.add_argument("-recovery-budget", dest="recovery_budget", type=int,
                    default=None,
                    help="max survivor-driven recoveries before falling "
                         "back to fail-fast (default KF_RECOVERY_BUDGET "
                         "or 3)")
    ap.add_argument("prog", nargs=argparse.REMAINDER,
                    help="-- program and args")
    args = ap.parse_args(argv)

    prog = args.prog
    if prog and prog[0] == "--":
        prog = prog[1:]
    if not prog:
        ap.error("no program given (use: kfrun [flags] -- prog args)")

    from .discovery import nic_ipv4_net, resolve_host_list

    if args.nic:
        try:
            nic_ipv4_net(args.nic)
        except OSError as e:
            print(f"[kfrun] bad -nic {args.nic!r}: {e}", file=sys.stderr)
            return 2
    try:
        hosts = resolve_host_list(args.hosts, args.nic) \
            if args.hosts else None
    except ValueError as e:
        print(f"[kfrun] bad -H: {e}", file=sys.stderr)
        return 2
    if args.self_ip:
        self_ip = args.self_ip
    elif hosts is None:
        self_ip = "127.0.0.1"
    else:
        # pick the host-list entry this machine matches: any local NIC
        # address that is listed, else loopback if listed, else
        # (single-host list) that host — otherwise require -self
        from ..plan import format_ipv4, parse_ipv4

        from .discovery import in_subnet, list_nics

        host_ips = {h.ipv4 for h in hosts}
        loopback_net = (parse_ipv4("127.0.0.0"), parse_ipv4("255.0.0.0"))
        nics = [args.nic] if args.nic else list_nics()
        local = []
        for nic in nics:
            try:
                local.append(nic_ipv4_net(nic)[0])
            except OSError:
                pass
        # fabric addresses first; loopback only as the explicit fallback
        # (lo is first in if_nameindex and must not shadow the real NIC)
        matches = [ip for ip in local
                   if ip in host_ips and not in_subnet(ip, *loopback_net)]
        if matches:
            self_ip = format_ipv4(matches[0])
        elif parse_ipv4("127.0.0.1") in host_ips:
            self_ip = "127.0.0.1"
        elif len(hosts) == 1:
            self_ip = format_ipv4(hosts[0].ipv4)
        else:
            inferred = infer_self_ipv4()
            print(
                f"[kfrun] cannot tell which of {args.hosts} is this host "
                f"(inferred {inferred}); pass -self",
                file=sys.stderr,
            )
            return 2
    if hosts is None:
        hosts = HostList.single_host(args.np, self_ip)
    port_range = PortRange.parse(args.port_range)
    workers = hosts.gen_peer_list(args.np, port_range)
    runners = hosts.gen_runner_list(args.runner_port)
    cluster = Cluster(runners=runners, workers=workers)
    err = cluster.validate()
    if err:
        print(f"[kfrun] invalid cluster: {err}", file=sys.stderr)
        return 2
    stage = Stage(version=0, cluster=cluster)
    runner_id = PeerID.from_host(self_ip, args.runner_port)

    if args.config_server:
        # seed the config server if it has no stage yet, so workers'
        # resize polls and external resize tools share one source of truth
        from ..retrying import NO_RETRY, RetryPolicy

        try:
            # single-shot probe: a 404 here is the expected "unseeded"
            # answer, not a fault to back off from
            fetch_url(args.config_server, retry=NO_RETRY)
        except (urllib.error.URLError, urllib.error.HTTPError, OSError):
            try:
                # generous window: runners routinely RACE their config
                # server up (same launch script), and a server that
                # never gets seeded serves 404 to every later resize
                # and recovery — worth several seconds of patience
                put_url(args.config_server.replace("/get", "/put"),
                        stage.to_json(),
                        retry=RetryPolicy(attempts=8, base_ms=100,
                                          max_ms=2000, deadline_s=10.0,
                                          name="seed config server"))
            except (OSError, ValueError) as e:  # HTTP layer / bad URL
                print(f"[kfrun] cannot seed config server: {e}",
                      file=sys.stderr)

    if args.watch:
        slots = hosts.slots_of(runner_id.ipv4) or args.np
        if args.recover and not args.config_server:
            print("[kfrun] -recover needs -config-server (the agreement "
                  "point survivors poll); running fail-fast",
                  file=sys.stderr)
            # an inherited KF_RECOVER=1 would still reach the workers
            # (spawn copies os.environ) and make them swallow the real
            # collective error — clear it so fail-fast stays fail-fast
            os.environ.pop("KF_RECOVER", None)
        if args.recover and args.config_server:
            # workers must know recovery is on (they poll instead of
            # dying) — but ONLY when it actually is: exporting this
            # without a config server would make workers swallow the
            # original collective error and die with an opaque rc
            os.environ["KF_RECOVER"] = "1"
        return watch_run(
            prog,
            runner_id,
            slots=slots,
            initial=stage,
            strategy=args.strategy,
            config_server=args.config_server,
            logdir=args.logdir,
            quiet=args.quiet,
            keep=args.keep,
            recover=args.recover,
            recovery_budget=args.recovery_budget,
        )
    # simple mode has no supervisor to propose a shrunken stage, so an
    # inherited KF_RECOVER=1 (left over from a watch-mode run's shell)
    # would only make workers swallow the real collective error while
    # they poll for a recovery that can never arrive
    os.environ.pop("KF_RECOVER", None)
    return simple_run(
        prog,
        runner_id.ipv4,
        stage,
        strategy=args.strategy,
        config_server=args.config_server,
        logdir=args.logdir,
        quiet=args.quiet,
        parent=runner_id,
    )


if __name__ == "__main__":
    sys.exit(main())
