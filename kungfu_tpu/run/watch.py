"""Worker supervision: simple mode and the elastic watch loop.

Rebuild of the reference's runner (reference: srcs/go/kungfu/runner/
{watch,simple,handler}.go). The runner owns a libkf control endpoint on
the runner port; workers (or the config server path through them) push
"update" stages there, and the watch loop reconciles the local worker set:
diff old/new membership, terminate departed workers, spawn joiners with a
fresh epoch env. By default a worker crash (nonzero exit that wasn't an
intentional removal) fails the whole runner fast, matching the
reference's fail-fast-and-respawn-from-survivors model (SURVEY §5.3).

With recovery enabled (`-recover` / KF_RECOVER=1) the runner instead
becomes the failure DETECTOR of a survivor-driven recovery loop: it
proposes a shrunken PeerList (current stage minus every dead worker
reaped in the same supervision pass — a whole-host SIGKILL arrives as
a burst and must become ONE proposal, never intermediate stages still
containing a corpse) to the config server, and the surviving workers —
whose collectives failed fast with KF_ERR_CONN — poll for that stage
and adopt it without the dead peers' votes (`Peer.recover_from_url`),
restore state over the live resync path, and keep training. The proposal budget (`KF_RECOVERY_BUDGET`)
bounds how many times this may happen before the runner falls back to
fail-fast; every phase emits a KF_MTTR marker so
`benchmarks/recovery.py` can decompose detect/consensus/restore.
"""

from __future__ import annotations

import os
import queue
import subprocess
import time
from typing import Dict, List, Optional

from .. import trace
from ..ffi import NativePeer
from ..peer import Stage, fetch_url, put_url
from ..plan import Cluster, PeerID, PeerList
from ..retrying import NO_RETRY, control_plane_policy
from .job import ChipPool, Proc, WarmPool, activate_warm, spawn_worker


def _local_workers(workers: PeerList, host_ipv4: int) -> PeerList:
    return workers.on_host(host_ipv4)


def simple_run(
    prog: List[str],
    self_ipv4: int,
    stage: Stage,
    strategy: str = "AUTO",
    config_server: str = "",
    logdir: str = ".",
    quiet: bool = False,
    parent: Optional[PeerID] = None,
) -> int:
    """Non-elastic: spawn all local workers, wait, fail if any fails
    (reference: runner/simple.go)."""
    local = _local_workers(stage.cluster.workers, self_ipv4)
    if not local:
        print("[kfrun] no workers scheduled on this host", flush=True)
        return 2
    pool = ChipPool(len(local))
    procs = [
        spawn_worker(
            prog,
            w,
            stage.cluster.workers,
            stage.version,
            strategy=strategy,
            parent=parent,
            config_server=config_server,
            chip=pool.get(),
            logdir=logdir,
            quiet=quiet,
        )
        for w in local
    ]
    code = 0
    for p in procs:
        c = p.wait()
        if c != 0:
            print(f"[kfrun] worker rank {p.rank} exited with {c}",
                  flush=True)
            code = code or c
    return code


class Watcher:
    """Elastic supervisor state machine."""

    def __init__(
        self,
        prog: List[str],
        runner_id: PeerID,
        slots: int,
        strategy: str,
        config_server: str,
        logdir: str,
        quiet: bool,
        keep: bool,
        recover: bool = False,
        recovery_budget: Optional[int] = None,
    ):
        self.prog = prog
        self.runner_id = runner_id
        self.strategy = strategy
        self.config_server = config_server
        self.logdir = logdir
        self.quiet = quiet
        self.keep = keep
        # survivor-driven recovery: needs a config server (the agreement
        # point survivors poll) — without one we can only fail fast
        self.recover = recover and bool(config_server)
        self.recovery_budget = (
            int(os.environ.get("KF_RECOVERY_BUDGET", "3"))
            if recovery_budget is None else recovery_budget)
        self.recoveries = 0
        self.pool = ChipPool(slots)
        self.slots = slots
        # joiners activate from pre-warmed interpreters (imports already
        # paid) so a resize costs one env write, not a python+jax boot —
        # the bulk of round 2's ~6s resize latency (KF_PREWARM=0 opts out)
        self.warm = WarmPool(prog, target=0, quiet=True, logdir=logdir)
        self.procs: Dict[PeerID, Proc] = {}
        # the last stage this runner APPLIED — the recovery proposal's
        # fallback base when the config server answers 404 (restarted
        # empty, or the boot-time seed lost its race)
        self.last_stage: Optional[Stage] = None
        # set when a crash burst emptied this host under recovery: the
        # schedule/policy is about to re-grow onto it, so the runner
        # must LINGER instead of exiting at 0 local workers (a
        # whole-host death would otherwise leave nobody to spawn the
        # replacement joiners and wedge the survivors' join barrier) —
        # bounded, so a run that finishes at the shrunken size still
        # terminates
        self.regrow_deadline: Optional[float] = None
        self.expected_exits: set = set()
        self.stages: "queue.Queue[Optional[Stage]]" = queue.Queue()
        self.seen_versions: set = set()
        self.current_version = -1
        self.control = NativePeer(str(runner_id), "", version=0)
        self.control.set_control_handler(self._on_control)
        # the runner is the failure DETECTOR: its detect/propose events
        # open the structured MTTR timeline every worker's flight
        # records close (docs/observability.md)
        trace.install(role="runner")

    # -- control channel ----------------------------------------------------

    def _on_control(self, name: str, payload: bytes):
        if name == "exit":
            self.stages.put(None)
            return
        if name != "update":
            return
        try:
            stage = Stage.from_json(payload.decode())
        except (ValueError, KeyError, TypeError, UnicodeDecodeError) as e:
            # malformed update must not kill the runner
            print(f"[kfrun] bad update stage: {e}", flush=True)
            return
        # dedup: every worker notifies every runner (reference
        # handler.go:86-105 dedups by version the same way)
        if stage.version in self.seen_versions:
            return
        self.seen_versions.add(stage.version)
        self.stages.put(stage)

    # -- reconciliation -----------------------------------------------------

    def _apply(self, stage: Stage):
        if stage.version <= self.current_version:
            return
        self.current_version = stage.version
        self.last_stage = stage
        new_local = set(
            _local_workers(stage.cluster.workers, self.runner_id.ipv4))
        old_local = set(self.procs.keys())
        for peer in old_local - new_local:
            proc = self.procs.pop(peer)
            proc.terminate()
            try:
                proc.popen.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                # wedged in a native collective or trapping SIGTERM:
                # escalate rather than hanging the reconcile loop
                proc.kill()
                proc.popen.wait()
            # reaped synchronously: do NOT leave a stale expected-exit
            # marker behind — a future joiner may reuse this PeerID and a
            # real crash of it must still fail fast
            self.expected_exits.discard(peer)
            if proc.chip is not None:
                self.pool.put(proc.chip)
        for peer in sorted(new_local - old_local):
            kwargs = dict(
                strategy=self.strategy,
                parent=self.runner_id,
                config_server=self.config_server,
                chip=self.pool.get(),
                logdir=self.logdir,
                quiet=self.quiet,
            )
            proc = activate_warm(self.warm, peer, stage.cluster.workers,
                                 stage.version, **kwargs)
            if proc is None:  # no warm slot ready: cold spawn
                proc = spawn_worker(self.prog, peer,
                                    stage.cluster.workers, stage.version,
                                    **kwargs)
            self.procs[peer] = proc
        if self.procs:
            self.regrow_deadline = None  # host repopulated
        print(
            f"[kfrun] epoch {stage.version}: {len(self.procs)} local "
            f"worker(s) of {len(stage.cluster.workers)}",
            flush=True,
        )

    def _check_procs(self) -> Optional[int]:
        """Reap exits. Crash (unexpected nonzero) => recover (when
        enabled and within budget) or fail fast. ALL deaths reaped in
        one pass are proposed as ONE shrink: a whole emulated host
        SIGKILLed (the crash_host chaos fault) reaps as a burst, and
        publishing intermediate stages that still contain a dead peer
        would race survivors into join barriers no one can complete."""
        crashed = []
        for peer, proc in list(self.procs.items()):
            code = proc.popen.poll()
            if code is None:
                continue
            del self.procs[peer]
            if proc.chip is not None:
                self.pool.put(proc.chip)
            expected = peer in self.expected_exits
            self.expected_exits.discard(peer)
            if code != 0 and not expected:
                crashed.append((peer, proc, code))
        if not crashed:
            return None
        if self._propose_shrink(crashed):
            return None
        for peer, proc, code in crashed:
            print(
                f"[kfrun] worker rank {proc.rank} crashed with {code}; "
                "failing fast",
                flush=True,
            )
        return crashed[0][2]

    def _propose_shrink(self, crashed) -> bool:
        """Survivor-driven recovery, detection side: publish ONE
        shrunken stage (minus every dead worker in `crashed`) to the
        config server. The survivors — blocked on KF_ERR_CONN — poll
        for it and adopt it without the dead peers' votes
        (Peer.recover_from_url). A multi-death burst counts as one
        recovery against the budget. Returns False when recovery is
        off/over budget/impossible, which sends the caller down
        today's fail-fast path."""
        if not self.recover:
            return False
        if self.recoveries >= self.recovery_budget:
            print(
                f"[kfrun] recovery budget exhausted "
                f"({self.recoveries}/{self.recovery_budget}); failing fast",
                flush=True,
            )
            return False
        t_detect = time.time()
        dead_set = [peer for peer, _proc, _code in crashed]
        for peer, proc, code in crashed:
            print(
                f"KF_MTTR detect t={t_detect * 1e3:.1f} rank={proc.rank} "
                f"peer={peer} code={code}",
                flush=True,
            )
            trace.event("recovery.detect", cat="recovery",
                        dead_rank=proc.rank, code=code)
        # The runner's whole propose window must END before the
        # survivors' recovery polls give up (KF_RECOVERY_DEADLINE_MS,
        # default 30 s) — a proposal landing after the survivors exited
        # turns a recoverable fault into total job loss. Budget HALF the
        # worker deadline and derive both from the same knob.
        worker_deadline_s = float(
            os.environ.get("KF_RECOVERY_DEADLINE_MS", "30000")) / 1e3
        propose_deadline = time.monotonic() + min(15.0,
                                                  worker_deadline_s / 2)
        # fetch-modify-put with the shared backoff; a stale-version 400
        # means another runner's proposal won the race — refetch and
        # re-check whether the dead peer is even still a member
        policy = control_plane_policy(name="recovery-propose",
                                      attempts=3, deadline_s=4.0)
        attempt = 0
        while True:
            attempt += 1
            try:
                stage = Stage.from_json(
                    fetch_url(self.config_server, retry=policy))
            except (OSError, ValueError, KeyError, TypeError) as e:
                # unreachable OR unseeded (404: the server restarted
                # with empty state, or the boot-time seed lost its
                # race): fall back to the last stage this runner
                # applied — the shrunken successor then RE-SEEDS the
                # server, healing its lost state as a side effect
                if self.last_stage is None:
                    print(
                        f"[kfrun] recovery: config server unreachable "
                        f"and no applied stage to fall back to: {e}",
                        flush=True,
                    )
                    return False
                print(
                    f"[kfrun] recovery: config server fetch failed "
                    f"({e}); proposing from last applied stage "
                    f"v{self.last_stage.version}",
                    flush=True,
                )
                stage = self.last_stage
            workers = stage.cluster.workers
            if all(workers.rank(d) is None for d in dead_set):
                # already removed (another proposal / a planned resize
                # covering these deaths): survivors will adopt that
                # stage. Nothing was proposed HERE, so neither the
                # budget nor the KF_MTTR proposed marker applies — but
                # an emptied host must STILL linger for the re-grow
                # (the wedge does not care who published the shrink)
                print(
                    f"[kfrun] recovery: {dead_set} already absent from "
                    f"stage v{stage.version}; survivors adopt that",
                    flush=True,
                )
                self._arm_regrow_linger()
                return True
            remaining = PeerList(w for w in workers if w not in dead_set)
            if not remaining:
                print("[kfrun] recovery: no survivors to shrink to",
                      flush=True)
                return False
            shrunken = Stage(
                version=stage.version + 1,
                cluster=Cluster(runners=stage.cluster.runners,
                                workers=remaining),
            )
            try:
                put_url(self.config_server.replace("/get", "/put"),
                        shrunken.to_json(), retry=NO_RETRY)
                break
            except (OSError, ValueError):  # 400 stale-version is OSError
                # version race or server hiccup: refetch decides which
                if time.monotonic() >= propose_deadline:
                    print("[kfrun] recovery: could not publish shrunken "
                          "stage; failing fast", flush=True)
                    return False
                time.sleep(min(policy.backoff_s(attempt),
                               max(0.0, propose_deadline
                                   - time.monotonic())))
        self.recoveries += 1
        print(
            f"KF_MTTR proposed t={time.time() * 1e3:.1f} "
            f"propose_ms={(time.time() - t_detect) * 1e3:.1f} "
            f"survivors={len(self.procs)} local "
            f"recovery={self.recoveries}/{self.recovery_budget}",
            flush=True,
        )
        trace.event("recovery.propose", cat="recovery",
                    stage_version=shrunken.version,
                    survivors=len(self.procs))
        self._arm_regrow_linger()
        return True

    def _arm_regrow_linger(self) -> None:
        """A recovery that emptied this host (whole-host death): the
        schedule observes size < target at the survivors' next step
        and re-grows ONTO this host — stay alive to spawn the
        replacement joiners, bounded by twice the survivors' recovery
        deadline so a run that ends shrunken still terminates."""
        if self.procs:
            return
        worker_deadline_s = float(
            os.environ.get("KF_RECOVERY_DEADLINE_MS", "30000")) / 1e3
        linger_s = 2 * worker_deadline_s
        self.regrow_deadline = time.monotonic() + linger_s
        print(
            f"[kfrun] recovery emptied this host; lingering up to "
            f"{linger_s:.0f}s for the schedule's re-grow",
            flush=True,
        )

    def run(self, initial: Optional[Stage]) -> int:
        self.control.start()
        try:
            if initial is not None:
                self.stages.put(initial)
            while True:
                try:
                    stage = self.stages.get(timeout=0.25)
                    if stage is None:  # exit control message
                        break
                    self._apply(stage)
                except queue.Empty:
                    pass
                code = self._check_procs()
                if code is not None:
                    self._shutdown()
                    return code
                # keep enough warm slots for the largest possible join
                # wave; spawned during steady state, never in a resize
                self.warm.target = max(0, self.slots - len(self.procs))
                self.warm.refill()
                if not self.procs and not self.keep \
                        and self.current_version >= 0 \
                        and self.stages.empty():
                    if self.regrow_deadline is not None:
                        if time.monotonic() < self.regrow_deadline:
                            continue  # awaiting the post-crash re-grow
                        print("[kfrun] no re-grow arrived within the "
                              "linger window; exiting", flush=True)
                    break
            self._shutdown()
            return 0
        finally:
            self.control.close()

    def _shutdown(self):
        self.warm.shutdown()
        for proc in self.procs.values():
            proc.terminate()
        deadline = time.time() + 5.0
        for proc in self.procs.values():
            if proc.popen.poll() is None and time.time() < deadline:
                try:
                    proc.popen.wait(timeout=max(0.1,
                                                deadline - time.time()))
                except subprocess.TimeoutExpired:
                    proc.kill()
        self.procs.clear()


def watch_run(
    prog: List[str],
    runner_id: PeerID,
    slots: int,
    initial: Optional[Stage],
    strategy: str = "AUTO",
    config_server: str = "",
    logdir: str = ".",
    quiet: bool = False,
    keep: bool = False,
    recover: bool = False,
    recovery_budget: Optional[int] = None,
) -> int:
    w = Watcher(prog, runner_id, slots, strategy, config_server, logdir,
                quiet, keep, recover=recover,
                recovery_budget=recovery_budget)
    return w.run(initial)
