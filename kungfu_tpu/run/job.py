"""Worker process creation: env injection, chip assignment, log capture.

TPU translation of the reference's job package (reference: srcs/go/job/
{job,proc,gpu_resource,cuda_visible_device}.go): the GPU slot bitmask pool
becomes a TPU chip pool driving TPU_VISIBLE_DEVICES (plus
JAX_PLATFORMS=cpu passthrough for host-simulation runs), and each worker's
stdout/stderr is captured to a log file and optionally tee'd to the
console with a rank prefix (reference: srcs/go/utils/iostream).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import env as kfenv
from ..plan import PeerID, PeerList


class ChipPool:
    """Bitmask allocator of local accelerator slots (reference GPUPool,
    gpu_resource.go:17-51)."""

    def __init__(self, slots: int):
        self._free = list(range(slots))
        self._lock = threading.Lock()

    def get(self) -> Optional[int]:
        with self._lock:
            return self._free.pop(0) if self._free else None

    def put(self, chip: int):
        with self._lock:
            self._free.append(chip)
            self._free.sort()


@dataclass
class Proc:
    """One supervised worker process."""

    peer: PeerID
    rank: int
    popen: subprocess.Popen
    chip: Optional[int]
    log_path: str
    pumps: List[threading.Thread] = field(default_factory=list)

    def wait(self) -> int:
        code = self.popen.wait()
        for t in self.pumps:
            t.join(timeout=2.0)
        return code

    def terminate(self):
        if self.popen.poll() is None:
            self.popen.terminate()

    def kill(self):
        if self.popen.poll() is None:
            self.popen.kill()


_COLORS = [31, 32, 33, 34, 35, 36, 91, 92, 93, 94, 95, 96]


def _pump(stream, log_file, prefix: str, color: int, quiet: bool):
    """Forward a worker stream to its log file (+ prefixed console)."""
    with log_file:
        for raw in iter(stream.readline, b""):
            log_file.write(raw)
            log_file.flush()
            if not quiet:
                line = raw.decode(errors="replace").rstrip("\n")
                sys.stderr.write(
                    f"\x1b[{color}m[{prefix}]\x1b[0m {line}\n")
        stream.close()


def spawn_worker(
    prog: List[str],
    self_id: PeerID,
    peers: PeerList,
    version: int,
    strategy: str = "AUTO",
    parent: Optional[PeerID] = None,
    config_server: str = "",
    chip: Optional[int] = None,
    logdir: str = ".",
    quiet: bool = False,
    extra_env: Optional[Dict[str, str]] = None,
) -> Proc:
    rank = peers.rank(self_id)
    env = dict(os.environ)
    env.update(
        kfenv.worker_env(
            self_id,
            peers,
            version,
            strategy=strategy,
            parent=parent,
            config_server=config_server,
        )
    )
    if chip is not None:
        # one TPU chip per slot, like CUDA_VISIBLE_DEVICES per GPU slot
        # (reference: job.go:41-47); harmless when workers run on CPU
        env["TPU_VISIBLE_DEVICES"] = str(chip)
        env["TPU_PROCESS_BOUNDS"] = env.get("TPU_PROCESS_BOUNDS", "")
    if extra_env:
        env.update(extra_env)

    os.makedirs(logdir, exist_ok=True)
    log_path = os.path.join(logdir, f"worker-{rank}-{self_id.port}.log")
    popen = subprocess.Popen(
        prog,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        bufsize=0,
    )
    log_file = open(log_path, "wb")
    color = _COLORS[(rank if rank is not None else 0) % len(_COLORS)]
    pump = threading.Thread(
        target=_pump,
        args=(popen.stdout, log_file, str(rank), color, quiet),
        daemon=True,
    )
    pump.start()
    return Proc(
        peer=self_id,
        rank=rank if rank is not None else -1,
        popen=popen,
        chip=chip,
        log_path=log_path,
        pumps=[pump],
    )
