"""Worker process creation: env injection, chip assignment, log capture.

TPU translation of the reference's job package (reference: srcs/go/job/
{job,proc,gpu_resource,cuda_visible_device}.go): the GPU slot bitmask pool
becomes a TPU chip pool driving TPU_VISIBLE_DEVICES (plus
JAX_PLATFORMS=cpu passthrough for host-simulation runs), and each worker's
stdout/stderr is captured to a log file and optionally tee'd to the
console with a rank prefix (reference: srcs/go/utils/iostream).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import chaos
from .. import env as kfenv
from ..plan import PeerID, PeerList


class ChipPool:
    """Bitmask allocator of local accelerator slots (reference GPUPool,
    gpu_resource.go:17-51)."""

    def __init__(self, slots: int):
        # grabbed by the reconcile loop and worker-exit callbacks at once
        self._free = list(range(slots))  # kf: guarded_by(_lock)
        self._lock = threading.Lock()

    def get(self) -> Optional[int]:
        with self._lock:
            return self._free.pop(0) if self._free else None

    def put(self, chip: int):
        with self._lock:
            self._free.append(chip)
            self._free.sort()


@dataclass
class Proc:
    """One supervised worker process."""

    peer: PeerID
    rank: int
    popen: subprocess.Popen
    chip: Optional[int]
    log_path: str
    pumps: List[threading.Thread] = field(default_factory=list)

    def wait(self) -> int:
        code = self.popen.wait()
        for t in self.pumps:
            t.join(timeout=2.0)
        return code

    def terminate(self):
        if self.popen.poll() is None:
            self.popen.terminate()

    def kill(self):
        if self.popen.poll() is None:
            self.popen.kill()


_COLORS = [31, 32, 33, 34, 35, 36, 91, 92, 93, 94, 95, 96]


def _pump(stream, log_file, prefix: str, color: int, quiet: bool):
    """Forward a worker stream to its log file (+ prefixed console)."""
    with log_file:
        for raw in iter(stream.readline, b""):
            log_file.write(raw)
            log_file.flush()
            if not quiet:
                line = raw.decode(errors="replace").rstrip("\n")
                sys.stderr.write(
                    f"\x1b[{color}m[{prefix}]\x1b[0m {line}\n")
        stream.close()


def _worker_env_delta(
    self_id: PeerID,
    peers: PeerList,
    version: int,
    strategy: str,
    parent: Optional[PeerID],
    config_server: str,
    chip: Optional[int],
    extra_env: Optional[Dict[str, str]],
    logdir: str,
) -> Dict[str, str]:
    env = dict(
        kfenv.worker_env(
            self_id,
            peers,
            version,
            strategy=strategy,
            parent=parent,
            config_server=config_server,
        )
    )
    if chip is not None:
        # one TPU chip per slot, like CUDA_VISIBLE_DEVICES per GPU slot
        # (reference: job.go:41-47); harmless when workers run on CPU
        env["TPU_VISIBLE_DEVICES"] = str(chip)
        env["TPU_PROCESS_BOUNDS"] = os.environ.get(
            "TPU_PROCESS_BOUNDS", "")
    # persistent XLA compilation cache shared across worker GENERATIONS:
    # an elastic resize rebuilds mesh + jitted step in the new epoch's
    # workers; with the cache the recompile is a disk hit instead of a
    # from-scratch XLA run (VERDICT r2 item 5)
    if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
        env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(
            os.path.abspath(logdir), ".jax-cache")
    if extra_env:
        env.update(extra_env)
    return env


def _attach_pump(popen, rank, log_path: str, quiet: bool) -> Proc:
    # append, never truncate: a replacement joiner after a recovery
    # reuses its predecessor's (rank, port) — and the predecessor's
    # log holds its crash record (KF_CHAOS_FIRE, flight-dump notices),
    # exactly the bytes a post-mortem (and the MTTR harness) needs
    log_file = open(log_path, "ab")
    color = _COLORS[(rank if rank is not None else 0) % len(_COLORS)]
    pump = threading.Thread(
        target=_pump,
        args=(popen.stdout, log_file, str(rank), color, quiet),
        daemon=True,
    )
    pump.start()
    return popen, pump


def spawn_worker(
    prog: List[str],
    self_id: PeerID,
    peers: PeerList,
    version: int,
    strategy: str = "AUTO",
    parent: Optional[PeerID] = None,
    config_server: str = "",
    chip: Optional[int] = None,
    logdir: str = ".",
    quiet: bool = False,
    extra_env: Optional[Dict[str, str]] = None,
) -> Proc:
    rank = peers.rank(self_id)
    # chaos hook: a scheduled spawn_delay fault for this rank holds the
    # spawn here — inside the resize window — emulating a slow host
    chaos.on_spawn(rank)
    env = dict(os.environ)
    env.update(
        _worker_env_delta(self_id, peers, version, strategy, parent,
                          config_server, chip, extra_env, logdir)
    )

    os.makedirs(logdir, exist_ok=True)
    log_path = os.path.join(logdir, f"worker-{rank}-{self_id.port}.log")
    popen = subprocess.Popen(
        prog,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        bufsize=0,
    )
    popen, pump = _attach_pump(popen, rank, log_path, quiet)
    return Proc(
        peer=self_id,
        rank=rank if rank is not None else -1,
        popen=popen,
        chip=chip,
        log_path=log_path,
        pumps=[pump],
    )


def _is_python_prog(prog: List[str]) -> bool:
    """True only for programs prewarm can actually re-run via runpy:
    `python -m mod ...` or `python script.py ...`. Interpreter flags
    (`python -u x.py`) are rejected — runpy can't honor them, and a
    wrongly-warmed slot would crash at activation and fail the whole
    cluster fast. The interpreter must resolve to THIS runner's
    `sys.executable`: warm slots are spawned with it, so accepting any
    'python*' basename would warm-activate a job meant for a different
    interpreter (e.g. a venv's) under the wrong one."""
    if not prog:
        return False
    import shutil

    exe = shutil.which(prog[0]) or prog[0]
    try:
        # same interpreter file AND same bin directory: venvs symlink
        # bin/python to one base interpreter, so a realpath match alone
        # would accept a *different* venv's python (whose site-packages
        # the warm slot does not have)
        if (os.path.realpath(exe)
                != os.path.realpath(sys.executable)
                or os.path.realpath(os.path.dirname(os.path.abspath(exe)))
                != os.path.realpath(os.path.dirname(sys.executable))):
            return False
    except OSError:
        return False
    tail = prog[1:]
    if not tail:
        return False
    if tail[0] == "-m":
        return len(tail) >= 2
    return not tail[0].startswith("-")


class WarmPool:
    """Pre-spawned worker slots: interpreter + imports paid OUTSIDE the
    resize window (see `run/prewarm.py`; reference peers swap membership
    in-process in ms — peer.go:137-159 — this is the closest a
    process-per-epoch design gets).

    Only python programs can be pre-warmed (the worker runs in-process
    via runpy after activation); for anything else `take()` returns None
    and callers fall back to a cold `spawn_worker`.
    """

    def __init__(self, prog: List[str], target: int, quiet: bool = True,
                 logdir: str = "."):
        self.prog = prog
        self.target = max(0, target)
        self.quiet = quiet
        self.logdir = logdir
        self.enabled = (_is_python_prog(prog)
                        and os.environ.get("KF_PREWARM", "1") != "0")
        # warm interpreters cost ~150 MB RSS and a few seconds of
        # import-time CPU each: cap the pool and spawn ONE per refill
        # call (the supervisor loop ticks ~4x/s) at low priority, so
        # warming never competes with the cluster it serves
        self.cap = int(os.environ.get("KF_PREWARM_MAX", "2"))
        self._warm: List[subprocess.Popen] = []
        # consecutive pre-activation deaths disable the pool: a broken
        # interpreter/env would otherwise respawn ~4x/s forever
        self._failures = 0
        self._max_failures = 3

    def refill(self):
        """Top the pool up (at most one spawn per call); call from the
        supervisor's idle loop."""
        if not self.enabled:
            return
        alive = [p for p in self._warm if p.poll() is None]
        died = len(self._warm) - len(alive)
        self._warm = alive
        if died:
            self._failures += died
            if self._failures >= self._max_failures:
                print(f"[kfrun] prewarm slots died {self._failures}x "
                      "before activation; disabling the warm pool "
                      "(joiners will cold-spawn)", flush=True)
                self.enabled = False
                return
        if len(self._warm) < min(self.target, self.cap):
            env = dict(os.environ)
            # jax freezes this env var at IMPORT time, and prewarm
            # imports jax before the activation env arrives — so the
            # compile-cache dir must be present at spawn, not activation
            env.setdefault(
                "JAX_COMPILATION_CACHE_DIR",
                os.path.join(os.path.abspath(self.logdir), ".jax-cache"))
            p = subprocess.Popen(
                [sys.executable, "-m", "kungfu_tpu.run.prewarm", "--"]
                + self.prog[1:],
                env=env,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                bufsize=0,
            )
            try:
                # deprioritize AFTER the fork: a preexec_fn would run
                # python between fork and exec in a multithreaded parent
                # (the log pumps), which can deadlock
                os.setpriority(os.PRIO_PROCESS, p.pid, 19)
            except (OSError, AttributeError):
                pass
            self._warm.append(p)

    def take(self) -> Optional[subprocess.Popen]:
        """Pop a warm slot, preferring one whose imports have finished
        (prewarm prints a readiness line once it blocks on stdin)."""
        import select

        self._warm = [p for p in self._warm if p.poll() is None]
        if not self._warm:
            return None
        ready_fds = select.select(
            [p.stdout for p in self._warm], [], [], 0)[0]
        for p in self._warm:
            if p.stdout in ready_fds:
                self._warm.remove(p)
                line = p.stdout.readline()
                if b"KF_WARM_READY" in line:
                    self._failures = 0
                    return p
                # stderr is merged into stdout: early output that isn't
                # the marker means the preimport failed — not a warm slot
                print(f"[kfrun] discarding failed prewarm slot: "
                      f"{line.decode(errors='replace').strip()!r}",
                      flush=True)
                p.kill()
                self._failures += 1
                return self.take()
        return self._warm.pop(0) if self._warm else None  # still importing

    def mark_activation_ok(self):
        """A successful activation proves the pool healthy — also for
        slots popped on take()'s still-importing path, which bypasses
        the marker-read reset. Without this, scattered pre-activation
        deaths over a long run would permanently disable the pool
        despite healthy activations in between."""
        self._failures = 0

    def shutdown(self):
        for p in self._warm:
            try:
                p.stdin.close()  # EOF => prewarm exits 0
            except (OSError, ValueError):  # dead slot / already closed
                pass
        deadline = 2.0
        for p in self._warm:
            try:
                p.wait(timeout=deadline)
            except subprocess.TimeoutExpired:
                p.kill()
        self._warm.clear()


def activate_warm(
    pool: WarmPool,
    self_id: PeerID,
    peers: PeerList,
    version: int,
    strategy: str = "AUTO",
    parent: Optional[PeerID] = None,
    config_server: str = "",
    chip: Optional[int] = None,
    logdir: str = ".",
    quiet: bool = False,
    extra_env: Optional[Dict[str, str]] = None,
) -> Optional[Proc]:
    """Turn a warm slot into a live worker: one JSON env write. Returns
    None when no warm slot is available (caller cold-spawns)."""
    import json

    popen = pool.take()
    if popen is None:
        return None
    try:
        # warming ran at nice 19 to stay off the cluster's CPUs; the
        # activated WORKER must run at normal priority (root only —
        # unprivileged runners keep the inherited niceness)
        os.setpriority(os.PRIO_PROCESS, popen.pid, 0)
    except (OSError, AttributeError):
        pass
    rank = peers.rank(self_id)
    env = _worker_env_delta(self_id, peers, version, strategy, parent,
                            config_server, chip, extra_env, logdir)
    os.makedirs(logdir, exist_ok=True)
    log_path = os.path.join(logdir, f"worker-{rank}-{self_id.port}.log")
    try:
        popen.stdin.write((json.dumps(env) + "\n").encode())
        popen.stdin.flush()
        popen.stdin.close()
    except (OSError, ValueError):  # slot died / pipe already closed
        popen.kill()
        return None
    pool.mark_activation_ok()
    popen, pump = _attach_pump(popen, rank, log_path, quiet)
    return Proc(
        peer=self_id,
        rank=rank if rank is not None else -1,
        popen=popen,
        chip=chip,
        log_path=log_path,
        pumps=[pump],
    )
