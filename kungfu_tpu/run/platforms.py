"""Platform launchers: derive the kfrun invocation from a managed
platform's environment (reference: srcs/go/cmd/kungfu-modelarts-launcher +
srcs/go/plan/platforms/modelarts — the same job, for Huawei ModelArts).

The TPU analog reads the env Cloud-TPU-style pod schedulers inject on each
host (GKE TPU slices set ``TPU_WORKER_HOSTNAMES``, ``TPU_WORKER_ID``,
``TPU_ACCELERATOR_TYPE``) and turns it into ``-H``/``-self``/``-np`` flags,
so one command line works unchanged on every host of a pod:

    python -m kungfu_tpu.run.platforms -- python train.py
"""

from __future__ import annotations

import logging
import os
import socket
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional

# TPU_ACCELERATOR_TYPE suffix counts TensorCores on v2-v5p (2 cores/chip)
# but chips on v5e/v6e (1 core/chip); chips-per-host then follows from the
# host count the scheduler reports. Overridable via KF_SLOTS_PER_HOST.
_CORES_PER_CHIP = {"v2": 2, "v3": 2, "v4": 2, "v5p": 2,
                   "v5litepod": 1, "v5e": 1, "v6e": 1}


def _slots_from_accelerator(acc: str, num_hosts: int) -> int:
    """chips/host from e.g. ("v4-32", 4) -> 4 or ("v5litepod-8", 1) -> 8;
    0 when the type is unparseable."""
    family, _, suffix = acc.partition("-")
    if family not in _CORES_PER_CHIP or not suffix.isdigit():
        return 0
    chips = int(suffix) // _CORES_PER_CHIP[family]
    return max(1, chips // max(1, num_hosts))


def _resolve(host: str) -> str:
    """hostname -> IPv4, passing literal IPs through (reference resolves
    -H hostnames via DNS, runner/discovery.go)."""
    try:
        socket.inet_aton(host)
        return host
    except OSError:
        return socket.gethostbyname(host)


@dataclass
class PodSpec:
    """One host's view of the pod: every worker hostname + its own index."""

    hosts: List[str]
    self_index: int
    slots_per_host: int

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    @property
    def total_slots(self) -> int:
        return self.num_hosts * self.slots_per_host


def detect_tpu_pod(environ: Optional[Dict[str, str]] = None) -> Optional[
        PodSpec]:
    """Parse the TPU pod env; None when not on a managed TPU pod."""
    env = os.environ if environ is None else environ
    hostnames = env.get("TPU_WORKER_HOSTNAMES", "")
    if not hostnames:
        return None
    hosts = [h.strip() for h in hostnames.split(",") if h.strip()]
    try:
        self_index = int(env.get("TPU_WORKER_ID", "0"))
    except ValueError:
        raise ValueError(
            f"malformed TPU_WORKER_ID={env['TPU_WORKER_ID']!r}; every host "
            "would claim index 0")
    if not 0 <= self_index < len(hosts):
        raise ValueError(
            f"TPU_WORKER_ID={self_index} out of range for "
            f"{len(hosts)} hosts")
    if env.get("KF_SLOTS_PER_HOST"):
        slots = int(env["KF_SLOTS_PER_HOST"])
    else:
        acc = env.get("TPU_ACCELERATOR_TYPE", "")
        slots = _slots_from_accelerator(acc, len(hosts))
        if not slots:
            slots = 4
            logging.getLogger(__name__).warning(
                "unrecognized TPU_ACCELERATOR_TYPE=%r; assuming %d "
                "slots/host (set KF_SLOTS_PER_HOST to override)",
                acc, slots)
    return PodSpec(hosts=hosts, self_index=self_index, slots_per_host=slots)


def kfrun_args(
    pod: PodSpec,
    prog: List[str],
    extra_flags: Optional[List[str]] = None,
    resolve=_resolve,
) -> List[str]:
    """The kfrun argv equivalent to this pod env."""
    ips = [resolve(h) for h in pod.hosts]
    host_list = ",".join(f"{ip}:{pod.slots_per_host}" for ip in ips)
    args = [
        "-np", str(pod.total_slots),
        "-H", host_list,
        "-self", ips[pod.self_index],
    ]
    if extra_flags:
        args += extra_flags
    return args + ["--"] + prog


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    extra: List[str] = []
    if "--" in argv:
        split = argv.index("--")
        extra, prog = argv[:split], argv[split + 1:]
    else:
        prog = argv
    if not prog:
        print("usage: python -m kungfu_tpu.run.platforms "
              "[kfrun flags] -- prog args...", file=sys.stderr)
        return 2
    pod = detect_tpu_pod()
    if pod is None:
        print("[kf-platforms] no TPU pod env (TPU_WORKER_HOSTNAMES unset); "
              "running single-host", file=sys.stderr)
        pod = PodSpec(hosts=["127.0.0.1"], self_index=0,
                      slots_per_host=int(os.environ.get(
                          "KF_SLOTS_PER_HOST", "1")))
    from .__main__ import main as kfrun_main

    return kfrun_main(kfrun_args(pod, prog, extra_flags=extra))


if __name__ == "__main__":
    sys.exit(main())
