"""kfdistribute: run one copy of a program on every host, over SSH, in
parallel — the fleet bootstrap tool (reference: srcs/go/cmd/
kungfu-distribute + srcs/go/utils/runner/remote + utils/ssh).

Typical use: push the same `kfrun` invocation to each host of a pod so
every host starts its own runner:

    python -m kungfu_tpu.run.distribute -H 10.0.0.1:4,10.0.0.2:4 -- \\
        kfrun -np 8 -H 10.0.0.1:4,10.0.0.2:4 -- python train.py

Each host's output is streamed with a colored ``[host]`` prefix and
captured to ``<logdir>/<host>.log``. Fail-fast: the first host that exits
nonzero terminates the rest (the reference's remote runner cancels the
shared context on first error).
"""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys
import threading
import time
from typing import List, Optional

from ..plan import HostList
from .job import _COLORS, _pump


def ssh_command(
    host: str,
    prog: List[str],
    user: str = "",
    ssh: Optional[List[str]] = None,
) -> List[str]:
    """The argv used to run `prog` on `host`.

    `ssh` overrides the transport (tests substitute a local stub); the
    remote command is a single shell word so arguments survive the remote
    shell, like the reference quotes its remote command.
    """
    base = ssh if ssh is not None else ["ssh", "-o", "BatchMode=yes"]
    dest = f"{user}@{host}" if user else host
    return base + [dest, shlex.join(prog)]


def distribute_run(
    hosts: List[str],
    prog: List[str],
    user: str = "",
    ssh: Optional[List[str]] = None,
    logdir: str = ".",
    quiet: bool = False,
    timeout: Optional[float] = None,
) -> int:
    """Run `prog` on every host in parallel; 0 iff every host succeeded."""
    import os

    os.makedirs(logdir, exist_ok=True)
    procs: List[tuple] = []  # (host, Popen) — a list, so duplicate hosts
    pumps: List[threading.Thread] = []  # in -H each get their own process
    for i, host in enumerate(hosts):
        argv = ssh_command(host, prog, user=user, ssh=ssh)
        popen = subprocess.Popen(
            argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            bufsize=0,
        )
        procs.append((host, popen))
        log_name = (f"{host}.log" if hosts.count(host) == 1
                    else f"{host}.{i}.log")
        log_file = open(os.path.join(logdir, log_name), "wb")
        t = threading.Thread(
            target=_pump,
            args=(popen.stdout, log_file, host,
                  _COLORS[i % len(_COLORS)], quiet),
            daemon=True,
        )
        t.start()
        pumps.append(t)

    # Concurrent wait: poll every proc so a failure on *any* host is seen
    # while the others still run (a sequential wait would sit on host 0
    # for its full runtime before noticing host 1 died).
    failed: Optional[str] = None
    deadline = (time.monotonic() + timeout) if timeout else None
    try:
        while failed is None:
            running = False
            for host, popen in procs:
                code = popen.poll()
                if code is None:
                    running = True
                elif code != 0:
                    failed = f"{host} exited {code}"
                    break
            if not running or failed:
                break
            if deadline is not None and time.monotonic() > deadline:
                failed = "timeout"
                break
            time.sleep(0.05)
    except KeyboardInterrupt:
        failed = "interrupted"
    if failed:
        print(f"[kfdistribute] {failed}; terminating remaining hosts",
              file=sys.stderr)
        for _, popen in procs:
            if popen.poll() is None:
                popen.terminate()
    for _, popen in procs:
        try:
            popen.wait(timeout=10)
        except subprocess.TimeoutExpired:
            popen.kill()
    for t in pumps:
        t.join(timeout=2.0)
    return 0 if failed is None and all(
        p.returncode == 0 for _, p in procs) else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="kfdistribute", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-H", dest="hosts", required=True,
                    help="host list ip:slots[:pub],... (one run per host)")
    ap.add_argument("-user", default="", help="ssh user")
    ap.add_argument("-ssh", default="",
                    help="override ssh transport command (for tests)")
    ap.add_argument("-logdir", default=".kfdistribute-logs")
    ap.add_argument("-q", dest="quiet", action="store_true")
    ap.add_argument("-timeout", type=float, default=None,
                    help="total wall-clock limit for the whole run, seconds")
    ap.add_argument("prog", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    prog = args.prog
    if prog and prog[0] == "--":
        prog = prog[1:]
    if not prog:
        ap.error("no program given (use: kfdistribute -H ... -- prog args)")

    host_list = HostList.parse(args.hosts)
    hosts = [h.public_addr for h in host_list]
    return distribute_run(
        hosts,
        prog,
        user=args.user,
        ssh=shlex.split(args.ssh) if args.ssh else None,
        logdir=args.logdir,
        quiet=args.quiet,
        timeout=args.timeout,
    )


if __name__ == "__main__":
    sys.exit(main())
