"""Warm worker bootstrap: pay interpreter + import cost before activation.

The reference swaps cluster membership in-process in milliseconds
(reference: srcs/go/kungfu/peer/peer.go:137-159 — one Go peer object is
re-pointed at the new cluster). A Python worker can't do that across
processes: round 2 measured ~6s per elastic resize, dominated by
spawning the joiner (interpreter start + numpy/jax/kungfu_tpu imports)
inside the resize window. This module moves that cost OUT of the window:
the runner keeps a pool of "warm" processes that have already imported
the heavy stack and are blocked reading stdin; activating one is a
single write of the worker's epoch environment.

Protocol (driven by `job.WarmPool` / `job.activate_warm`):

1. Runner spawns `python -m kungfu_tpu.run.prewarm -- <prog tail>` with
   stdin=PIPE at job start / during steady state — NOT during a resize.
2. This process imports numpy, jax, kungfu_tpu (backend init stays
   lazy, so accelerator visibility env vars can still arrive later),
   then blocks on one stdin line.
3. At activation the runner writes one JSON object of env deltas
   (`kungfu_tpu.env.worker_env` + chip visibility) and closes stdin.
4. The line is applied to `os.environ` and the worker program runs
   in-process via runpy — same pid, imports already hot.

An EOF on stdin (runner shutdown before activation) exits 0.
"""

from __future__ import annotations

import json
import os
import runpy
import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--":
        argv = argv[1:]
    if not argv:
        print("prewarm: no program given", file=sys.stderr)
        return 2

    # Pay the heavy imports now, before activation. jax does NOT
    # initialize a backend at import time, so TPU_VISIBLE_DEVICES /
    # JAX_PLATFORMS from the activation env still take effect.
    try:
        import numpy  # noqa: F401
        import jax  # noqa: F401
        import kungfu_tpu  # noqa: F401
    # third-party import-time side effects can raise anything; a broken
    # optional dep must not kill the warm slot, only cost it the
    # preimport win
    # kflint: disable=retry-discipline
    except Exception as e:
        print(f"prewarm: preimport skipped: {e}", file=sys.stderr)

    # readiness marker: WarmPool.take() prefers slots whose imports are
    # done (it consumes this line); if this slot is activated early the
    # marker just lands as the first line of the worker log
    sys.stdout.write("KF_WARM_READY\n")
    sys.stdout.flush()
    line = sys.stdin.readline()
    if not line.strip():
        return 0  # runner shut down before this slot was needed
    env = json.loads(line)
    os.environ.update({str(k): str(v) for k, v in env.items()})
    if "JAX_COMPILATION_CACHE_DIR" in env:
        # jax froze the env var at import; late-bind via config so an
        # activation-time cache dir still takes effect
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir",
                              env["JAX_COMPILATION_CACHE_DIR"])
        except (ImportError, AttributeError, KeyError, ValueError):
            pass  # older jax without the config key: cold compile only

    if argv[0] == "-m":
        if len(argv) < 2:
            print("prewarm: -m needs a module", file=sys.stderr)
            return 2
        module, rest = argv[1], argv[2:]
        sys.argv = [module] + rest
        # sys.path[0] is already the cwd: this process was itself
        # launched with `python -m`, the same layout cold `python -m
        # <module>` would produce
        try:
            runpy.run_module(module, run_name="__main__", alter_sys=True)
        except SystemExit as e:
            return _exit_code(e)
        return 0
    sys.argv = argv
    # cold `python script.py` puts the SCRIPT'S directory at sys.path[0]
    # (how examples import their sibling common.py) and does NOT expose
    # the cwd; REPLACE the cwd entry this process's own `python -m`
    # launch left there, so warm == cold exactly
    sys.path[0] = os.path.dirname(os.path.abspath(argv[0]))
    try:
        runpy.run_path(argv[0], run_name="__main__")
    except SystemExit as e:
        return _exit_code(e)
    return 0


def _exit_code(e: SystemExit) -> int:
    if e.code is None:
        return 0
    if isinstance(e.code, int):
        return e.code
    print(e.code, file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
