"""kfrun — the launcher and elastic supervisor.

The role of the reference's `kungfu-run` (reference: srcs/go/cmd/kungfu-run,
srcs/go/kungfu/runner): spawn one worker process per slot with the KF_*
env-var bootstrap, assign TPU chips to local slots, supervise the workers
(fail-fast on crash), and — in watch mode — reconcile the local worker set
whenever the cluster membership changes (config-server-driven elastic
training).

Usage:
    python -m kungfu_tpu.run -np 4 -H 127.0.0.1:4 -- python3 train.py
    python -m kungfu_tpu.run -np 4 -w -config-server http://...:9100/get -- ...
"""

from .job import ChipPool, Proc, spawn_worker
from .watch import simple_run, watch_run

__all__ = ["spawn_worker", "Proc", "ChipPool", "simple_run", "watch_run"]
