"""Fused projection-head + softmax cross-entropy for LM training.

The textbook LM loss materializes `[B, T, vocab]` float32 logits twice
per step (forward activation + backward dlogits) — at GPT-2-small scale
(B=8, T=1024, V=50257) that is ~1.6 GB of pure HBM traffic per
direction, the largest single memory consumer of the train step. This
op computes

    mean_i( logsumexp_v(x_i . W_v + b_v) - (x_i . W_t_i + b_t_i) )

without ever holding float32 logits in HBM. Two schemes, selected by
`fused_cross_entropy(residual=...)`:

- **recompute** (`residual=False`): the forward is one grid pass over
  (token-block, vocab-block) with the online-logsumexp recurrence in
  VMEM scratch, saving ONLY the [N, 1] row logsumexp — no [N, V]
  array of any dtype exists. The backward runs two kernels with
  opposite grid orders, each rebuilding every logits block from x.W
  on the fly: the dW kernel (v outer, n inner) accumulates
  `dW[:, j] = sum_i x_i^T d_ij` and the bias gradient in VMEM; the dx
  kernel (n outer, v inner) accumulates `dx_i = sum_j d_ij W_j^T`.
  Cost: two extra bf16 logits passes plus per-block x/W re-streaming;
  saving: every HBM touch of an [N, V] residual — the only scheme
  whose memory footprint is independent of N*V.
- **residual=True** (default; measured faster at GPT-2 scale — see
  `fused_cross_entropy`): the forward additionally writes a *bfloat16*
  logits residual; the backward's d-kernel rebuilds
  `softmax - onehot` blockwise from that residual (d aliased over the
  same buffer) and dW/dx are two plain XLA bf16 matmuls. Fewer FLOPs,
  more HBM traffic — the right trade only when the [N, V] write is
  cheaper than a logits pass.

All big matmuls in both schemes run bfloat16 with float32
accumulation, and padding/casting happens once in ordinary
differentiable jnp ops outside the custom_vjp (JAX transposes the pad
to a slice on the way back, so callers see unpadded gradients).

No reference counterpart: the reference trains through TF's fused
`sparse_softmax_cross_entropy_with_logits` (data-parallel wrappers
only, e.g. /root/reference/srcs/python/kungfu/tensorflow/optimizers/
sync_sgd.py); this module is the TPU-native equivalent of relying on
a framework-fused loss, required here because XLA does not fuse away
the f32 logits materialization on its own.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)
# bias for padded vocab columns: exp(x - m) underflows to exactly 0 for
# any finite row max m, and the value survives a bf16 round-trip
_PAD_BIAS = -1e30

# swept on v5e at GPT-2-small scale (N=8184, H=768, V=50257):
# (bn, bv) 512/512 -> 100.0k tok/s, 1024/512 -> 101.6k, 2048/512 ->
# 97.6k, 1024/1024 -> 102.4k, 2048/1024 -> over VMEM. 1024/1024 keeps
# the W stream at 8 passes and the [bn, bv] f32 accumulator at 4 MB.
_BLOCK_N = 1024
_BLOCK_V = 1024
# Mosaic's scoped-vmem stack limit is 16 MB. Calibration points on
# v5e: h=768 at 1024/1024 blocks (estimate 14.7 MB) compiles and is
# the measured-fastest config; h=1024 at 1024/1024 (estimate 16.8 MB,
# real 18.92 MB) OOMs at compile time. The budget sits between them,
# so blocks shrink exactly when the real limit would bite.
_VMEM_BUDGET = 15 * 1024 * 1024


def _fwd_vmem_bytes(bn, h, bv):
    """Forward-kernel VMEM: double-buffered x/W/bias/target blocks +
    double-buffered outputs + the f32 matmul accumulator + scratch."""
    inputs = 2 * (bn * h * 2 + h * bv * 2 + bv * 4 + bn * 4)
    outputs = 2 * (bn * bv * 2 + 2 * bn * 4)
    acc = bn * bv * 4
    return inputs + outputs + acc + 3 * bn * 4


def _recompute_vmem_bytes(bn, h, bv):
    """Worst of the three recompute-path kernels (fwd-no-residual, dW,
    dx): shared terms are the double-buffered x/W/bias/target/lse
    inputs and the [bn, bv] f32 logits/d temporary; the dW and dx
    kernels add their f32 accumulator plus a double-buffered output."""
    inputs = 2 * (bn * h * 2 + h * bv * 2 + bv * 4 + 2 * bn * 4)
    d_tmp = bn * bv * 4
    fwd = inputs + 2 * (2 * bn * 4) + d_tmp + 3 * bn * 4
    dw = inputs + 2 * (h * bv * 2 + bv * 4) + h * bv * 4 + bv * 4 + d_tmp
    dx = inputs + 2 * (bn * h * 2) + bn * h * 4 + d_tmp
    return max(fwd, dw, dx)


def _pick_blocks(n, h, v, vmem_bytes=_fwd_vmem_bytes):
    """(bn, bv) fitting the VMEM budget, or None when no block size
    does (very large H — the un-blocked dim); callers then fall back
    to the reference path instead of hitting a Mosaic compile OOM."""
    bn = min(_BLOCK_N, _round_up(n, 16))
    bv = min(_BLOCK_V, _round_up(v, 128))
    if n > 8192 and bv > 512:
        # empirical (v5e): the SAME (1024, 1024) blocks that compile
        # and are fastest at n<=8192 hit Mosaic's scoped-vmem limit
        # inside large full-model graphs at n=16384 (18.72 MB real vs
        # a 14.7 MB estimate) — Mosaic's scheduling headroom shrinks
        # with grid extent. bv=512 is verified there and costs <1%
        # at the sizes that fit either way.
        bv = 512
    while vmem_bytes(bn, h, bv) > _VMEM_BUDGET:
        if bv > 512:
            bv //= 2
        elif bn > 128:
            bn //= 2
        else:
            return None
    return bn, bv


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def reference_cross_entropy(hidden, kernel, bias, targets):
    """Plain-XLA fallback (and numerics oracle): same math, f32 logits.

    Used when shapes don't tile for the kernel (H not a multiple of
    128); also the definition the tests hold the fused path to. Same
    padded-row semantics as the kernels: target -1 marks a row that is
    dropped from the mean (and so contributes zero gradient) — without
    the mask, a fallback would silently change the loss exactly when
    shapes stop tiling."""
    logits = jnp.dot(hidden, kernel,
                     preferred_element_type=jnp.float32)
    logits = logits + bias.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tl = jnp.take_along_axis(logits, jnp.maximum(targets, 0)[:, None],
                             axis=-1)[:, 0]
    valid = (targets >= 0).astype(jnp.float32)
    return jnp.sum((lse - tl) * valid) / jnp.maximum(jnp.sum(valid), 1.0)


def _fwd_common(x_ref, w_ref, b_ref, t_ref, logits_ref, lse_ref, tl_ref,
                m_ref, s_ref, tacc_ref, *, block_v):
    """Shared forward body, grid (n-blocks, v-blocks), v innermost: the
    x block stays resident while W blocks stream; online-logsumexp
    state lives in VMEM scratch and the outputs are written on the last
    v step. `logits_ref=None` (the recompute path) skips the bf16
    residual store — everything else is identical by construction."""
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        s_ref[:] = jnp.zeros_like(s_ref)
        tacc_ref[:] = jnp.zeros_like(tacc_ref)

    acc = jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc = acc + b_ref[:].astype(jnp.float32)         # [bn, bv]
    if logits_ref is not None:
        logits_ref[:] = acc.astype(logits_ref.dtype)

    m = m_ref[:]                                     # [bn, 1]
    m_new = jnp.maximum(m, jnp.max(acc, axis=1, keepdims=True))
    s_ref[:] = (s_ref[:] * jnp.exp(m - m_new)
                + jnp.sum(jnp.exp(acc - m_new), axis=1, keepdims=True))
    m_ref[:] = m_new

    # the target column hits exactly one (n, v) cell per row; padded
    # rows carry target -1 and never match
    col = t_ref[:] - j * block_v                     # [bn, 1]
    hit = lax.broadcasted_iota(jnp.int32, acc.shape, 1) == col
    tacc_ref[:] += jnp.sum(jnp.where(hit, acc, 0.0), axis=1,
                           keepdims=True)

    @pl.when(j == nv - 1)
    def _():
        lse_ref[:] = m_ref[:] + jnp.log(s_ref[:])
        tl_ref[:] = tacc_ref[:]




def _bwd_kernel(scale_ref, logits_ref, lse_ref, t_ref, d_ref, db_ref,
                dbacc_ref, *, block_v):
    """Grid (v-blocks, n-blocks), n innermost: d = (p - onehot) * g/N
    in bf16 (aliased over the logits residual), with the bias gradient
    accumulated across the n sweep."""
    j = pl.program_id(0)
    i = pl.program_id(1)
    nn = pl.num_programs(1)

    @pl.when(i == 0)
    def _():
        dbacc_ref[:] = jnp.zeros_like(dbacc_ref)

    p = jnp.exp(logits_ref[:].astype(jnp.float32) - lse_ref[:])
    col = t_ref[:] - j * block_v
    hit = lax.broadcasted_iota(jnp.int32, p.shape, 1) == col
    valid = (t_ref[:] >= 0).astype(jnp.float32)      # [bn, 1] pad mask
    d = (p - hit.astype(jnp.float32)) * (scale_ref[0, 0] * valid)
    d_ref[:] = d.astype(d_ref.dtype)
    dbacc_ref[:] += jnp.sum(d, axis=0, keepdims=True)

    @pl.when(i == nn - 1)
    def _():
        db_ref[:] = dbacc_ref[:]


def _fwd_kernel_nores(x_ref, w_ref, b_ref, t_ref, lse_ref, tl_ref,
                      m_ref, s_ref, tacc_ref, *, block_v):
    """`_fwd_common` without the logits residual output: the recompute
    backward rebuilds every logits block from x.W, so the forward only
    produces the per-row lse and target logit."""
    _fwd_common(x_ref, w_ref, b_ref, t_ref, None, lse_ref, tl_ref,
                m_ref, s_ref, tacc_ref, block_v=block_v)


def _recompute_d(x_ref, w_ref, b_ref, t_ref, lse_ref, scale_ref, j,
                 block_v):
    """Shared by both recompute backward kernels: rebuild this block's
    logits from x.W + b and form d = (softmax - onehot) * g/N in f32
    registers — the [N, V] d matrix never exists outside VMEM."""
    acc = jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc = acc + b_ref[:].astype(jnp.float32)
    p = jnp.exp(acc - lse_ref[:])
    col = t_ref[:] - j * block_v
    hit = lax.broadcasted_iota(jnp.int32, p.shape, 1) == col
    valid = (t_ref[:] >= 0).astype(jnp.float32)      # [bn, 1] pad mask
    return (p - hit.astype(jnp.float32)) * (scale_ref[0, 0] * valid)


def _dw_kernel(scale_ref, x_ref, w_ref, b_ref, t_ref, lse_ref,
               dw_ref, db_ref, dwacc_ref, dbacc_ref, *, block_v):
    """Grid (v-blocks, n-blocks), n innermost: the W block stays
    resident while x blocks stream; dW[:, j] = sum_i x_i^T d_ij and the
    bias gradient accumulate in VMEM scratch across the n sweep."""
    j = pl.program_id(0)
    i = pl.program_id(1)
    nn = pl.num_programs(1)

    @pl.when(i == 0)
    def _():
        dwacc_ref[:] = jnp.zeros_like(dwacc_ref)
        dbacc_ref[:] = jnp.zeros_like(dbacc_ref)

    d = _recompute_d(x_ref, w_ref, b_ref, t_ref, lse_ref, scale_ref, j,
                     block_v)
    dwacc_ref[:] += jax.lax.dot_general(
        x_ref[:], d.astype(x_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dbacc_ref[:] += jnp.sum(d, axis=0, keepdims=True)

    @pl.when(i == nn - 1)
    def _():
        dw_ref[:] = dwacc_ref[:].astype(dw_ref.dtype)
        db_ref[:] = dbacc_ref[:]


def _dx_kernel(scale_ref, x_ref, w_ref, b_ref, t_ref, lse_ref, dx_ref,
               dxacc_ref, *, block_v):
    """Grid (n-blocks, v-blocks), v innermost: the x block stays
    resident while W blocks stream; dx_i = sum_j d_ij W_j^T accumulates
    in VMEM scratch across the v sweep."""
    i = pl.program_id(0)
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        dxacc_ref[:] = jnp.zeros_like(dxacc_ref)

    d = _recompute_d(x_ref, w_ref, b_ref, t_ref, lse_ref, scale_ref, j,
                     block_v)
    dxacc_ref[:] += jax.lax.dot_general(
        d.astype(w_ref.dtype), w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == nv - 1)
    def _():
        dx_ref[:] = dxacc_ref[:].astype(dx_ref.dtype)


# -- reusable pallas_call wrappers ------------------------------------------
# The sharded head (parallel/vocab_ce.py) drives the SAME kernels on each
# vocab shard, so the pallas_call plumbing is factored out of the
# custom_vjp bodies. Sharding needs no kernel change because the target
# column input `t` carries per-row sentinels: -1 marks a padded row (no
# hit, zero gradient via the in-kernel `t >= 0` mask) and any value >=
# v_pad marks a VALID row whose target lives in another vocab shard (no
# hit — its gradient is the pure-softmax term — but `t >= 0` keeps it in
# the loss/gradient scale).


def _fwd_pallas(x, w, b, t, bn, bv, interpret, residual):
    """Forward grid pass: (logits|None, lse, tl) for padded blocks.

    `residual=True` additionally writes the bf16 logits residual the
    residual-scheme backward consumes; otherwise only the per-row
    online-logsumexp outputs exist.
    """
    n_pad, h = x.shape
    v_pad = w.shape[1]
    nn, nv = n_pad // bn, v_pad // bv
    kernel = _fwd_common if residual else _fwd_kernel_nores
    out_specs = [
        pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
    ]
    if residual:
        out_specs = [pl.BlockSpec((bn, bv), lambda i, j: (i, j))] \
            + out_specs
        out_shape = [jax.ShapeDtypeStruct((n_pad, v_pad), jnp.bfloat16)] \
            + out_shape
    out = pl.pallas_call(
        functools.partial(kernel, block_v=bv),
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i, j: (i, 0)),
            pl.BlockSpec((h, bv), lambda i, j: (0, j)),
            pl.BlockSpec((1, bv), lambda i, j: (0, j)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bn, 1), jnp.float32),   # running max
            pltpu.VMEM((bn, 1), jnp.float32),   # running sum-exp
            pltpu.VMEM((bn, 1), jnp.float32),   # target-logit gather
        ],
        interpret=interpret,
    )(x, w, b, t)
    if residual:
        logits, lse, tl = out
    else:
        logits, (lse, tl) = None, out
    return logits, lse, tl


def _residual_d_pallas(scale, logits, lse, t, bn, bv, interpret):
    """(d, db) of the residual scheme: d = (softmax - onehot) * scale
    rebuilt blockwise from the bf16 logits residual (aliased in place)."""
    n_pad, v_pad = logits.shape
    nn, nv = n_pad // bn, v_pad // bv
    return pl.pallas_call(
        functools.partial(_bwd_kernel, block_v=bv),
        grid=(nv, nn),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((bn, bv), lambda j, i: (i, j)),
            pl.BlockSpec((bn, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda j, i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, bv), lambda j, i: (i, j)),
            pl.BlockSpec((1, bv), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, v_pad), jnp.bfloat16),
            jax.ShapeDtypeStruct((1, v_pad), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, bv), jnp.float32)],
        # d overwrites the logits residual in place: same shape/dtype,
        # consumed nowhere else
        input_output_aliases={1: 0},
        interpret=interpret,
    )(scale, logits, lse, t)


def _dw_pallas(scale, x, w, b, t, lse, bn, bv, interpret):
    """(dw, db) of the recompute scheme (fused logits rebuild)."""
    n_pad, h = x.shape
    v_pad = w.shape[1]
    nn, nv = n_pad // bn, v_pad // bv
    return pl.pallas_call(
        functools.partial(_dw_kernel, block_v=bv),
        grid=(nv, nn),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((bn, h), lambda j, i: (i, 0)),
            pl.BlockSpec((h, bv), lambda j, i: (0, j)),
            pl.BlockSpec((1, bv), lambda j, i: (0, j)),
            pl.BlockSpec((bn, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda j, i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((h, bv), lambda j, i: (0, j)),
            pl.BlockSpec((1, bv), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h, v_pad), w.dtype),
            jax.ShapeDtypeStruct((1, v_pad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((h, bv), jnp.float32),   # dW accumulator
            pltpu.VMEM((1, bv), jnp.float32),   # db accumulator
        ],
        interpret=interpret,
    )(scale, x, w, b, t, lse)


def _dx_pallas(scale, x, w, b, t, lse, bn, bv, interpret):
    """dx of the recompute scheme (fused logits rebuild)."""
    n_pad, h = x.shape
    v_pad = w.shape[1]
    nn, nv = n_pad // bn, v_pad // bv
    return pl.pallas_call(
        functools.partial(_dx_kernel, block_v=bv),
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((bn, h), lambda i, j: (i, 0)),
            pl.BlockSpec((h, bv), lambda i, j: (0, j)),
            pl.BlockSpec((1, bv), lambda i, j: (0, j)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, h), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, h), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((bn, h), jnp.float32),   # dx accumulator
        ],
        interpret=interpret,
    )(scale, x, w, b, t, lse)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_ce_recompute(x, w, b, t, bn, bv, interpret):
    loss, _ = _fcr_fwd(x, w, b, t, bn, bv, interpret)
    return loss


def _fcr_fwd(x, w, b, t, bn, bv, interpret):
    _, lse, tl = _fwd_pallas(x, w, b, t, bn, bv, interpret,
                             residual=False)
    valid = (t >= 0).astype(jnp.float32)             # [n_pad, 1]
    num_valid = jnp.maximum(jnp.sum(valid), 1.0)
    loss = jnp.sum((lse - tl) * valid) / num_valid
    return loss, (x, w, b, lse, t, num_valid)


def _fcr_bwd(bn, bv, interpret, res, g):
    x, w, b, lse, t, num_valid = res
    scale = (g / num_valid).astype(jnp.float32)[None, None]
    dw, db = _dw_pallas(scale, x, w, b, t, lse, bn, bv, interpret)
    dx = _dx_pallas(scale, x, w, b, t, lse, bn, bv, interpret)
    return (dx, dw, db.astype(jnp.float32),
            np.zeros(t.shape, jax.dtypes.float0))


_fused_ce_recompute.defvjp(_fcr_fwd, _fcr_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_ce_padded(x, w, b, t, bn, bv, interpret):
    loss, _ = _fce_fwd(x, w, b, t, bn, bv, interpret)
    return loss


def _fce_fwd(x, w, b, t, bn, bv, interpret):
    logits, lse, tl = _fwd_pallas(x, w, b, t, bn, bv, interpret,
                                  residual=True)
    valid = (t >= 0).astype(jnp.float32)             # [n_pad, 1]
    num_valid = jnp.maximum(jnp.sum(valid), 1.0)
    loss = jnp.sum((lse - tl) * valid) / num_valid
    return loss, (x, w, logits, lse, t, num_valid)


def _fce_bwd(bn, bv, interpret, res, g):
    x, w, logits, lse, t, num_valid = res
    scale = (g / num_valid).astype(jnp.float32)[None, None]
    d, db = _residual_d_pallas(scale, logits, lse, t, bn, bv, interpret)

    # dW = x^T d and dx = d W^T: plain bf16 matmuls, f32 accumulation;
    # padded rows/cols of x and d are zero so the pads contribute 0
    dw = jax.lax.dot_general(x, d, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dx = jax.lax.dot_general(d, w, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    return (dx.astype(x.dtype), dw.astype(w.dtype),
            db.astype(jnp.float32),
            np.zeros(t.shape, jax.dtypes.float0))


_fused_ce_padded.defvjp(_fce_fwd, _fce_bwd)


def fused_cross_entropy(hidden, kernel, bias, targets,
                        interpret: bool | None = None,
                        residual: bool = True):
    """Mean softmax cross-entropy of `hidden @ kernel + bias` against
    integer `targets`, differentiable in (hidden, kernel, bias).

    hidden: [N, H] (any float dtype; compute runs bf16 with f32
    accumulation), kernel: [H, V], bias: [V], targets: [N] int. Shapes
    whose H is not a multiple of 128 fall back to the plain-XLA
    reference path (`reference_cross_entropy`).

    Two backward schemes (measured head-to-head on v5e at GPT-2-small
    b=12: residual 113.2k tok/s vs recompute 105.5k — the residual
    default wins where the [N, V] bf16 residual fits):

    - `residual=True` (default): bf16 logits residual written forward,
      d rebuilt from it and aliased over the same buffer backward,
      dW/dx as two plain XLA bf16 matmuls.
    - `residual=False`: the backward RECOMPUTES each logits block from
      x.W inside fused dW and dx kernels (Liger-style), so no [N, V]
      array of any dtype ever exists — the forward saves only the
      [N, 1] row logsumexp. Two extra bf16 logits passes plus x/W
      re-streaming cost ~7% at small-b12 scale, but this is the only
      path whose HBM footprint is independent of N*V — use it when
      the residual itself would not fit (very long context x large
      vocab).
    """
    n, h = hidden.shape
    v = kernel.shape[1]
    vmem = _fwd_vmem_bytes if residual else _recompute_vmem_bytes
    blocks = _pick_blocks(n, h, v, vmem) if h % 128 == 0 else None
    if blocks is None:
        return reference_cross_entropy(hidden, kernel, bias, targets)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bn, bv = blocks
    n_pad, v_pad = _round_up(n, bn), _round_up(v, bv)
    # ordinary jnp pads/casts: their transposes (slice, cast-back) give
    # callers unpadded gradients automatically
    x = jnp.pad(hidden.astype(jnp.bfloat16), ((0, n_pad - n), (0, 0)))
    w = jnp.pad(kernel.astype(jnp.bfloat16), ((0, 0), (0, v_pad - v)))
    b = jnp.pad(bias.astype(jnp.float32), (0, v_pad - v),
                constant_values=_PAD_BIAS)[None, :]
    t = jnp.pad(lax.stop_gradient(targets).astype(jnp.int32),
                (0, n_pad - n), constant_values=-1)[:, None]
    if residual:
        return _fused_ce_padded(x, w, b, t, bn, bv, interpret)
    return _fused_ce_recompute(x, w, b, t, bn, bv, interpret)
