"""Flash attention as a Pallas TPU kernel.

The hot op of the long-context path (`parallel/sequence.py`): plain
attention materializes [T, T] scores in HBM; this kernel streams K/V
blocks through VMEM with online-softmax accumulation so HBM traffic is
O(T) per query block (FlashAttention, Dao et al. 2022 — on TPU the
win is HBM bandwidth, the usual bottleneck, not SRAM reuse).

Grid: one program per (batch*head, query-block). Each program keeps its
Q block, the running max/denominator and the output accumulator in
VMEM/registers and loops over K/V blocks with `lax.fori_loop`.

`flash_attention` falls back to the plain jnp implementation when
shapes don't tile (T % block != 0) or on backends without Mosaic
(interpret mode covers CPU tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, scale,
            causal, block_q, block_k):
    """Grid (B*H, nq, nk), nk innermost: the VMEM scratch (accumulator +
    running max/denominator) carries the online-softmax state across the
    sequential K-block steps; K/V blocks stream through VMEM one at a
    time, so resident VMEM stays O(block) regardless of T."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(jk == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: K blocks entirely above the diagonal contribute nothing
    diag_ok = (jk * block_k <= (iq + 1) * block_q - 1) if causal else True

    @pl.when(diag_ok)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale  # [block_q, D]
        k_blk = k_ref[0].astype(jnp.float32)      # [block_k, D]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [block_q, block_k]
        if causal:
            q_pos = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = jk * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m = m_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, axis=-1)
        m_ref[:, 0] = m_new
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jk == nk - 1)
    def _():
        l = l_ref[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)


def _plain_attention(q, k, v, causal, scale):
    # single reference implementation, shared with the sequence-parallel
    # mixers (sequence.py has no pallas dependency; this module does)
    from ..parallel.sequence import _local_attention

    return _local_attention(q, k, v, causal=causal, scale=scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Attention over [B, T, H, D] without materializing [T, T] scores.

    Tiling requires T % block == 0 (and causal additionally
    block_q % block_k == 0); other shapes use the plain implementation.
    `interpret=None` auto-selects interpreter mode off-TPU so tests run
    on the CPU mesh.

    Backward pass: recomputation through the PLAIN attention VJP — the
    forward saves only q/k/v (flash's O(T) memory win), but the backward
    currently materializes [T, T] scores per head like standard
    attention. A fused flash backward kernel is future work.
    """
    return _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                           interpret)


def _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret):
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if (t % block_q or t % block_k
            or (causal and block_q % block_k)):
        return _plain_attention(q, k, v, causal, scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # [B, T, H, D] -> [B*H, T, D]: one grid row per (batch, head)
    def bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    kernel = functools.partial(
        _kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q, t // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda i, j, kk: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denominator
        ],
        interpret=interpret,
    )(bh(q), bh(k), bh(v))
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                          interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    _, vjp = jax.vjp(lambda q, k, v: _plain_attention(q, k, v, causal,
                                                      scale), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
