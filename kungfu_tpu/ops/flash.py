"""Flash attention as a Pallas TPU kernel.

The hot op of the long-context path (`parallel/sequence.py`): plain
attention materializes [T, T] scores in HBM; this kernel streams K/V
blocks through VMEM with online-softmax accumulation so HBM traffic is
O(T) per query block (FlashAttention, Dao et al. 2022 — on TPU the
win is HBM bandwidth, the usual bottleneck, not SRAM reuse).

Two execution schemes per kernel (fwd / dq / dkv), selected by a
VMEM-budget estimate in the style of `ops/fused_ce.py:_pick_blocks`
(`flash_plan` shows the decision for a shape):

- **resident** (preferred whenever the estimate fits `_VMEM_BUDGET`):
  grid (B*H, outer-block); the streamed side (K/V for fwd/dq, Q/dO for
  dkv) is held in VMEM at FULL length per head and the kernel loops
  over its blocks with a `lax.fori_loop` whose bounds come from
  `_k_span`/`_q_span` — for causal and windowed attention the trip
  count genuinely shrinks per program (causal visits the lower
  triangle only, ~half the blocks; windows visit O(window) blocks),
  and no fully-masked block is ever visited, in ALL of fwd, dq and
  dkv. As a bonus the resident side is DMA'd once per head instead of
  once per outer block (the streaming grid re-fetches every K/V block
  nq times).
- **stream** (fallback past the VMEM budget — long T, big D): the
  round-5 grid (B*H, outer, inner) with VMEM-scratch-carried online
  state. Causal masking skips compute via `pl.when`; sliding windows
  narrow the inner grid dim itself (`_window_span`, affine
  front-padded index maps).

Auto block sizes are budget-driven too: the largest measured-fastest
power-of-two tile that keeps the worst kernel's VMEM estimate under
budget (big head dims shrink blocks instead of failing to compile).

Backward overhead trims (round 6): the delta precompute
(`rowsum(dO * O)`, FlashAttention-2 eq. 4) is folded into the dq
kernel's first pass — dq already streams dO, so the separate XLA
reduction and its extra full read of dO/O are gone; dq emits the
per-row delta for the dkv kernel to consume. Residuals stay at the
input dtype end to end (bf16 in, bf16 residuals; only the [B*H, T]
lse/delta row vectors are f32).

`flash_attention` falls back to the plain jnp implementation when
shapes don't tile (T % block != 0) or on backends without Mosaic
(interpret mode covers CPU tests).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)

# Mosaic's scoped-vmem stack limit is 16 MB; 15 MB leaves scheduling
# headroom (same calibration rationale as ops/fused_ce.py). The
# estimates below are tuned so the round-5 measured-fastest config
# (1024x1024 blocks at d=64) still fits — the budget bites only where
# the real limit would (large T residency, large head dims).
_VMEM_BUDGET = 15 * 1024 * 1024

# test/bench escape hatch: force "stream" or "resident" regardless of
# the budget decision (unset = auto). Read at trace time so tests can
# monkeypatch the module attribute.
_FORCE_SCHEME = os.environ.get("KUNGFU_FLASH_SCHEME") or None


def _scores(q_blk, k_blk, iq, jk, *, scale, causal, block_q, block_k,
            window=None, transpose=False):
    """Scaled (and causal/window-masked) score block — shared by the
    forward and both backward kernels so the masking and scaling
    semantics cannot drift apart.

    `transpose=False`: [block_q, block_k] (q on sublanes) — the
    forward and dq-kernel layout (dq caches the per-q lse/delta
    columns in VMEM scratch once per q-block). `transpose=True`:
    [block_k, block_q] (q on LANES) — the dkv kernel works in this
    transposed score space so the compactly-stored lane-major
    lse/delta rows (see `_flash_bwd_impl`) broadcast against scores
    with no lane<->sublane relayout, and its two accumulations become
    Mosaic-native NN contractions (the untransposed dkv pays two TN
    forms). Measured on v5e at T=16k: this split is the fastest of
    the four layout/orientation combinations tried (see git history
    of this file), 7% faster end-to-end fwd+bwd than the round-3
    [B*H, T, 128] lane-broadcast scheme it replaces.

    `window` (sliding-window attention, causal only): position q
    attends to keys [q - window, q]. Self is always visible, so no row
    is ever fully masked.
    """
    if transpose:
        shape = (block_k, block_q)
        q_dim, k_dim = 1, 0
        s = jax.lax.dot_general(
            k_blk.astype(jnp.float32),
            q_blk.astype(jnp.float32) * scale,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        shape = (block_q, block_k)
        q_dim, k_dim = 0, 1
        s = jax.lax.dot_general(
            q_blk.astype(jnp.float32) * scale,
            k_blk.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    if causal or window is not None:
        q_pos = iq * block_q + lax.broadcasted_iota(
            jnp.int32, shape, q_dim)
        k_pos = jk * block_k + lax.broadcasted_iota(
            jnp.int32, shape, k_dim)
        keep = q_pos >= k_pos
        if window is not None:
            keep &= q_pos - k_pos <= window
        s = jnp.where(keep, s, NEG_INF)
    return s


def _fwd_step(q_blk, k_blk, v_blk, iq, jk, acc, m, l, *, scale, causal,
              block_q, block_k, window=None):
    """One K/V block's online-softmax update — the SINGLE definition of
    the forward recurrence, shared by the resident kernel (fori carry)
    and the streaming kernel (VMEM-scratch state) so the two schemes
    cannot drift numerically (the `_scores` discipline, applied to the
    whole block update). State shapes: acc [bq, d] f32, m/l [bq] f32.
    Returns the updated (acc, m, l)."""
    s = _scores(q_blk, k_blk, iq, jk, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k, window=window)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[:, None])
    l = l * alpha + jnp.sum(p, axis=-1)
    acc = acc * alpha[:, None] + jax.lax.dot_general(
        p, v_blk.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return acc, m_new, l


def _fwd_finish(acc, m, l, dtype, save_lse):
    """(o_block, lse_row | None) from the final online-softmax state —
    l == 0 (a fully-masked row, only reachable on the streaming grid's
    padded steps) divides by 1 instead."""
    l = jnp.where(l == 0.0, 1.0, l)
    o = (acc / l[:, None]).astype(dtype)
    return o, ((m + jnp.log(l)) if save_lse else None)


def _dq_step(q_blk, k_blk, v_blk, do, lse_col, delta_col, iq, jk, *,
             scale, causal, block_q, block_k, window=None):
    """One K/V block's dq contribution (FlashAttention-2: p rebuilt
    from lse; ds = p * (dp - delta); returns scale * ds @ k) — shared
    by both backward-dq schemes."""
    do = do.astype(jnp.float32)
    s = _scores(q_blk, k_blk, iq, jk, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k, window=window)
    p = jnp.exp(s - lse_col)
    dp = jax.lax.dot_general(
        do, v_blk.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta_col)
    return scale * jax.lax.dot_general(
        ds, k_blk.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _dkv_step(q_blk, k_blk, v_blk, do, lse_row, delta_row, iq, jk, *,
              scale, causal, block_q, block_k, window=None):
    """One Q/dO block's (dk, dv) contribution in TRANSPOSED score
    space (q on lanes — see `_scores`): dv = pT @ do,
    dk = scale * dsT @ q — shared by both backward-dkv schemes."""
    do = do.astype(jnp.float32)
    s_t = _scores(q_blk, k_blk, iq, jk, scale=scale, causal=causal,
                  block_q=block_q, block_k=block_k, window=window,
                  transpose=True)                     # [bk, bq]
    p_t = jnp.exp(s_t - lse_row)
    dv = jax.lax.dot_general(
        p_t, do, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # p^T @ do
    dp_t = jax.lax.dot_general(
        v_blk.astype(jnp.float32), do, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # (do @ v^T)^T
    ds_t = p_t * (dp_t - delta_row)
    dk = scale * jax.lax.dot_general(
        ds_t, q_blk.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # ds^T @ q
    return dk, dv


def _diag_ok(iq, jk, causal, block_q, block_k, window=None):
    """False for blocks with no visible entries: causal K blocks
    entirely above the diagonal, and (with a sliding window) K blocks
    entirely below the window — those are SKIPPED, which is what makes
    windowed attention O(T * window) compute instead of O(T^2)."""
    ok = (jk * block_k <= (iq + 1) * block_q - 1) if causal else True
    if window is not None:
        # newest key of this block still within the OLDEST query's reach
        win_ok = jk * block_k + block_k - 1 >= iq * block_q - window
        ok = win_ok if ok is True else jnp.logical_and(ok, win_ok)
    return ok


def _span_step(iq, kk, *, span, causal, block_q, block_k, window):
    """Streaming-scheme inner-step gate, shared by `_kernel` and
    `_bwd_dq_kernel` (the single definition of which narrowed steps
    are real, so forward and dq cannot diverge on the visible set):
    recovers the real k-block index from the window-relative grid
    index over the front-padded K/V — affine, `jk = iq*m + kk -
    (span - m)`; a max() in the index map instead was measured to
    defeat Mosaic's DMA prefetch pipelining (~28% slower) — and
    returns (jk, ok) where ok is False for steps with no visible
    entries (above the causal diagonal, past the window, or in the
    jk < 0 pad)."""
    if span is None:
        jk = kk
    else:
        m_ratio = block_q // block_k
        jk = iq * m_ratio + kk - (span - m_ratio)
    ok = _diag_ok(iq, jk, causal, block_q, block_k, window)
    if span is not None:
        ok = jnp.logical_and(jk >= 0, ok)
    return jk, ok


def _window_span(window, block_q, block_k, n_blocks):
    """K blocks a q-block can see under a causal sliding window, in
    k-block units, for block_q = m * block_k (the causal tiling
    invariant): first visible k-block of q-block i is
    i*m - ceil(window/block_k) and the last is i*m + m - 1, both
    AFFINE in i, so span = m + ceil(window/block_k) and the padded
    index map stays affine (see _flash_fwd_impl). m > 1 trades masked
    score area inside the band for fewer per-q-block prologues;
    measured at T=16k/window=512 the masked area wins (m=2 forward
    1.445 ms vs m=1's 0.969) so auto never picks m > 1 — the
    generality exists for window/block mixes where the trade flips.
    None = no narrowing (window absent, or it would not shrink the
    grid)."""
    if window is None:
        return None
    m = block_q // block_k
    span = m + (window + block_k - 1) // block_k
    return span if span < n_blocks else None


# ---------------------------------------------------------------------------
# block-skip loop bounds (resident scheme)
#
# Shared by the resident kernels AND the structural trip-count tests
# (`tests/test_flash_skip.py`): the fori_loop trip count of every
# program IS `hi - lo`, so pinning these functions pins the work-skip
# behaviour of all five loop nests (fwd/dq over k-blocks, dkv over
# q-blocks, causal and windowed).
# ---------------------------------------------------------------------------


def _k_span(iq, nk, *, causal, window, block_q, block_k):
    """Half-open range [lo, hi) of k-blocks with >= 1 visible entry for
    q-block `iq` — the fwd/dq resident loop bounds. Works on python
    ints (tests, planning) and traced values (inside kernels) alike.
    Causal: hi stops at the diagonal block (~halves the total visited
    blocks); a sliding window additionally lifts lo to the oldest
    in-window block, making the visit count O(window / block_k)."""
    if not causal:
        return 0, nk
    hi = jnp.minimum(((iq + 1) * block_q - 1) // block_k + 1, nk)
    if window is None:
        return 0, hi
    lo = jnp.maximum((iq * block_q - window) // block_k, 0)
    return lo, hi


def _q_span(jk, nq, *, causal, window, block_q, block_k):
    """Half-open range [lo, hi) of q-blocks that can see k-block `jk` —
    the dkv resident loop bounds (mirror image of `_k_span`). Causal:
    lo starts at the diagonal block; a window caps hi at the newest
    q-block still within `window` of this block's NEWEST key
    (jk*block_k + block_k - 1) — the newest key reaches furthest, so
    it defines the last visible q-block."""
    if not causal:
        return 0, nq
    lo = (jk * block_k) // block_q
    if window is None:
        return lo, nq
    hi = jnp.minimum((jk * block_k + block_k - 1 + window) // block_q + 1,
                     nq)
    return lo, hi


# ---------------------------------------------------------------------------
# VMEM-budget estimates (style of ops/fused_ce.py:_pick_blocks)
#
# Per-kernel resident-VMEM models: double-buffered pipeline blocks +
# f32 accumulator state + the [bq, bk] f32 score/probability
# temporaries (2 for the forward's s/p, 3 for the backwards' s/p +
# dp/ds). `t` terms are the full-length arrays the resident scheme
# holds per head; the budget is what flips a shape back to streaming.
# ---------------------------------------------------------------------------


def _fwd_stream_vmem(bq, bk, d, isz):
    inputs = 2 * (bq * d * isz + 2 * bk * d * isz)
    outputs = 2 * (bq * d * isz + bq * 4)
    scratch = bq * d * 4 + 2 * bq * 4
    return inputs + outputs + scratch + 2 * bq * bk * 4


def _dq_stream_vmem(bq, bk, d, isz):
    inputs = 2 * (3 * bq * d * isz + 2 * bk * d * isz + 2 * bq * 4)
    outputs = 2 * (bq * d * isz + bq * 4)
    scratch = bq * d * 4 + 2 * bq * 4
    return inputs + outputs + scratch + 3 * bq * bk * 4


def _dkv_stream_vmem(bq, bk, d, isz, t):
    inputs = 2 * (2 * bk * d * isz + 2 * bq * d * isz + 2 * t * 4)
    outputs = 2 * (2 * bk * d * isz)
    scratch = 2 * bk * d * 4
    return inputs + outputs + scratch + 3 * bq * bk * 4


def _fwd_res_vmem(bq, bk, d, isz, t):
    inputs = 2 * (bq * d * isz + 2 * t * d * isz)
    outputs = 2 * (bq * d * isz + bq * 4)
    carry = bq * d * 4 + 2 * bq * 4
    return inputs + outputs + carry + 2 * bq * bk * 4


def _dq_res_vmem(bq, bk, d, isz, t):
    inputs = 2 * (3 * bq * d * isz + 2 * t * d * isz + bq * 4)
    outputs = 2 * (bq * d * isz + bq * 4)
    carry = bq * d * 4
    return inputs + outputs + carry + 3 * bq * bk * 4


def _dkv_res_vmem(bq, bk, d, isz, t):
    inputs = 2 * (2 * bk * d * isz + 2 * t * d * isz + 2 * t * 4)
    outputs = 2 * (2 * bk * d * isz)
    carry = 2 * bk * d * 4
    return inputs + outputs + carry + 3 * bq * bk * 4


_RES_VMEM = {"fwd": _fwd_res_vmem, "dq": _dq_res_vmem,
             "dkv": _dkv_res_vmem}


def _choose_scheme(which, t, d, isz, bq, bk):
    """'resident' when the full-length-per-head scheme fits the VMEM
    budget (it both skips masked blocks AND fetches the streamed side
    once per head), else 'stream'. `_FORCE_SCHEME` overrides for
    benchmarking/tests."""
    if _FORCE_SCHEME in ("stream", "resident"):
        return _FORCE_SCHEME
    est = _RES_VMEM[which](bq, bk, d, isz, t)
    return "resident" if est <= _VMEM_BUDGET else "stream"


def _dim_semantics(n):
    """Pipelining hint: every grid dim is embarrassingly parallel
    except a streaming kernel's innermost (scratch-carried online
    state ⇒ sequential)."""
    sem = ("parallel",) * n if n == 2 else (
        ("parallel",) * (n - 1) + ("arbitrary",))
    return pltpu.TPUCompilerParams(dimension_semantics=sem)


# ---------------------------------------------------------------------------
# streaming kernels (grid (B*H, outer, inner), VMEM-scratch state)
# ---------------------------------------------------------------------------


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
            scale, causal, block_q, block_k, window=None, span=None):
    """Grid (B*H, nq, nk), nk innermost: the VMEM scratch (accumulator +
    running max/denominator) carries the online-softmax state across the
    sequential K-block steps; K/V blocks stream through VMEM one at a
    time, so resident VMEM stays O(block) regardless of T.

    `span` (sliding window): the grid's inner dim is narrowed to the
    `span` K blocks a q-block can actually see, and the K/V index maps
    shift by the q-block (see _flash_fwd_impl) — out-of-window K/V
    blocks never even stream their DMA. The kernel recovers the REAL
    k-block index from the window-relative grid index here."""
    iq = pl.program_id(1)
    kk = pl.program_id(2)            # window-relative when narrowed
    nk = pl.num_programs(2)
    jk, ok = _span_step(iq, kk, span=span, causal=causal,
                        block_q=block_q, block_k=block_k, window=window)

    @pl.when(kk == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(ok)
    def _():
        acc, m, l = _fwd_step(
            q_ref[0], k_ref[0], v_ref[0], iq, jk, acc_ref[:],
            m_ref[:, 0], l_ref[:, 0], scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, window=window)
        acc_ref[:] = acc
        m_ref[:, 0] = m
        l_ref[:, 0] = l

    @pl.when(kk == nk - 1)
    def _():
        o, lse = _fwd_finish(acc_ref[:], m_ref[:, 0], l_ref[:, 0],
                             o_ref.dtype, lse_ref is not None)
        o_ref[0] = o
        if lse_ref is not None:
            # per-row logsumexp of the scaled scores — the backward
            # kernels reconstruct p = exp(s - lse) from it instead of
            # saving [T, T]. Stored lane-major at true [B*H, T] size;
            # the one sublane->lane relayout here runs once per
            # q-block, not per inner step. Skipped entirely on the
            # no-grad forward (save_lse=False).
            lse_ref[0, 0] = lse.reshape(1, block_q)


def _kernel_nolse(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  scale, causal, block_q, block_k, window=None,
                  span=None):
    _kernel(q_ref, k_ref, v_ref, o_ref, None, acc_ref, m_ref, l_ref,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            window=window, span=span)


# ---------------------------------------------------------------------------
# resident kernels (grid (B*H, outer), dynamic-trip-count inner fori)
# ---------------------------------------------------------------------------


def _fwd_res_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                    causal, block_q, block_k, window=None, nk=None):
    """Grid (B*H, nq): K/V live in VMEM at full length per head (one
    O(T)-per-head DMA, vs the streaming grid re-fetching each K/V
    block nq times); the online-softmax state is a fori_loop carry (no
    cross-step scratch), and the loop runs ONLY over `_k_span`'s
    visible k-blocks — causal programs stop at the diagonal, windowed
    programs start at the window edge, so fully-masked blocks spend no
    compute (their bytes still ride the full-length fetch)."""
    iq = pl.program_id(1)
    q_blk = q_ref[0]
    d = q_blk.shape[-1]
    lo, hi = _k_span(iq, nk, causal=causal, window=window,
                     block_q=block_q, block_k=block_k)

    def body(jk, carry):
        off = pl.multiple_of(jk * block_k, block_k)
        return _fwd_step(
            q_blk, k_ref[0, pl.ds(off, block_k), :],
            v_ref[0, pl.ds(off, block_k), :], iq, jk, *carry,
            scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, window=window)

    acc, m, l = lax.fori_loop(lo, hi, body, (
        jnp.zeros((block_q, d), jnp.float32),
        jnp.full((block_q,), NEG_INF, jnp.float32),
        jnp.zeros((block_q,), jnp.float32)))
    o, lse = _fwd_finish(acc, m, l, o_ref.dtype, lse_ref is not None)
    o_ref[0] = o
    if lse_ref is not None:
        lse_ref[0, 0] = lse.reshape(1, block_q)


def _fwd_res_kernel_nolse(q_ref, k_ref, v_ref, o_ref, *, scale, causal,
                          block_q, block_k, window=None, nk=None):
    _fwd_res_kernel(q_ref, k_ref, v_ref, o_ref, None, scale=scale,
                    causal=causal, block_q=block_q, block_k=block_k,
                    window=window, nk=nk)


def _dq_res_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref,
                   delta_ref, *, scale, causal, block_q, block_k,
                   window=None, nk=None):
    """Grid (B*H, nq): dq for one Q block against VMEM-resident K/V,
    visiting only `_k_span`'s visible k-blocks. The delta precompute
    (rowsum(dO * O), FlashAttention-2 eq. 4) is folded into this
    kernel's prologue — dO and O are already here as q-blocks, so the
    standalone XLA reduction (and its extra HBM pass over both) is
    gone; the lane-major delta row is emitted for the dkv kernel."""
    iq = pl.program_id(1)
    q_blk = q_ref[0]
    d = q_blk.shape[-1]
    do = do_ref[0].astype(jnp.float32)
    delta_col = jnp.sum(do * o_ref[0].astype(jnp.float32), axis=-1,
                        keepdims=True)                    # [bq, 1]
    delta_ref[0, 0] = delta_col.reshape(1, block_q)
    lse_col = lse_ref[0, 0].reshape(block_q, 1)
    lo, hi = _k_span(iq, nk, causal=causal, window=window,
                     block_q=block_q, block_k=block_k)

    def body(jk, acc):
        off = pl.multiple_of(jk * block_k, block_k)
        return acc + _dq_step(
            q_blk, k_ref[0, pl.ds(off, block_k), :],
            v_ref[0, pl.ds(off, block_k), :], do, lse_col, delta_col,
            iq, jk, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, window=window)

    acc = lax.fori_loop(lo, hi, body,
                        jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = acc.astype(dq_ref.dtype)


def _dkv_res_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                    window=None, nq=None):
    """Grid (B*H, nk): dk/dv for one K/V block against VMEM-resident
    Q/dO, in TRANSPOSED score space (q on lanes — see `_scores`),
    visiting only `_q_span`'s visible q-blocks: causal programs start
    at the diagonal, windowed programs stop at the window edge.
    lse/delta arrive as the head's full lane-major row set, DMA'd once
    per head; the per-q-block row is a cheap non-tiled-dim select."""
    jk = pl.program_id(1)
    k_blk = k_ref[0]
    d = k_blk.shape[-1]
    lo, hi = _q_span(jk, nq, causal=causal, window=window,
                     block_q=block_q, block_k=block_k)

    def body(iq, carry):
        dk_acc, dv_acc = carry
        off = pl.multiple_of(iq * block_q, block_q)
        dk, dv = _dkv_step(
            q_ref[0, pl.ds(off, block_q), :], k_blk, v_ref[0],
            do_ref[0, pl.ds(off, block_q), :],
            lse_ref[0, iq, 0, :][None, :],    # [1, bq] lane rows
            delta_ref[0, iq, 0, :][None, :],
            iq, jk, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, window=window)
        return dk_acc + dk, dv_acc + dv

    dk_acc, dv_acc = lax.fori_loop(lo, hi, body, (
        jnp.zeros((block_k, d), jnp.float32),
        jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def _plain_attention(q, k, v, causal, scale, window=None):
    # single reference implementation, shared with the sequence-parallel
    # mixers (sequence.py has no pallas dependency; this module does)
    from ..parallel.sequence import _local_attention

    return _local_attention(q, k, v, causal=causal, scale=scale,
                            window=window)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    window: int | None = None,
) -> jnp.ndarray:
    """Attention over [B, T, H, D] without materializing [T, T] scores.

    Tiling requires T % block == 0 (and causal additionally
    block_q % block_k == 0); other shapes use the plain implementation.
    `block_q`/`block_k` default to auto: T <= 1024 runs as ONE block
    (any length — full-dim blocks always satisfy Mosaic's tiling rule;
    odd lengths verified on real v5e), longer T picks the largest of
    1024/512/256/128 dividing it (1024 fastest measured on v5e) that
    also keeps every kernel's VMEM estimate under `_VMEM_BUDGET`
    (large head dims shrink blocks instead of compile-OOMing), and
    longer non-dividing T takes the plain fallback. `interpret=None`
    auto-selects interpreter mode off-TPU so tests run on the CPU mesh.

    Each kernel then runs the VMEM-resident block-skipping scheme when
    it fits the budget, else the streaming grid — see the module
    docstring and `flash_plan` for the decision and the per-shape
    visited-block counts.

    Backward pass: fused flash backward kernels — the forward saves only
    (q, k, v, o, lse), dq/dk/dv are computed blockwise with the
    FlashAttention-2 recurrence (p re-materialized per block from the
    saved logsumexp), and the delta precompute rides inside the dq
    kernel, so both directions are O(T) in HBM with no standalone
    reduction pass. Non-tiling shapes fall back to the plain VJP.

    `window` (requires causal=True): sliding-window attention — position
    q attends to keys [q - window, q] (Mistral-style local attention).
    Out-of-window blocks stream no DMA and spend no FLOPs — O(T *
    window) compute AND data movement — via the resident loop bounds
    (`_k_span`/`_q_span`), or, on the streaming fallback, via the
    narrowed inner grid (`_window_span`; the streaming dkv narrows only
    at block_q == block_k and keeps compute-skip otherwise). Measured
    at T=16k, window=512 on v5e with the round-5 slope harness:
    training fwd+bwd 5.48x, forward 4.54x vs the full-causal
    auto-block baseline.
    """
    out, _ = _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                             interpret, save_lse=False, window=window)
    return out


def _tiles(t, causal, block_q, block_k, window=None, *, d=None,
           itemsize=4):
    """The (block_q, block_k) actually usable for length t, or None.

    `None` block sizes auto-select the largest power-of-two <= 1024
    that divides t. Round-5 v5e sweep (fwd+bwd, b*h=144, d=64):
    1024 beats 512 by 21-22% at t = 1024 / 2048 / 4096 (fewer
    per-q-block prologue/epilogues and bigger matmuls); 512 had
    previously beaten 128 by ~25%. With a sliding `window`, the cap is
    the largest power-of-two <= window instead: past-window score area
    inside a block is masked waste, and at t=16k/window=512 the 1024
    block measured 40% SLOWER (7.04 vs 5.02 ms) than 512. When the
    head dim `d` is known, auto blocks additionally shrink (bk first,
    then bq, powers of two, floor 128) until the WORST streaming
    kernel's VMEM estimate fits `_VMEM_BUDGET` — the fused_ce
    `_pick_blocks` discipline, so big-D shapes trade tile size for
    compilability instead of OOMing in Mosaic. Explicit sizes are
    respected as given (no budget shrink); mixing one explicit size
    with auto fills the other with the SAME value so the causal
    divisibility invariant can't silently demote the call to plain
    attention. Tiles below 128 starve the MXU, so auto only goes
    smaller when one block covers the whole (short) sequence;
    otherwise non-tiling lengths take the plain fallback as before.
    """
    auto = block_q is None and block_k is None
    if auto:
        cap = 1024
        if window is not None:
            cap = max(128, 1 << max(7, (window).bit_length() - 1))
            cap = min(cap, 1024)
        if t <= cap:
            block_q = block_k = t  # one block: any length tiles
        else:
            pick = next((b for b in (1024, 512, 256, 128)
                         if b <= cap and t % b == 0), None)
            if pick is None:
                return None
            block_q = block_k = pick
    elif block_q is None:
        block_q = block_k
    elif block_k is None:
        block_k = block_q
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if (t % block_q or t % block_k
            or (causal and block_q % block_k)):
        return None
    if auto and d is not None:
        # budget shrink — auto pow2 blocks only (halving a pow2 divisor
        # of t keeps dividing t and preserves bq % bk == 0)
        def _pow2(x):
            return x & (x - 1) == 0

        def _worst(bq, bk):
            return max(_fwd_stream_vmem(bq, bk, d, itemsize),
                       _dq_stream_vmem(bq, bk, d, itemsize),
                       _dkv_stream_vmem(bq, bk, d, itemsize, t))

        while _worst(block_q, block_k) > _VMEM_BUDGET:
            if block_k > 128 and _pow2(block_k):
                block_k //= 2
            elif block_q > 128 and _pow2(block_q):
                block_q //= 2
            else:
                # cannot shrink further (non-pow2 single-block tile, or
                # already at the 128 floor) and STILL over budget:
                # plain attention beats handing Mosaic an OOMing tile
                return None
    return block_q, block_k


def _narrowed_kv(causal, window, block_q, block_k, nk, kb, vb):
    """Streaming-scheme sliding-window narrowing, shared by the
    forward and dq paths (which MUST agree on which blocks stream):
    returns (span, kv index map, K/V inputs). With a window, the inner
    grid dim narrows to the `span` K blocks a q-block can see and the
    K/V index maps shift by the q-block — out-of-window K/V never
    streams (round 3 skipped only the COMPUTE via pl.when, leaving the
    full-causal DMA schedule, and measured 2.3x where FLOP
    proportionality allows ~8x). K/V are front-padded by span-m blocks
    (m = bq//bk, affine for any m — see `_window_span`) so the map
    stays AFFINE — a max() in the map was measured to defeat Mosaic's
    DMA prefetch pipelining (~28% slower; see `_kernel`)."""
    span = (_window_span(window, block_q, block_k, nk)
            if causal else None)
    if span is None:
        return None, (lambda i, j, kk: (i, kk, 0)), kb, vb
    m_ratio = block_q // block_k
    kv_pad = (span - m_ratio) * block_k
    return (span,
            lambda i, j, kk: (i, j * m_ratio + kk, 0),
            jnp.pad(kb, ((0, 0), (kv_pad, 0), (0, 0))),
            jnp.pad(vb, ((0, 0), (kv_pad, 0), (0, 0))))


def _bh(x):
    """[B, T, H, D] -> [B*H, T, D]: one grid row per (batch, head)."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unbh(x, b, h):
    bh_, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret,
                    save_lse, window=None):
    """Returns (out, lse) — lse is None on the plain-attention fallback
    or when `save_lse` is False (the no-grad forward skips the extra
    [B*H, T] output entirely: no HBM allocation, no writes)."""
    # validated HERE, not in the custom_vjp primal: under jax.grad the
    # primal body never runs (custom_vjp routes straight to _flash_fwd,
    # which also lands here), so a primal-only check would let autodiff
    # silently compute semantics the caller never asked for
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 0:
            raise ValueError(f"window must be >= 0, got {window}")
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    isz = jnp.dtype(q.dtype).itemsize
    tiles = _tiles(t, causal, block_q, block_k, window, d=d,
                   itemsize=isz)
    if tiles is None:
        return _plain_attention(q, k, v, causal, scale,
                                window=window), None
    block_q, block_k = tiles
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    nq, nk = t // block_q, t // block_k
    o_shape = jax.ShapeDtypeStruct((b * h, t, d), q.dtype)
    lse_shape = jax.ShapeDtypeStruct((b * h, nq, 1, block_q),
                                     jnp.float32)

    if _choose_scheme("fwd", t, d, isz, block_q, block_k) == "resident":
        kernel = functools.partial(
            _fwd_res_kernel if save_lse else _fwd_res_kernel_nolse,
            scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, window=window, nk=nk)
        o_spec = pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0))
        lse_spec = pl.BlockSpec((1, 1, 1, block_q),
                                lambda i, j: (i, j, 0, 0))
        result = pl.pallas_call(
            kernel,
            grid=(b * h, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            ],
            out_specs=[o_spec, lse_spec] if save_lse else o_spec,
            out_shape=[o_shape, lse_shape] if save_lse else o_shape,
            compiler_params=_dim_semantics(2),
            interpret=interpret,
        )(_bh(q), _bh(k), _bh(v))
    else:
        span, kv_j, kb_in, vb_in = _narrowed_kv(
            causal, window, block_q, block_k, nk, _bh(k), _bh(v))
        kernel = functools.partial(
            _kernel if save_lse else _kernel_nolse, scale=scale,
            causal=causal, block_q=block_q, block_k=block_k,
            window=window, span=span)
        o_spec = pl.BlockSpec((1, block_q, d),
                              lambda i, j, kk: (i, j, 0))
        lse_spec = pl.BlockSpec((1, 1, 1, block_q),
                                lambda i, j, kk: (i, j, 0, 0))
        result = pl.pallas_call(
            kernel,
            grid=(b * h, nq, span if span is not None else nk),
            in_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda i, j, kk: (i, j, 0)),
                pl.BlockSpec((1, block_k, d), kv_j),
                pl.BlockSpec((1, block_k, d), kv_j),
            ],
            out_specs=[o_spec, lse_spec] if save_lse else o_spec,
            out_shape=[o_shape, lse_shape] if save_lse else o_shape,
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),  # out accumulator
                pltpu.VMEM((block_q, 1), jnp.float32),  # running max
                pltpu.VMEM((block_q, 1), jnp.float32),  # running denom
            ],
            compiler_params=_dim_semantics(3),
            interpret=interpret,
        )(_bh(q), kb_in, vb_in)
    if not save_lse:
        return _unbh(result, b, h), None
    out, lse = result
    return _unbh(out, b, h), lse.reshape(b * h, t)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                   dq_ref, delta_out_ref, acc_ref, lse_col, delta_col,
                   *, scale, causal, block_q, block_k, window=None,
                   span=None):
    """Grid (B*H, nq, nk), nk innermost: accumulate dq for one Q block
    while K/V blocks stream by. p is rebuilt from the saved lse, never
    stored: ds = p * (dp - delta); dq += scale * ds @ k. The q-row lse
    arrives lane-major (compact [B*H, T] storage) and is relayouted to
    a column ONCE per q-block into VMEM scratch; delta is COMPUTED here
    in the kk == 0 prologue (rowsum(dO * O) — dO/O are this program's
    q-blocks already) and emitted lane-major for the dkv kernel, so no
    standalone XLA delta pass touches HBM. This kernel's blocks change
    only with (i, q-block), so the inner k-sweep reuses the cached
    columns; its matmuls stay in Mosaic-native NN/NT forms (a fully
    transposed-space dq variant turns ds @ k into a TN contraction and
    measured 36% slower end-to-end)."""
    iq = pl.program_id(1)
    kk = pl.program_id(2)            # window-relative when narrowed
    nk = pl.num_programs(2)
    jk, ok = _span_step(iq, kk, span=span, causal=causal,
                        block_q=block_q, block_k=block_k, window=window)

    @pl.when(kk == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        lse_col[:] = lse_ref[0, 0].reshape(block_q, 1)
        delta_col[:] = jnp.sum(
            do_ref[0].astype(jnp.float32)
            * o_ref[0].astype(jnp.float32), axis=-1, keepdims=True)
        delta_out_ref[0, 0] = delta_col[:].reshape(1, block_q)

    @pl.when(ok)
    def _():
        acc_ref[:] += _dq_step(
            q_ref[0], k_ref[0], v_ref[0],
            do_ref[0].astype(jnp.float32), lse_col[:], delta_col[:],
            iq, jk, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, window=window)

    @pl.when(kk == nk - 1)
    def _():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, window=None, span=None,
                    nq_total=None):
    """Grid (B*H, nk, nq), nq innermost: accumulate dk/dv for one K/V
    block while Q/dO blocks stream by, in TRANSPOSED score space (q on
    lanes — see _scores): dv += pT @ do; dk += scale * dsT @ q.

    lse/delta arrive as the head's FULL row set ([1, nq, 1, block_q],
    index_map constant over both inner grid dims), so their DMA runs
    once per head instead of once per inner step — per-step 2 KB
    fetches left ~30% on the table at T=16k — and the per-q-block row
    is a cheap non-tiled-dim select. In transposed space the row is
    already a lane vector (no relayout) and both accumulations are
    Mosaic-native NN contractions."""
    jk = pl.program_id(1)
    kk = pl.program_id(2)            # window-relative when narrowed
    nq = pl.num_programs(2)
    if span is None:
        iq = kk
        iq_c = kk
        valid = True
    else:
        # a K block's in-window q-blocks are [jk, jk + span); Q/dO are
        # END-padded by span-1 blocks so the index map stays affine,
        # and the pad tail must not contribute
        iq = jk + kk
        iq_c = jnp.minimum(iq, nq_total - 1)
        valid = iq <= nq_total - 1

    @pl.when(kk == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    ok = _diag_ok(iq, jk, causal, block_q, block_k, window)
    if valid is not True:
        ok = jnp.logical_and(ok, valid)

    @pl.when(ok)
    def _():
        dk, dv = _dkv_step(
            q_ref[0], k_ref[0], v_ref[0], do_ref[0],
            lse_ref[0, iq_c, 0, :][None, :],          # [1, bq] lanes
            delta_ref[0, iq_c, 0, :][None, :],
            iq, jk, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, window=window)
        dk_acc[:] += dk
        dv_acc[:] += dv

    @pl.when(kk == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, o, lse, g, causal, scale, block_q, block_k,
                    interpret, window=None):
    b, t, h, d = q.shape
    isz = jnp.dtype(q.dtype).itemsize
    plan = _tiles(t, causal, block_q, block_k, window, d=d, itemsize=isz)
    assert plan is not None, (
        "no flash tile fits the VMEM budget for this shape — the forward "
        "pass takes the plain-attention fallback for identical arguments, "
        "so this backward must be unreachable")
    block_q, block_k = plan
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qb, kb, vb = _bh(q), _bh(k), _bh(v)
    dob, ob = _bh(g), _bh(o)
    # lse enters the kernels at TRUE [B*H, T] size, reshaped to
    # [B*H, nq, 1, block_q] so Mosaic's tiling rule (trailing block
    # dims equal the array dims) accepts a one-row block; the dq kernel
    # relayouts the row into VMEM column scratch once per q-block, the
    # dkv kernel works in transposed score space where the row is
    # already lane-shaped (see _scores). delta (rowsum(dO * O),
    # FlashAttention-2 eq. 4) is no longer precomputed by XLA at all:
    # the dq kernel folds it into its kk == 0 / loop prologue (dO and O
    # stream there anyway) and emits it in the same compact lane-major
    # layout for the dkv kernel. This closes the round-2 ADVICE item
    # (the old layout broadcast both vectors to [B*H, T, 128] f32 in
    # HBM) AND the round-5 one (the separate delta reduction paid one
    # extra full HBM pass over dO and O per backward).
    nq, nk = t // block_q, t // block_k
    lse4 = lse.reshape(b * h, nq, 1, block_q)
    delta_shape = jax.ShapeDtypeStruct((b * h, nq, 1, block_q),
                                       jnp.float32)
    dq_shape = jax.ShapeDtypeStruct((b * h, t, d), q.dtype)

    if _choose_scheme("dq", t, d, isz, block_q, block_k) == "resident":
        dq_kernel = functools.partial(
            _dq_res_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, window=window, nk=nk)
        dq, delta4 = pl.pallas_call(
            dq_kernel,
            grid=(b * h, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((1, 1, 1, block_q),
                             lambda i, j: (i, j, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((1, 1, 1, block_q),
                             lambda i, j: (i, j, 0, 0)),
            ],
            out_shape=[dq_shape, delta_shape],
            compiler_params=_dim_semantics(2),
            interpret=interpret,
        )(qb, kb, vb, dob, ob, lse4)
    else:
        # same grid narrowing as the streaming forward — _narrowed_kv
        # is the single definition, so fwd and dq cannot disagree on
        # which blocks stream; narrows for any m = bq//bk (affine)
        span, kv_j, kb_in, vb_in = _narrowed_kv(
            causal, window, block_q, block_k, nk, kb, vb)
        dq_kernel = functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, window=window, span=span)
        dq, delta4 = pl.pallas_call(
            dq_kernel,
            grid=(b * h, nq, span if span is not None else nk),
            in_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda i, j, kk: (i, j, 0)),
                pl.BlockSpec((1, block_k, d), kv_j),
                pl.BlockSpec((1, block_k, d), kv_j),
                pl.BlockSpec((1, block_q, d),
                             lambda i, j, kk: (i, j, 0)),
                pl.BlockSpec((1, block_q, d),
                             lambda i, j, kk: (i, j, 0)),
                pl.BlockSpec((1, 1, 1, block_q),
                             lambda i, j, kk: (i, j, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda i, j, kk: (i, j, 0)),
                pl.BlockSpec((1, 1, 1, block_q),
                             lambda i, j, kk: (i, j, 0, 0)),
            ],
            out_shape=[dq_shape, delta_shape],
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),  # lse column
                pltpu.VMEM((block_q, 1), jnp.float32),  # delta column
            ],
            compiler_params=_dim_semantics(3),
            interpret=interpret,
        )(qb, kb_in, vb_in, dob, ob, lse4)

    dkv_shapes = [
        jax.ShapeDtypeStruct((b * h, t, d), k.dtype),
        jax.ShapeDtypeStruct((b * h, t, d), v.dtype),
    ]
    if _choose_scheme("dkv", t, d, isz, block_q, block_k) == "resident":
        dkv_kernel = functools.partial(
            _dkv_res_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, window=window, nq=nq)
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid=(b * h, nk),
            in_specs=[
                pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((1, nq, 1, block_q),
                             lambda i, j: (i, 0, 0, 0)),
                pl.BlockSpec((1, nq, 1, block_q),
                             lambda i, j: (i, 0, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            ],
            out_shape=dkv_shapes,
            compiler_params=_dim_semantics(2),
            interpret=interpret,
        )(kb, vb, qb, dob, lse4, delta4)
    else:
        # the streaming dkv kernel's q-start index jk // m is NOT
        # affine for m > 1, so it narrows only at m == 1 and otherwise
        # keeps the full grid with compute-skip. m == 1: q-blocks
        # [jk, jk+span) mirror the dq kernel's k-blocks [iq-span+1, iq]
        # over END-padded Q/dO arrays.
        m_ratio = block_q // block_k
        span = (_window_span(window, block_q, block_k, nk)
                if causal else None)
        span_dkv = span if m_ratio == 1 else None
        qb_in, dob_in = qb, dob
        if span_dkv is not None:
            q_pad = (span_dkv - 1) * block_q
            qb_in = jnp.pad(qb, ((0, 0), (0, q_pad), (0, 0)))
            dob_in = jnp.pad(dob, ((0, 0), (0, q_pad), (0, 0)))
        qdo_j = (lambda i, j, kk: (i, kk, 0)) if span_dkv is None else (
            lambda i, j, kk: (i, j + kk, 0))
        dkv_kernel = functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, window=window,
            span=span_dkv, nq_total=nq)
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid=(b * h, nk,
                  span_dkv if span_dkv is not None else nq),
            in_specs=[
                pl.BlockSpec((1, block_k, d),
                             lambda i, j, kk: (i, j, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda i, j, kk: (i, j, 0)),
                pl.BlockSpec((1, block_q, d), qdo_j),
                pl.BlockSpec((1, block_q, d), qdo_j),
                pl.BlockSpec((1, nq, 1, block_q),
                             lambda i, j, kk: (i, 0, 0, 0)),
                pl.BlockSpec((1, nq, 1, block_q),
                             lambda i, j, kk: (i, 0, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d),
                             lambda i, j, kk: (i, j, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda i, j, kk: (i, j, 0)),
            ],
            out_shape=dkv_shapes,
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
            compiler_params=_dim_semantics(3),
            interpret=interpret,
        )(kb, vb, qb_in, dob_in, lse4, delta4)
    return (_unbh(dq, b, h), _unbh(dk, b, h), _unbh(dv, b, h))


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               window):
    out, lse = _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                               interpret, save_lse=True, window=window)
    if lse is None:  # fallback path (statically decidable from shapes)
        return out, (q, k, v)
    # The residual is carried as [B, T, H, 1] — the same
    # batch/sequence/head layout as q/k/v/o — NOT the kernel's [B*H, T],
    # and the residual tuple carries NO None sentinels: both confuse
    # `shard_map(..., check_vma=False)` grad residual handling (the
    # hoisted residual gets mis-wired and downstream reshapes see the
    # lse where the output should be — see test_ulysses_flash_grads).
    b, t, h, d = q.shape
    lse4 = _unbh(lse[..., None], b, h)  # [B*H, T, 1] -> [B, T, H, 1]
    return out, (q, k, v, out, lse4)


def _flash_bwd(causal, scale, block_q, block_k, interpret, window,
               res, g):
    q, k, v = res[0], res[1], res[2]
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if len(res) == 3:  # shapes didn't tile: mirror the fallback forward
        _, vjp = jax.vjp(
            lambda q, k, v: _plain_attention(q, k, v, causal, scale,
                                             window=window), q, k, v)
        return vjp(g)
    o, lse4 = res[3], res[4]
    lse = _bh(lse4)[..., 0]  # [B, T, H, 1] -> [B*H, T]
    return _flash_bwd_impl(q, k, v, o, lse, g, causal, scale, block_q,
                           block_k, interpret, window=window)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# planning / accounting introspection (benchmarks + structural tests)
# ---------------------------------------------------------------------------


def flash_plan(t, d, *, dtype=jnp.float32, causal=False, window=None,
               block_q=None, block_k=None):
    """Static execution plan for `flash_attention` at this shape: block
    sizes, per-kernel scheme, and per-kernel VISITED K/V (or Q/dO)
    block counts — the exact fori/grid trip totals, derived from the
    same `_k_span`/`_q_span`/`_window_span` the kernels use, so the
    structural block-skip tests and published benchmark metadata
    cannot drift from the implementation. `grid_blocks` is the
    unskipped outer*inner product for comparison."""
    isz = jnp.dtype(dtype).itemsize
    tiles = _tiles(t, causal, block_q, block_k, window, d=d,
                   itemsize=isz)
    if tiles is None:
        return {"scheme": "plain"}
    bq, bk = tiles
    nq, nk = t // bq, t // bk
    plan = {"block_q": bq, "block_k": bk, "nq": nq, "nk": nk}
    span = _window_span(window, bq, bk, nk) if causal else None
    for which in ("fwd", "dq", "dkv"):
        scheme = _choose_scheme(which, t, d, isz, bq, bk)
        if which == "dkv":
            grid_blocks = nk * nq
            if scheme == "resident":
                visited = 0
                for jk in range(nk):
                    lo, hi = _q_span(jk, nq, causal=causal,
                                     window=window, block_q=bq,
                                     block_k=bk)
                    visited += int(hi) - int(lo)
            else:
                span_dkv = span if bq == bk else None
                visited = nk * (span_dkv if span_dkv is not None
                                else nq)
        else:
            grid_blocks = nq * nk
            if scheme == "resident":
                visited = 0
                for iq in range(nq):
                    lo, hi = _k_span(iq, nk, causal=causal,
                                     window=window, block_q=bq,
                                     block_k=bk)
                    visited += int(hi) - int(lo)
            else:
                visited = nq * (span if span is not None else nk)
        plan[which] = {"scheme": scheme, "visited_blocks": visited,
                       "grid_blocks": grid_blocks}
    return plan


def flash_attention_flops(b, t, h, d, causal=False, window=None,
                          backward=False):
    """Useful matmul FLOPs of one flash_attention call (per the
    standard 2-FLOPs/MAC convention), counting only VISIBLE (q, k)
    position pairs — causal halves the full t^2, a sliding window caps
    each row at window+1 — so achieved/peak from this numerator is the
    honest kernel efficiency (masked-but-computed score area inside
    partially visible blocks counts as overhead, not work). Forward:
    QK^T + PV = 4*pairs*d; `backward=True` returns the fwd+bwd total
    for a grad call (the four backward block matmuls add 8*pairs*d)."""
    if causal:
        if window is not None:
            w = min(window, t - 1)
            pairs = t * (w + 1) - w * (w + 1) // 2
        else:
            pairs = t * (t + 1) // 2
    else:
        pairs = t * t
    flops = 4 * b * h * pairs * d
    if backward:
        flops += 8 * b * h * pairs * d
    return flops
