"""Flash attention as a Pallas TPU kernel.

The hot op of the long-context path (`parallel/sequence.py`): plain
attention materializes [T, T] scores in HBM; this kernel streams K/V
blocks through VMEM with online-softmax accumulation so HBM traffic is
O(T) per query block (FlashAttention, Dao et al. 2022 — on TPU the
win is HBM bandwidth, the usual bottleneck, not SRAM reuse).

Grid: one program per (batch*head, query-block). Each program keeps its
Q block, the running max/denominator and the output accumulator in
VMEM/registers and loops over K/V blocks with `lax.fori_loop`.

`flash_attention` falls back to the plain jnp implementation when
shapes don't tile (T % block != 0) or on backends without Mosaic
(interpret mode covers CPU tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _scores(q_blk, k_blk, iq, jk, *, scale, causal, block_q, block_k,
            window=None, transpose=False):
    """Scaled (and causal/window-masked) score block — shared by the
    forward and both backward kernels so the masking and scaling
    semantics cannot drift apart.

    `transpose=False`: [block_q, block_k] (q on sublanes) — the
    forward and dq-kernel layout (dq caches the per-q lse/delta
    columns in VMEM scratch once per q-block). `transpose=True`:
    [block_k, block_q] (q on LANES) — the dkv kernel works in this
    transposed score space so the compactly-stored lane-major
    lse/delta rows (see `_flash_bwd_impl`) broadcast against scores
    with no lane<->sublane relayout, and its two accumulations become
    Mosaic-native NN contractions (the untransposed dkv pays two TN
    forms). Measured on v5e at T=16k: this split is the fastest of
    the four layout/orientation combinations tried (see git history
    of this file), 7% faster end-to-end fwd+bwd than the round-3
    [B*H, T, 128] lane-broadcast scheme it replaces.

    `window` (sliding-window attention, causal only): position q
    attends to keys [q - window, q]. Self is always visible, so no row
    is ever fully masked.
    """
    if transpose:
        shape = (block_k, block_q)
        q_dim, k_dim = 1, 0
        s = jax.lax.dot_general(
            k_blk.astype(jnp.float32),
            q_blk.astype(jnp.float32) * scale,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        shape = (block_q, block_k)
        q_dim, k_dim = 0, 1
        s = jax.lax.dot_general(
            q_blk.astype(jnp.float32) * scale,
            k_blk.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    if causal or window is not None:
        q_pos = iq * block_q + lax.broadcasted_iota(
            jnp.int32, shape, q_dim)
        k_pos = jk * block_k + lax.broadcasted_iota(
            jnp.int32, shape, k_dim)
        keep = q_pos >= k_pos
        if window is not None:
            keep &= q_pos - k_pos <= window
        s = jnp.where(keep, s, NEG_INF)
    return s


def _diag_ok(iq, jk, causal, block_q, block_k, window=None):
    """False for blocks with no visible entries: causal K blocks
    entirely above the diagonal, and (with a sliding window) K blocks
    entirely below the window — those are SKIPPED, which is what makes
    windowed attention O(T * window) compute instead of O(T^2)."""
    ok = (jk * block_k <= (iq + 1) * block_q - 1) if causal else True
    if window is not None:
        # newest key of this block still within the OLDEST query's reach
        win_ok = jk * block_k + block_k - 1 >= iq * block_q - window
        ok = win_ok if ok is True else jnp.logical_and(ok, win_ok)
    return ok


def _window_span(window, block_q, block_k, n_blocks):
    """K blocks a q-block can see under a causal sliding window, in
    k-block units, for block_q = m * block_k (the causal tiling
    invariant): first visible k-block of q-block i is
    i*m - ceil(window/block_k) and the last is i*m + m - 1, both
    AFFINE in i, so span = m + ceil(window/block_k) and the padded
    index map stays affine (see _flash_fwd_impl). m > 1 trades masked
    score area inside the band for fewer per-q-block prologues;
    measured at T=16k/window=512 the masked area wins (m=2 forward
    1.445 ms vs m=1's 0.969) so auto never picks m > 1 — the
    generality exists for window/block mixes where the trade flips.
    None = no narrowing (window absent, or it would not shrink the
    grid)."""
    if window is None:
        return None
    m = block_q // block_k
    span = m + (window + block_k - 1) // block_k
    return span if span < n_blocks else None


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
            scale, causal, block_q, block_k, window=None, span=None):
    """Grid (B*H, nq, nk), nk innermost: the VMEM scratch (accumulator +
    running max/denominator) carries the online-softmax state across the
    sequential K-block steps; K/V blocks stream through VMEM one at a
    time, so resident VMEM stays O(block) regardless of T.

    `span` (sliding window): the grid's inner dim is narrowed to the
    `span` K blocks a q-block can actually see, and the K/V index maps
    shift by the q-block (see _flash_fwd_impl) — out-of-window K/V
    blocks never even stream their DMA. The kernel recovers the REAL
    k-block index from the window-relative grid index here."""
    iq = pl.program_id(1)
    kk = pl.program_id(2)            # window-relative when narrowed
    nk = pl.num_programs(2)
    # narrowed: K/V are front-padded by span-m blocks (m = bq//bk) so
    # the index map stays AFFINE (i, j*m + kk) — a max() in the map
    # was measured to defeat Mosaic's DMA prefetch pipelining (~28%
    # slower) — and the real k-block index is recovered here (< 0
    # falls in the pad and is skipped)
    if span is None:
        jk = kk
    else:
        m_ratio = block_q // block_k
        jk = iq * m_ratio + kk - (span - m_ratio)
    ok = _diag_ok(iq, jk, causal, block_q, block_k, window)
    if span is not None:
        ok = jnp.logical_and(jk >= 0, ok)

    @pl.when(kk == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(ok)
    def _():
        s = _scores(q_ref[0], k_ref[0], iq, jk, scale=scale,
                    causal=causal, block_q=block_q, block_k=block_k,
                    window=window)
        v_blk = v_ref[0].astype(jnp.float32)
        m = m_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, axis=-1)
        m_ref[:, 0] = m_new
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _():
        l = l_ref[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)
        if lse_ref is not None:
            # per-row logsumexp of the scaled scores — the backward
            # kernels reconstruct p = exp(s - lse) from it instead of
            # saving [T, T]. Stored lane-major at true [B*H, T] size;
            # the one sublane->lane relayout here runs once per
            # q-block, not per inner step. Skipped entirely on the
            # no-grad forward (save_lse=False).
            lse_ref[0, 0] = (m_ref[:, 0] + jnp.log(l)).reshape(
                1, block_q)


def _kernel_nolse(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  scale, causal, block_q, block_k, window=None,
                  span=None):
    _kernel(q_ref, k_ref, v_ref, o_ref, None, acc_ref, m_ref, l_ref,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            window=window, span=span)


def _plain_attention(q, k, v, causal, scale, window=None):
    # single reference implementation, shared with the sequence-parallel
    # mixers (sequence.py has no pallas dependency; this module does)
    from ..parallel.sequence import _local_attention

    return _local_attention(q, k, v, causal=causal, scale=scale,
                            window=window)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    window: int | None = None,
) -> jnp.ndarray:
    """Attention over [B, T, H, D] without materializing [T, T] scores.

    Tiling requires T % block == 0 (and causal additionally
    block_q % block_k == 0); other shapes use the plain implementation.
    `block_q`/`block_k` default to auto: T <= 512 runs as ONE block
    (any length — full-dim blocks always satisfy Mosaic's tiling rule;
    odd lengths verified on real v5e), longer T picks the largest of
    512/256/128 dividing it (512 fastest measured on v5e), and longer
    non-dividing T takes the plain fallback. `interpret=None`
    auto-selects interpreter mode off-TPU so tests run on the CPU mesh.

    Backward pass: fused flash backward kernels — the forward saves only
    (q, k, v, o, lse), and dq/dk/dv are computed blockwise with the
    FlashAttention-2 recurrence (p re-materialized per block from the
    saved logsumexp), so both directions are O(T) in HBM. Non-tiling
    shapes fall back to the plain VJP.

    `window` (requires causal=True): sliding-window attention — position
    q attends to keys [q - window, q] (Mistral-style local attention).
    The grid itself narrows to the `span` K blocks a q-block can see
    (K/V and Q/dO are padded so the shifted index maps stay affine), so
    out-of-window blocks stream no DMA and spend no FLOPs — O(T *
    window) compute AND data movement. The forward and dq kernels
    narrow for ANY block_q = m * block_k (the maps stay affine — see
    `_window_span`); only the dkv kernel requires m == 1 and keeps
    compute-skip otherwise. Measured at T=16k, window=512 on v5e with
    the round-5 slope harness (earlier per-call figures were
    relay-latency artifacts): training fwd+bwd 5.48x, forward 4.54x
    vs the full-causal auto-block baseline.
    """
    out, _ = _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                             interpret, save_lse=False, window=window)
    return out


def _tiles(t, causal, block_q, block_k, window=None):
    """The (block_q, block_k) actually usable for length t, or None.

    `None` block sizes auto-select the largest power-of-two <= 1024
    that divides t. Round-5 v5e sweep (fwd+bwd, b*h=144, d=64):
    1024 beats 512 by 21-22% at t = 1024 / 2048 / 4096 (fewer
    per-q-block prologue/epilogues and bigger matmuls); 512 had
    previously beaten 128 by ~25%. With a sliding `window`, the cap is
    the largest power-of-two <= window instead: past-window score area
    inside a block is masked waste, and at t=16k/window=512 the 1024
    block measured 40% SLOWER (7.04 vs 5.02 ms) than 512. Explicit
    sizes are respected as given; mixing one explicit size with auto
    fills the other with the SAME value so the causal divisibility
    invariant can't silently demote the call to plain attention. Tiles
    below 128 starve the MXU, so auto only goes smaller when one block
    covers the whole (short) sequence; otherwise non-tiling lengths
    take the plain fallback as before.
    """
    if block_q is None and block_k is None:
        cap = 1024
        if window is not None:
            cap = max(128, 1 << max(7, (window).bit_length() - 1))
            cap = min(cap, 1024)
        if t <= cap:
            block_q = block_k = t  # one block: any length tiles
        else:
            auto = next((b for b in (1024, 512, 256, 128)
                         if b <= cap and t % b == 0), None)
            if auto is None:
                return None
            block_q = block_k = auto
    elif block_q is None:
        block_q = block_k
    elif block_k is None:
        block_k = block_q
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if (t % block_q or t % block_k
            or (causal and block_q % block_k)):
        return None
    return block_q, block_k


def _bh(x):
    """[B, T, H, D] -> [B*H, T, D]: one grid row per (batch, head)."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unbh(x, b, h):
    bh_, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret,
                    save_lse, window=None):
    """Returns (out, lse) — lse is None on the plain-attention fallback
    or when `save_lse` is False (the no-grad forward skips the extra
    [B*H, T] output entirely: no HBM allocation, no writes)."""
    # validated HERE, not in the custom_vjp primal: under jax.grad the
    # primal body never runs (custom_vjp routes straight to _flash_fwd,
    # which also lands here), so a primal-only check would let autodiff
    # silently compute semantics the caller never asked for
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 0:
            raise ValueError(f"window must be >= 0, got {window}")
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    tiles = _tiles(t, causal, block_q, block_k, window)
    if tiles is None:
        return _plain_attention(q, k, v, causal, scale,
                                window=window), None
    block_q, block_k = tiles
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # sliding window: narrow the inner grid dim to the `span` K blocks
    # a q-block can see and shift the K/V index maps by the q-block —
    # out-of-window K/V never streams (round 3 skipped only the
    # COMPUTE via pl.when, leaving the full-causal DMA schedule, and
    # measured 2.3x where FLOP proportionality allows ~8x). K/V are
    # front-padded by span-m blocks (m = bq//bk, affine for any m —
    # see _window_span) so the map stays AFFINE (see _kernel).
    span = (_window_span(window, block_q, block_k, t // block_k)
            if causal else None)
    m_ratio = block_q // block_k
    kv_j = (lambda i, j, kk: (i, kk, 0)) if span is None else (
        lambda i, j, kk: (i, j * m_ratio + kk, 0))
    kb_in, vb_in = _bh(k), _bh(v)
    if span is not None:
        kv_pad = (span - m_ratio) * block_k
        kb_in = jnp.pad(kb_in, ((0, 0), (kv_pad, 0), (0, 0)))
        vb_in = jnp.pad(vb_in, ((0, 0), (kv_pad, 0), (0, 0)))
    kernel = functools.partial(
        _kernel if save_lse else _kernel_nolse, scale=scale,
        causal=causal, block_q=block_q, block_k=block_k, window=window,
        span=span)
    o_spec = pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0))
    o_shape = jax.ShapeDtypeStruct((b * h, t, d), q.dtype)
    nq = t // block_q
    lse_spec = pl.BlockSpec((1, 1, 1, block_q),
                            lambda i, j, kk: (i, j, 0, 0))
    lse_shape = jax.ShapeDtypeStruct((b * h, nq, 1, block_q),
                                     jnp.float32)
    result = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q,
              span if span is not None else t // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), kv_j),
            pl.BlockSpec((1, block_k, d), kv_j),
        ],
        out_specs=[o_spec, lse_spec] if save_lse else o_spec,
        out_shape=[o_shape, lse_shape] if save_lse else o_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denominator
        ],
        interpret=interpret,
    )(_bh(q), kb_in, vb_in)
    if not save_lse:
        return _unbh(result, b, h), None
    out, lse = result
    return _unbh(out, b, h), lse.reshape(b * h, t)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, acc_ref, lse_col, delta_col, *, scale,
                   causal, block_q, block_k, window=None, span=None):
    """Grid (B*H, nq, nk), nk innermost: accumulate dq for one Q block
    while K/V blocks stream by. p is rebuilt from the saved lse, never
    stored: ds = p * (dp - delta); dq += scale * ds @ k. The q-row
    lse/delta arrive lane-major (compact [B*H, T] storage) and are
    relayouted to columns ONCE per q-block into VMEM scratch — this
    kernel's blocks change only with (i, q-block), so the inner k-sweep
    reuses the cached columns; its matmuls stay in Mosaic-native NN/NT
    forms (a fully transposed-space dq variant turns ds @ k into a TN
    contraction and measured 36% slower end-to-end)."""
    iq = pl.program_id(1)
    kk = pl.program_id(2)            # window-relative when narrowed
    nk = pl.num_programs(2)
    # affine narrowed indexing over front-padded K/V (see _kernel)
    if span is None:
        jk = kk
    else:
        m_ratio = block_q // block_k
        jk = iq * m_ratio + kk - (span - m_ratio)
    ok = _diag_ok(iq, jk, causal, block_q, block_k, window)
    if span is not None:
        ok = jnp.logical_and(jk >= 0, ok)

    @pl.when(kk == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        lse_col[:] = lse_ref[0, 0].reshape(block_q, 1)
        delta_col[:] = delta_ref[0, 0].reshape(block_q, 1)

    @pl.when(ok)
    def _():
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = _scores(q_ref[0], k_ref[0], iq, jk, scale=scale,
                    causal=causal, block_q=block_q, block_k=block_k,
                    window=window)
        p = jnp.exp(s - lse_col[:])
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_col[:])
        acc_ref[:] += scale * jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, window=None, span=None,
                    nq_total=None):
    """Grid (B*H, nk, nq), nq innermost: accumulate dk/dv for one K/V
    block while Q/dO blocks stream by, in TRANSPOSED score space (q on
    lanes — see _scores): dv += pT @ do; dk += scale * dsT @ q.

    lse/delta arrive as the head's FULL row set ([1, nq, 1, block_q],
    index_map constant over both inner grid dims), so their DMA runs
    once per head instead of once per inner step — per-step 2 KB
    fetches left ~30% on the table at T=16k — and the per-q-block row
    is a cheap non-tiled-dim select. In transposed space the row is
    already a lane vector (no relayout) and both accumulations are
    Mosaic-native NN contractions."""
    jk = pl.program_id(1)
    kk = pl.program_id(2)            # window-relative when narrowed
    nq = pl.num_programs(2)
    if span is None:
        iq = kk
        iq_c = kk
        valid = True
    else:
        # a K block's in-window q-blocks are [jk, jk + span); Q/dO are
        # END-padded by span-1 blocks so the index map stays affine,
        # and the pad tail must not contribute
        iq = jk + kk
        iq_c = jnp.minimum(iq, nq_total - 1)
        valid = iq <= nq_total - 1

    @pl.when(kk == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    ok = _diag_ok(iq, jk, causal, block_q, block_k, window)
    if valid is not True:
        ok = jnp.logical_and(ok, valid)

    @pl.when(ok)
    def _():
        q = q_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s_t = _scores(q_ref[0], k_ref[0], iq, jk, scale=scale,
                      causal=causal, block_q=block_q, block_k=block_k,
                      window=window, transpose=True)  # [bk, bq]
        lse_row = lse_ref[0, iq_c, 0, :][None, :]     # [1, bq] lanes
        delta_row = delta_ref[0, iq_c, 0, :][None, :]
        p_t = jnp.exp(s_t - lse_row)
        dv_acc[:] += jax.lax.dot_general(
            p_t, do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # p^T @ do
        dp_t = jax.lax.dot_general(
            v_blk, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # (do @ v^T)^T
        ds_t = p_t * (dp_t - delta_row)
        dk_acc[:] += scale * jax.lax.dot_general(
            ds_t, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # ds^T @ q

    @pl.when(kk == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, o, lse, g, causal, scale, block_q, block_k,
                    interpret, window=None):
    b, t, h, d = q.shape
    block_q, block_k = _tiles(t, causal, block_q, block_k,
                                window)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qb, kb, vb = _bh(q), _bh(k), _bh(v)
    dob = _bh(g)
    # delta_i = rowsum(dO * O): one cheap elementwise pass, shared by
    # both kernels (FlashAttention-2 eq. 4). lse/delta enter the
    # kernels at TRUE [B*H, T] size, reshaped to [B*H, nq, 1, block_q]
    # so Mosaic's tiling rule (trailing block dims equal the array
    # dims) accepts a one-row block; the dq kernel relayouts the row
    # into VMEM column scratch once per q-block, the dkv kernel works
    # in transposed score space where the row is already lane-shaped
    # (see _scores). This closes the round-2 ADVICE item: the old
    # layout broadcast both vectors to [B*H, T, 128] f32 in HBM
    # (~100 MB each at B*H=8, T=32k) and paid 128x-sized DMAs per
    # backward grid step.
    nq, nk = t // block_q, t // block_k
    delta = jnp.sum(dob.astype(jnp.float32)
                    * _bh(o).astype(jnp.float32), axis=-1)  # [BH, T]
    lse4 = lse.reshape(b * h, nq, 1, block_q)
    delta4 = delta.reshape(b * h, nq, 1, block_q)
    # same grid narrowing as the forward (see _flash_fwd_impl): only
    # in-window K/V (for dq) and Q/dO (for dk/dv) blocks ever stream.
    # dq narrows for any m = bq//bk (affine, like the forward); the
    # dkv kernel's q-start index jk // m is NOT affine for m > 1, so
    # dkv narrows only at m == 1 and otherwise keeps the full grid
    # with compute-skip.
    m_ratio = block_q // block_k
    span = (_window_span(window, block_q, block_k, nk)
            if causal else None)
    span_dkv = span if m_ratio == 1 else None
    kv_j = (lambda i, j, kk: (i, kk, 0)) if span is None else (
        lambda i, j, kk: (i, j * m_ratio + kk, 0))
    kb_in, vb_in = kb, vb
    qb_in, dob_in = qb, dob
    if span is not None:
        kv_pad = (span - m_ratio) * block_k
        kb_in = jnp.pad(kb, ((0, 0), (kv_pad, 0), (0, 0)))
        vb_in = jnp.pad(vb, ((0, 0), (kv_pad, 0), (0, 0)))
    if span_dkv is not None:
        q_pad = (span_dkv - 1) * block_q
        qb_in = jnp.pad(qb, ((0, 0), (0, q_pad), (0, 0)))
        dob_in = jnp.pad(dob, ((0, 0), (0, q_pad), (0, 0)))
    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, window=window, span=span)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, nq, span if span is not None else nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), kv_j),
            pl.BlockSpec((1, block_k, d), kv_j),
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda i, j, kk: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda i, j, kk: (i, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda i, j, kk: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),  # lse column cache
            pltpu.VMEM((block_q, 1), jnp.float32),  # delta column cache
        ],
        interpret=interpret,
    )(qb, kb_in, vb_in, dob, lse4, delta4)

    # m == 1 only (see span_dkv above): q-blocks [jk, jk+span) mirror
    # the dq kernel's k-blocks [iq-span+1, iq] over the padded arrays
    qdo_j = (lambda i, j, kk: (i, kk, 0)) if span_dkv is None else (
        lambda i, j, kk: (i, j + kk, 0))
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, window=window, span=span_dkv, nq_total=nq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, nk, span_dkv if span_dkv is not None else nq),
        in_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_q, d), qdo_j),
            pl.BlockSpec((1, block_q, d), qdo_j),
            pl.BlockSpec((1, nq, 1, block_q),
                         lambda i, j, kk: (i, 0, 0, 0)),
            pl.BlockSpec((1, nq, 1, block_q),
                         lambda i, j, kk: (i, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(kb, vb, qb_in, dob_in, lse4, delta4)
    return (_unbh(dq, b, h), _unbh(dk, b, h), _unbh(dv, b, h))


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               window):
    out, lse = _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                               interpret, save_lse=True, window=window)
    if lse is None:  # fallback path (statically decidable from shapes)
        return out, (q, k, v)
    # The residual is carried as [B, T, H, 1] — the same
    # batch/sequence/head layout as q/k/v/o — NOT the kernel's [B*H, T],
    # and the residual tuple carries NO None sentinels: both confuse
    # `shard_map(..., check_vma=False)` grad residual handling (the
    # hoisted residual gets mis-wired and downstream reshapes see the
    # lse where the output should be — see test_ulysses_flash_grads).
    b, t, h, d = q.shape
    lse4 = _unbh(lse[..., None], b, h)  # [B*H, T, 1] -> [B, T, H, 1]
    return out, (q, k, v, out, lse4)


def _flash_bwd(causal, scale, block_q, block_k, interpret, window,
               res, g):
    q, k, v = res[0], res[1], res[2]
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if len(res) == 3:  # shapes didn't tile: mirror the fallback forward
        _, vjp = jax.vjp(
            lambda q, k, v: _plain_attention(q, k, v, causal, scale,
                                             window=window), q, k, v)
        return vjp(g)
    o, lse4 = res[3], res[4]
    lse = _bh(lse4)[..., 0]  # [B, T, H, 1] -> [B*H, T]
    return _flash_bwd_impl(q, k, v, o, lse, g, causal, scale, block_q,
                           block_k, interpret, window=window)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
