"""Fused paged-attention decode as a Pallas TPU kernel.

The serving decode step (`serve/paged.py`) is shaped around a block-
table KV pool: each batch row owns an ordered list of fixed-size pool
blocks and a length. The stock-JAX path re-gathers every row's blocks
into a contiguous [T, h, d] view per layer per step
(``pool_k[layer][tables]``), which materializes
B * max_blocks * block_tokens * h * d bytes of HBM traffic per layer
even for rows that occupy two blocks. This kernel removes the
re-gather: per-row block tables and lengths ride in as SCALAR-PREFETCH
arguments (`pltpu.PrefetchScalarGridSpec`), the K/V BlockSpec index
maps chase the table (``tbl[b, j]`` picks the j-th pool block of row
b), and the grid's inner dimension is clamped to each row's own
visible block count — steps past ``lengths[b] // bt`` re-issue the
LAST visible block's index, which Pallas's block-revisiting rule turns
into zero new DMA traffic, so the bytes actually moved per row are
O(length), not O(max_len). vLLM's PagedAttention decode shape
(PAPERS.md), as a flash-style Pallas kernel.

Two execution schemes per shape, chosen by a VMEM-budget estimate in
the `flash_plan` style (``paged_plan`` shows the decision):

- **resident** (preferred while it fits): VMEM scratch holds the
  row's full score buffer ([max_blocks, h, bt] f32) and a copy of its
  visited V blocks; the final grid step runs ONE full-width softmax
  over the buffer — the exact shape and masking of the functional
  path's f32 softmax, which is what makes the functional path a
  bitwise oracle for this scheme (pinned by
  tests/test_serve.py::TestPagedKernel).
- **stream** (fallback past the budget — long max_len residency):
  online-softmax carried in O(h*d) scratch across the inner grid, the
  flash recurrence at block_tokens granularity. Token-equivalent, not
  bitwise (the usual online-softmax reassociation).

Past BOTH estimates, `paged_plan` says ``functional`` and
`serve.paged.decode_step` keeps its stock-JAX gather — the same
over-budget fallback discipline as ops/flash.py (`_tiles` returning
None), so an impossible shape degrades to slower, never to a Mosaic
compile OOM. The kflint ``vmem-budget`` pass evaluates `paged_plan`
over the serving shape grid for exactly that reason.

`interpret=None` auto-selects interpreter mode off-TPU, so the CPU
test mesh runs the real kernel logic (scalar prefetch included)
without Mosaic.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)

#: same calibration as ops/flash.py — Mosaic's scoped-vmem stack limit
#: is 16 MB; 15 MB leaves scheduling headroom.
_VMEM_BUDGET = 15 * 1024 * 1024

#: test/bench escape hatch: force one scheme regardless of the budget
#: decision (unset = auto). Read at trace time so tests can
#: monkeypatch the module attribute (the KUNGFU_FLASH_SCHEME idiom).
_FORCE_SCHEME = os.environ.get("KUNGFU_PAGED_SCHEME") or None


# ---------------------------------------------------------------------------
# VMEM-budget estimates (style of ops/flash.py)
# ---------------------------------------------------------------------------


def _res_vmem(max_blocks, bt, h, d, isz):
    """Resident scheme: double-buffered K/V pool blocks + q/o + the
    full-length score buffer (f32) and V copy (pool dtype) + softmax
    temporaries (w and the exp intermediate, both [h, T] f32)."""
    t = max_blocks * bt
    inputs = 2 * (2 * bt * h * d * isz)
    io = 2 * (2 * h * d * isz)
    scratch = max_blocks * h * bt * 4 + t * h * d * isz
    temps = 2 * h * t * 4
    return inputs + io + scratch + temps


def _stream_vmem(bt, h, d, isz):
    """Stream scheme: double-buffered K/V blocks + q/o + the online
    state (acc [h, d] + m/l rows, f32) + per-block score temporaries.
    O(block) regardless of max_len."""
    inputs = 2 * (2 * bt * h * d * isz)
    io = 2 * (2 * h * d * isz)
    scratch = h * d * 4 + 2 * h * 4
    temps = 2 * h * bt * 4
    return inputs + io + scratch + temps


def paged_plan(max_blocks, block_tokens, num_heads, head_dim, *,
               dtype=jnp.float32):
    """Static execution plan for `paged_attention` at this pool shape:
    the chosen scheme and the per-scheme VMEM estimates — derived from
    the same models the kernel requests scratch with, so the kflint
    vmem-budget pass and the published benchmark metadata cannot drift
    from the implementation."""
    isz = jnp.dtype(dtype).itemsize
    res = _res_vmem(max_blocks, block_tokens, num_heads, head_dim, isz)
    strm = _stream_vmem(block_tokens, num_heads, head_dim, isz)
    if _FORCE_SCHEME in ("resident", "stream"):
        scheme = _FORCE_SCHEME
    elif res <= _VMEM_BUDGET:
        scheme = "resident"
    elif strm <= _VMEM_BUDGET:
        scheme = "stream"
    else:
        scheme = "functional"
    return {
        "scheme": scheme,
        "t": max_blocks * block_tokens,
        "max_blocks": max_blocks,
        "block_tokens": block_tokens,
        "resident_bytes": res,
        "stream_bytes": strm,
        "vmem_bytes": {"resident": res, "stream": strm,
                       "functional": 0}[scheme],
    }


def paged_traffic_bytes(lengths, block_tokens, num_heads, head_dim,
                        itemsize, layers=1):
    """Block-pool bytes a decode step actually VISITS under the
    table-chasing index maps: per row, the visible blocks only
    (length // bt + 1 of them), K and V, per layer. This is the
    traffic model `benchmarks/flash_eff.py` publishes achieved
    bandwidth against — the whole point of the kernel is that this,
    not B * max_blocks * bt, is what moves."""
    blocks = sum(int(n) // block_tokens + 1 for n in lengths)
    return 2 * layers * blocks * block_tokens * num_heads * head_dim \
        * itemsize


# ---------------------------------------------------------------------------
# kernels (grid (B, max_blocks), block tables + lengths scalar-prefetched)
# ---------------------------------------------------------------------------


def _block_scores(q_ref, k_ref, length, j, *, bt, scale):
    """One pool block's masked f32 score tile [h, bt] — shared by both
    schemes so masking/scaling semantics cannot drift. Matches the
    functional path exactly: f32 einsum over d, scale applied AFTER
    the contraction, invisible positions (> length) forced to
    f32-finfo.min."""
    q = q_ref[0].astype(jnp.float32)            # [h, d]
    k = k_ref[0].astype(jnp.float32)            # [bt, h, d]
    s = jnp.einsum("nd,tnd->nt", q, k) * scale  # [h, bt]
    pos = j * bt + jax.lax.broadcasted_iota(jnp.int32, (1, bt), 1)
    return jnp.where(pos <= length, s, NEG_INF)


def _res_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                s_buf, v_buf, *, bt, max_blocks, scale):
    """Resident scheme: accumulate per-block score tiles and V copies
    into full-length VMEM scratch; the LAST grid step runs one
    full-width softmax + weighted sum — the functional path's exact
    reduction shapes, hence bitwise logits parity (pool dtype V is
    cast to f32 at the same point the functional einsum casts it)."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    length = len_ref[b]
    nvis = length // bt + 1          # the incoming token sits at `length`

    @pl.when(j == 0)
    def _():
        # NEG_INF scores == the functional path's masked fill for
        # never-visited positions; zero V so 0-weight rows contribute
        # exact zeros instead of NaN-poisoning uninitialized VMEM
        s_buf[...] = jnp.full_like(s_buf, NEG_INF)
        v_buf[...] = jnp.zeros_like(v_buf)

    @pl.when(j < nvis)
    def _():
        s_buf[j] = _block_scores(q_ref, k_ref, length, j, bt=bt,
                                 scale=scale)
        v_buf[j] = v_ref[0]

    @pl.when(j == max_blocks - 1)
    def _():
        t = max_blocks * bt
        h = q_ref.shape[1]
        s = s_buf[...].transpose(1, 0, 2).reshape(h, t)   # [h, T]
        w = jax.nn.softmax(s, axis=-1)
        v = v_buf[...].reshape(t, h, -1).astype(jnp.float32)
        o = jnp.einsum("nt,tnd->nd", w, v)
        o_ref[0] = o.astype(o_ref.dtype)


def _stream_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, bt, scale):
    """Stream scheme: the flash online-softmax recurrence carried in
    O(h*d) VMEM scratch across the inner grid — resident VMEM stays
    constant in max_len, for pools whose full-length buffer would not
    fit the budget."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)
    length = len_ref[b]
    nvis = length // bt + 1

    @pl.when(j == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(j < nvis)
    def _():
        s = _block_scores(q_ref, k_ref, length, j, bt=bt, scale=scale)
        m = m_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, axis=-1)
        v = v_ref[0].astype(jnp.float32)          # [bt, h, d]
        acc_ref[...] = (acc_ref[...] * alpha[:, None]
                        + jnp.einsum("nt,tnd->nd", p, v))
        m_ref[:, 0] = m_new

    @pl.when(j == nb - 1)
    def _():
        l = l_ref[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)


def _kv_index_map(base, bt):
    """Table-chasing K/V index map: grid step (b, j) fetches pool
    block ``base + tbl[b, j]``, with j CLAMPED to the row's last
    visible block — past-length steps re-issue the same block index,
    which Pallas's revisiting rule resolves to no new DMA. `base`
    offsets into a [layers * (num_blocks + 1), bt, h, d] pool view so
    the per-layer call needs no layer-slice copy of the pool."""

    def index_map(b, j, tbl_ref, len_ref):
        jj = jnp.minimum(j, len_ref[b] // bt)
        return (base + tbl_ref[b, jj], 0, 0, 0)

    return index_map


def paged_attention(q, k_pool, v_pool, tables, lengths, *,
                    block_base=0, scheme=None, interpret=None):
    """Paged decode attention for one layer.

    - ``q`` [B, h, d] — the current token's query per row (its k/v
      must already be scattered at position ``lengths[b]``);
    - ``k_pool``/``v_pool`` [num_pool_blocks, bt, h, d] — the pool
      tensors (any leading layer structure flattened away; `block_base`
      offsets table entries into it);
    - ``tables`` [B, max_blocks] int32, ``lengths`` [B] int32 — the
      allocator's batch views; visibility is positions 0..length
      INCLUSIVE, matching `serve.paged.decode_step`.

    Returns ``o`` [B, h, d] in q's dtype (the attention output before
    the out-projection). `scheme=None` consults `paged_plan`; a
    "functional" plan raises — the CALLER owns the fallback (it has
    the stock-JAX path; this module has no second implementation to
    silently diverge)."""
    b, h, d = q.shape
    bt = k_pool.shape[1]
    max_blocks = tables.shape[1]
    if scheme is None:
        scheme = paged_plan(max_blocks, bt, h, d, dtype=q.dtype)["scheme"]
    if scheme == "functional":
        raise ValueError(
            "paged_plan chose the functional fallback for this shape — "
            "call serve.paged.decode_step with kernel='functional'")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = d ** -0.5
    tables = tables.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)

    if scheme == "resident":
        kernel = functools.partial(_res_kernel, bt=bt,
                                   max_blocks=max_blocks, scale=scale)
        scratch = [
            pltpu.VMEM((max_blocks, h, bt), jnp.float32),
            pltpu.VMEM((max_blocks, bt, h, d), v_pool.dtype),
        ]
    elif scheme == "stream":
        kernel = functools.partial(_stream_kernel, bt=bt, scale=scale)
        scratch = [
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
        ]
    else:
        raise ValueError(f"unknown paged scheme {scheme!r}")

    kv_map = _kv_index_map(block_base, bt)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_blocks),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda b_, j, tbl, ln: (b_, 0, 0)),
            pl.BlockSpec((1, bt, h, d), kv_map),
            pl.BlockSpec((1, bt, h, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, h, d),
                               lambda b_, j, tbl, ln: (b_, 0, 0)),
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(tables, lengths, q, k_pool, v_pool)
