"""Collectives over a named mesh axis (ICI data plane).

Equivalents of the reference's collective ops (reference:
srcs/python/kungfu/tensorflow/ops/collective.py, srcs/cpp/src/tensorflow/
ops/cpu/collective.cpp), restated for SPMD JAX: every function takes a
pytree and an `axis_name` and must be called inside `shard_map`/`pmap`
tracing over that axis. XLA lowers psum/all_gather/ppermute directly onto
ICI rings — topology selection (the reference's 7 strategy graphs) is the
compiler's job here, not ours.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def all_reduce(tree, axis_name: str = "data"):
    """Sum each leaf over the mesh axis (reference KungfuAllReduce, sum)."""
    return jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), tree)


def all_reduce_mean(tree, axis_name: str = "data"):
    """Mean each leaf over the mesh axis — the S-SGD gradient op."""
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis_name), tree)


def group_all_reduce(tensors: Sequence, axis_name: str = "data") -> List:
    """All-reduce a list of tensors. One psum per tensor, like the
    reference's per-gradient ops; XLA fuses small ones automatically, so
    explicit fusion is an optimization choice, not a correctness one."""
    return [lax.psum(t, axis_name) for t in tensors]


def broadcast(tree, axis_name: str = "data", root: int = 0):
    """Every shard adopts `root`'s value (reference KungfuBroadcast).

    Implemented as mask-then-psum: zero out non-root shards and sum. XLA
    recognises the pattern; cost equals an all-reduce of the tree.
    """

    def bc(x):
        idx = lax.axis_index(axis_name)
        mask = (idx == root).astype(x.dtype)
        return lax.psum(x * mask, axis_name)

    return jax.tree_util.tree_map(bc, tree)


def all_gather(x, axis_name: str = "data", axis: int = 0):
    """Concatenate shards along the existing leading axis (reference
    KungfuAllGather semantics: output leading dim = input dim x cluster
    size)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


def ring_neighbor(x, axis_name: str = "data", shift: int = 1):
    """Receive the value held by rank (i - shift) mod n — a ring rotation
    via collective_permute. The building block for gossip averaging."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def neighbor_exchange(tree, axis_name: str = "data", shift: int = 1):
    """Rotate a whole pytree around the ring by `shift`."""
    return jax.tree_util.tree_map(
        lambda x: ring_neighbor(x, axis_name, shift), tree
    )


# -- fuse/defuse -------------------------------------------------------------
# The reference packs a model into one flat buffer for fused all-reduce and
# P2P model exchange (reference: srcs/python/kungfu/tensorflow/ops/
# __init__.py:22-39, model_buffer.hpp). Same trick here: one contiguous
# vector minimizes DCN round trips for pair-averaging model transfer.


def fuse(tree) -> jnp.ndarray:
    """Flatten a pytree into one 1-D buffer.

    NOTE: mixed-dtype leaves promote to a common dtype (jnp.concatenate
    semantics) and defuse() casts back — lossless for float hierarchies
    (bf16/f16 under f32) but NOT for large ints/bools. For dtype-exact
    host-side transfer (elastic resync, checkpoints) use
    pack_bytes/unpack_bytes instead.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((0,), dtype=jnp.float32)
    return jnp.concatenate([jnp.ravel(l) for l in leaves])


def pack_bytes(tree) -> "np.ndarray":
    """Host-side dtype-exact packing: a pytree -> one uint8 numpy buffer."""
    import numpy as np

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return np.zeros((0,), dtype=np.uint8)
    return np.concatenate(
        [np.ascontiguousarray(np.asarray(l)).view(np.uint8).ravel()
         for l in leaves]
    )


def unpack_bytes(buf, tree_like):
    """Inverse of pack_bytes: uint8 numpy buffer -> pytree with the exact
    shapes/dtypes of `tree_like`.

    Leaves come back as the same kind of array they went in as: numpy
    stays numpy — `jnp.asarray` on a numpy tree would INITIALIZE the
    accelerator backend from a pure control-plane resync (and on the
    bench host route a 98 MiB elastic payload through the TPU relay;
    measured as the round-3 adaptation-latency regression)."""
    import numpy as np

    buf = np.asarray(buf, dtype=np.uint8)
    leaves, treedef = jax.tree_util.tree_flatten(tree_like)
    out = []
    offset = 0
    for l in leaves:
        arr = np.asarray(l)
        nbytes = arr.size * arr.itemsize
        chunk = buf[offset:offset + nbytes]
        restored = chunk.view(arr.dtype).reshape(arr.shape)
        out.append(restored.copy() if isinstance(l, np.ndarray)
                   else jnp.asarray(restored))
        offset += nbytes
    return jax.tree_util.tree_unflatten(treedef, out)


# -- chunked streaming -------------------------------------------------------
# pack_bytes above materializes the WHOLE tree as one host buffer — a
# full extra copy of a 98 MiB model before a single byte hits the wire
# (measured: 476 ms of the 2380 ms elastic grow 2->4, BASELINE round
# 6). The chunk schedule below is the zero-copy replacement: large
# leaves stream as byte-view slices (no copy on either side — the
# receiver lands them straight into the destination leaf), runs of
# small leaves coalesce into bounded scratch chunks. elastic/
# streaming.py drives it as a pipelined broadcast.


def leaf_byte_views(leaves) -> List["np.ndarray"]:
    """Contiguous uint8 1-D views of host leaves (zero-copy for
    C-contiguous numpy leaves; accelerator arrays pay their one
    unavoidable device->host transfer in np.asarray)."""
    import numpy as np

    out = []
    for l in leaves:
        a = np.ascontiguousarray(np.asarray(l))
        out.append(a.reshape(-1).view(np.uint8))
    return out


def chunk_schedule(tree_like, chunk_bytes: int) -> List[List[Tuple[int,
                                                                   int,
                                                                   int]]]:
    """Partition a pytree's bytes into chunks of spans.

    Returns a list of chunks; each chunk is a list of
    ``(leaf_index, byte_offset_in_leaf, nbytes)`` spans covering every
    byte of every leaf exactly once, in leaf order. Schedule-only —
    derived from shapes/dtypes, so every rank computes the identical
    schedule from its own `tree_like`.

    Layout rules: a leaf of >= `chunk_bytes` closes the open chunk
    first, so each of its FULL `chunk_bytes`-sized slices is a
    SINGLE-span chunk (a pure view: no assembly copy on root, received
    in place at the destination); only its sub-chunk remainder may
    coalesce with following small leaves. Smaller leaves coalesce into
    multi-span chunks of at most `chunk_bytes`.
    """
    import numpy as np

    if chunk_bytes <= 0:
        raise ValueError(f"chunk_bytes must be positive: {chunk_bytes}")
    leaves = jax.tree_util.tree_leaves(tree_like)
    chunks: List[List[Tuple[int, int, int]]] = []
    cur: List[Tuple[int, int, int]] = []
    cur_bytes = 0
    for i, l in enumerate(leaves):
        # same leaf tolerance as pack_bytes: Python scalars (no
        # .dtype) count via np.asarray; arrays stay on device
        dt = getattr(l, "dtype", None)
        if dt is None:
            a = np.asarray(l)
            nbytes = int(a.size) * a.itemsize
        else:
            nbytes = int(np.prod(np.shape(l), dtype=np.int64)) \
                * np.dtype(dt).itemsize
        if nbytes >= chunk_bytes and cur:
            chunks.append(cur)
            cur, cur_bytes = [], 0
        off = 0
        while nbytes - off > 0:
            take = min(chunk_bytes - cur_bytes, nbytes - off)
            cur.append((i, off, take))
            cur_bytes += take
            off += take
            if cur_bytes == chunk_bytes:
                chunks.append(cur)
                cur, cur_bytes = [], 0
    if cur:
        chunks.append(cur)
    return chunks


# -- gradient bucketing ------------------------------------------------------
# chunk_schedule above is byte-oriented: broadcast copies bytes, so
# mixed-dtype spans can share a chunk. A gradient ALL-REDUCE sums typed
# elements, so its buckets must be dtype-homogeneous and element-aligned
# — and they fill in REVERSE leaf order, because backward produces the
# output-side gradients first (PyTorch DDP's reverse-registration
# bucketing, Li et al. 2020): the pipeline can put bucket 0 on the wire
# while the input-side backward is still running.


def bucket_schedule(tree_like, bucket_bytes: int) -> List[Tuple[
        "np.dtype", List[Tuple[int, int, int]]]]:
    """Partition a gradient pytree into fixed-byte all-reduce buckets.

    Returns a list of buckets; each bucket is ``(dtype, spans)`` where
    spans are ``(leaf_index, elem_offset, n_elems)`` covering every
    element of every leaf exactly once, leaves taken in REVERSE leaf
    order (the order backward produces them). Schedule-only — derived
    from shapes/dtypes, so every rank computes the identical schedule
    (and therefore the identical bucket launch order) from its own
    `tree_like`.

    Built on `chunk_schedule`: reversed leaves are split into maximal
    same-dtype runs and each run is chunked with `bucket_bytes` rounded
    down to an element multiple, so the layout rules carry over (a
    >= bucket-sized leaf opens fresh and its full slices are
    single-span — zero-copy views end to end; small leaves coalesce).
    """
    import numpy as np

    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive: {bucket_bytes}")
    leaves = jax.tree_util.tree_leaves(tree_like)
    n = len(leaves)
    rev = list(reversed(leaves))

    def leaf_dtype(l):
        dt = getattr(l, "dtype", None)
        return np.dtype(dt) if dt is not None else np.asarray(l).dtype

    out: List[Tuple[np.dtype, List[Tuple[int, int, int]]]] = []
    run_start = 0
    while run_start < n:
        dt = leaf_dtype(rev[run_start])
        run_end = run_start
        while run_end < n and leaf_dtype(rev[run_end]) == dt:
            run_end += 1
        run = rev[run_start:run_end]
        esz = dt.itemsize
        per_bucket = max(1, bucket_bytes // esz) * esz
        for spans in chunk_schedule(run, per_bucket):
            elem_spans = [(n - 1 - (run_start + i), off // esz, nb // esz)
                          for i, off, nb in spans if nb > 0]
            if elem_spans:
                out.append((dt, elem_spans))
        run_start = run_end
    return out


# -- checkpoint sharding -----------------------------------------------------
# The sharded checkpoint tier (kungfu_tpu/checkpoint_async.py) divides
# the tree's bytes across peers so each writes only its shard. The
# assignment must be a pure function of shapes/dtypes — every rank
# derives the identical owner map from its own replica, with no
# negotiation traffic on the save path — so it is a thin layer over
# chunk_schedule: chunk i belongs to shard (i % num_shards).


def shard_schedule(tree_like, chunk_bytes: int,
                   num_shards: int) -> List[Tuple[int, List[Tuple[int,
                                                                  int,
                                                                  int]]]]:
    """Partition a pytree's bytes into per-shard write chunks.

    Returns ``[(owner, spans), ...]`` — the `chunk_schedule` chunks in
    order, chunk i owned by shard ``i % num_shards`` (round-robin keeps
    shard sizes within one chunk of each other for any leaf mix). Spans
    are ``(leaf_index, byte_offset_in_leaf, nbytes)`` covering every
    byte of every leaf exactly once. Schedule-only: derived from
    shapes/dtypes, so every rank computes the identical owner map from
    its own `tree_like` — the determinism contract the kfverify
    schedule-purity pass enforces on every feeder of this function.
    """
    if num_shards <= 0:
        raise ValueError(f"num_shards must be positive: {num_shards}")
    return [(i % num_shards, spans)
            for i, spans in enumerate(chunk_schedule(tree_like,
                                                     chunk_bytes))]


def subtree_shapes(tree) -> List[Tuple]:
    return [l.shape for l in jax.tree_util.tree_leaves(tree)]


def defuse(buf: jnp.ndarray, tree_like):
    """Unflatten `buf` back into the structure/shapes/dtypes of
    `tree_like`."""
    leaves, treedef = jax.tree_util.tree_flatten(tree_like)
    out = []
    offset = 0
    for l in leaves:
        n = l.size
        out.append(jnp.reshape(buf[offset:offset + n], l.shape).astype(
            l.dtype))
        offset += n
    return jax.tree_util.tree_unflatten(treedef, out)
