"""Stateful scalar helpers: step counter and exponential moving average.

The reference implements these as stateful TF kernels
(reference: srcs/cpp/src/tensorflow/ops/cpu/state.cpp:6-78 KungfuCounter /
KungfuExponentialMovingAverage; srcs/cpp/include/kungfu/utils/ema.hpp).
In JAX state is explicit, so they become pure update functions over
NamedTuple state — jit/scan friendly, no hidden resource variables.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class CounterState(NamedTuple):
    value: jnp.ndarray  # int32


def counter(init: int = 0, incr: int = 1):
    """Returns (init_state, update) — update bumps and returns the *pre*
    increment value, matching the reference kernel's semantics."""

    def init_fn() -> CounterState:
        return CounterState(value=jnp.asarray(init, jnp.int32))

    def update(state: CounterState):
        return state.value, CounterState(value=state.value + incr)

    return init_fn, update


class EMAState(NamedTuple):
    value: jnp.ndarray   # running average (bias-corrected on read)
    count: jnp.ndarray   # int32 number of updates


def ema(alpha: float):
    """Bias-corrected EMA: value_t = a*value + (1-a)*x, read corrected by
    1/(1-a^t) (reference: ema.hpp bias correction)."""
    a = float(alpha)

    def init_fn(like=0.0) -> EMAState:
        return EMAState(value=jnp.zeros_like(jnp.asarray(like, jnp.float32)),
                        count=jnp.asarray(0, jnp.int32))

    def update(state: EMAState, x):
        x = jnp.asarray(x, jnp.float32)
        count = state.count + 1
        value = a * state.value + (1.0 - a) * x
        corrected = value / (1.0 - a ** count.astype(jnp.float32))
        return corrected, EMAState(value=value, count=count)

    return init_fn, update
