"""TPU data-plane collective ops.

These are the ICI-native equivalents of the reference's TF custom ops
(reference: srcs/cpp/src/tensorflow/ops/, srcs/python/kungfu/tensorflow/ops/):
pure-JAX functions meant to run inside `shard_map`/`pmap` over a named mesh
axis, where XLA compiles them onto the ICI interconnect. There is no
order-group scheduler here — SPMD compilation fixes the collective order on
every chip, which dissolves the reference's NCCL-order machinery by design
(SURVEY §5.8).
"""

from .collective import (
    all_gather,
    all_reduce,
    all_reduce_mean,
    broadcast,
    defuse,
    fuse,
    pack_bytes,
    group_all_reduce,
    neighbor_exchange,
    unpack_bytes,
    ring_neighbor,
    subtree_shapes,
)
from .monitor import (
    GradNoiseScaleState,
    gradient_variance,
    init_noise_scale,
    tree_sq_norm,
    update_noise_scale,
    update_noise_scale_from_sq,
)
from .state import CounterState, EMAState, counter, ema
from .topology import (
    all_gather_latency_matrix,
    get_neighbour,
    get_peer_latencies,
    minimum_spanning_tree,
    neighbour_mask,
    round_robin,
)

__all__ = [
    # the flash_* names resolve lazily via __getattr__; listing them
    # here keeps star-import/dir() discoverability at the documented
    # cost that `import *` (only) eagerly pays the Pallas import
    "flash_attention",
    "flash_plan",
    "flash_attention_flops",
    "paged_attention",
    "paged_plan",
    "paged_traffic_bytes",
    "all_reduce",
    "all_reduce_mean",
    "group_all_reduce",
    "broadcast",
    "all_gather",
    "fuse",
    "defuse",
    "pack_bytes",
    "unpack_bytes",
    "subtree_shapes",
    "ring_neighbor",
    "neighbor_exchange",
    "GradNoiseScaleState",
    "init_noise_scale",
    "update_noise_scale",
    "update_noise_scale_from_sq",
    "tree_sq_norm",
    "gradient_variance",
    "CounterState",
    "EMAState",
    "counter",
    "ema",
    "get_peer_latencies",
    "all_gather_latency_matrix",
    "minimum_spanning_tree",
    "neighbour_mask",
    "get_neighbour",
    "round_robin",
]


def __getattr__(name):
    # lazy: flash pulls in jax.experimental.pallas (+ the Mosaic stack),
    # which baseline collective/optimizer users should not pay for
    if name in ("flash_attention", "flash_plan", "flash_attention_flops"):
        from . import flash

        attr = getattr(flash, name)
        globals()[name] = attr  # cache: next lookup is direct
        return attr
    if name in ("paged_attention", "paged_plan", "paged_traffic_bytes"):
        from . import paged_attn

        attr = getattr(paged_attn, name)
        globals()[name] = attr
        return attr
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
