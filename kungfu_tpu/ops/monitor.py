"""Training-health monitors: gradient noise scale and gradient variance.

Pure-JAX restatements of the reference's monitoring ops (reference:
srcs/python/kungfu/tensorflow/ops/monitor.py:4-16 for the GNS estimator,
srcs/cpp/src/tensorflow/ops/cpu/collective.cpp NoiseScale kernel for the
EMA smoothing, and optimizers/grad_variance.py for the variance monitor).
The stateful C++ EMA kernel becomes an explicit JAX state dataclass so it
lives inside the jitted train step.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class GradNoiseScaleState(NamedTuple):
    """EMA state of the biased G/S estimators (bias-corrected like the
    reference's ExponentialMovingAverage, ema.hpp)."""

    g_ema: jnp.ndarray  # EMA of |G|^2 estimate
    s_ema: jnp.ndarray  # EMA of tr(Sigma) estimate
    count: jnp.ndarray  # update count for bias correction


def init_noise_scale() -> GradNoiseScaleState:
    z = jnp.zeros((), dtype=jnp.float32)
    return GradNoiseScaleState(g_ema=z, s_ema=z, count=z)


def _ema_update(ema, x, count, alpha):
    new = (1 - alpha) * ema + alpha * x
    corrected = new / (1 - (1 - alpha) ** (count + 1))
    return new, corrected


def update_noise_scale(
    state: GradNoiseScaleState,
    batch_small: float,
    batch_big: float,
    grad_local_fused: jnp.ndarray,
    grad_avg_fused: jnp.ndarray,
    alpha: float = 0.6,
    axis_name: str | None = None,
):
    """One GNS estimate from the (local grad, cluster-averaged grad) pair.

    `batch_small` is the device batch, `batch_big` the global batch; the
    pair of gradient norms gives unbiased estimators of |G|^2 and tr(Sigma)
    (GNS paper, "An Empirical Model of Large-Batch Training"), matching
    monitor.py:4-16 in the reference. With `axis_name`, the small-batch
    norm is averaged over the mesh axis so every worker tracks the same
    global estimate (the reference's per-worker estimates use one local
    norm sample each and therefore differ across workers).
    Returns (new_state, noise_scale).
    """
    return update_noise_scale_from_sq(
        state,
        batch_small,
        batch_big,
        g_sq_small=jnp.sum(jnp.square(grad_local_fused)),
        g_sq_big=jnp.sum(jnp.square(grad_avg_fused)),
        alpha=alpha,
        axis_name=axis_name,
    )


def tree_sq_norm(tree) -> jnp.ndarray:
    """Sum of squared entries across a pytree without materializing a fused
    copy (cheaper than fuse() + norm on the train-step hot path)."""
    leaves = jax.tree_util.tree_leaves(tree)
    total = jnp.zeros((), dtype=jnp.float32)
    for l in leaves:
        flat = jnp.ravel(l).astype(jnp.float32)
        total = total + jnp.vdot(flat, flat)
    return total


def update_noise_scale_from_sq(
    state: GradNoiseScaleState,
    batch_small: float,
    batch_big: float,
    g_sq_small: jnp.ndarray,
    g_sq_big: jnp.ndarray,
    alpha: float = 0.6,
    axis_name: str | None = None,
):
    """GNS update from precomputed squared gradient norms."""
    b_small = jnp.asarray(batch_small, dtype=jnp.float32)
    b_big = jnp.asarray(batch_big, dtype=jnp.float32)
    if axis_name is not None:
        g_sq_small = lax.pmean(g_sq_small, axis_name)
    # a 1-worker cluster (local run, or elastic shrink to one) has
    # batch_big == batch_small: the estimator is undefined, so freeze the
    # EMAs instead of poisoning them with NaN
    denom_ok = b_big > b_small
    safe = jnp.where(denom_ok, b_big - b_small, 1.0)
    g_biased = (b_big * g_sq_big - b_small * g_sq_small) / safe
    s_biased = (g_sq_small - g_sq_big) * b_small * b_big / safe

    g_new, g_corr = _ema_update(state.g_ema, g_biased, state.count, alpha)
    s_new, s_corr = _ema_update(state.s_ema, s_biased, state.count, alpha)
    noise_scale = s_corr / jnp.where(g_corr == 0, 1e-30, g_corr)
    new_state = GradNoiseScaleState(
        g_ema=g_new, s_ema=s_new, count=state.count + 1
    )
    new_state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(denom_ok, new, old), new_state, state
    )
    return new_state, jnp.where(denom_ok, noise_scale, 0.0)


def gradient_variance(grads, axis_name: str = "data") -> jnp.ndarray:
    """Summed per-tensor gradient variance across workers.

    For each tensor: Var = mean(g^2) - mean(g)^2 over the axis; the monitor
    value is sum_t ||Var_t|| (reference: grad_variance.py:45-60). Call
    inside shard_map with the per-worker gradients.
    """
    leaves = jax.tree_util.tree_leaves(grads)
    total = jnp.zeros((), dtype=jnp.float32)
    for g in leaves:
        g32 = g.astype(jnp.float32)
        mean_sq = lax.pmean(jnp.square(g32), axis_name)
        sq_mean = jnp.square(lax.pmean(g32, axis_name))
        total = total + jnp.linalg.norm(jnp.ravel(mean_sq - sq_mean))
    return total
