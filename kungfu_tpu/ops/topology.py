"""Latency-aware topology ops: MST, neighbour selection, round-robin.

Rebuild of the reference's topology/monitoring ops (reference:
srcs/cpp/src/tensorflow/ops/cpu/topology.cpp:6-187 — KungfuGetPeerLatencies,
KungfuMinimumSpanningTree, KungfuGetNeighbour, KungfuRoundRobin — and the
Prim's-MST template at srcs/cpp/include/kungfu/mst.hpp:9-58).

These run host-side on the control plane (latency is a DCN property, not an
ICI one): the peer latency vector is all-gathered over libkf, Prim's MST is
computed on the symmetrized latency matrix, and peer-selection helpers pick
gossip partners from the resulting tree. On TPU the *data plane* topology is
XLA's problem; these ops exist for the decentralized/async training family,
which picks DCN peers for model exchange.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def get_peer_latencies(peer) -> np.ndarray:
    """RTT vector (us, float64) from this peer to every peer; 0 for self."""
    return np.asarray(peer.latencies(), dtype=np.float64)


def all_gather_latency_matrix(peer) -> np.ndarray:
    """(np, np) matrix: row r = rank r's latency vector, agreed cluster-wide.

    Equivalent of the reference's AllGatherTransform over latency vectors
    (reference: session.cpp:115-134 + cpu/topology.cpp:40-108).
    """
    row = get_peer_latencies(peer)
    flat = peer.all_gather(row, name="kf_latency_matrix")
    return np.asarray(flat, dtype=np.float64).reshape(peer.size, peer.size)


def minimum_spanning_tree(weights: np.ndarray) -> np.ndarray:
    """Prim's MST over a symmetrized dense weight matrix.

    Returns an (n-1, 2) int32 edge list, matching the reference kernel's
    output contract (reference: mst.hpp:9-58, cpu/topology.cpp:60-108).
    """
    w = np.asarray(weights, dtype=np.float64)
    n = w.shape[0]
    if w.shape != (n, n):
        raise ValueError(f"weights must be square, got {w.shape}")
    if n <= 1:
        return np.zeros((0, 2), dtype=np.int32)
    sym = np.minimum(w, w.T)  # symmetrize: use the faster direction
    in_tree = np.zeros(n, dtype=bool)
    best_cost = np.full(n, np.inf)
    best_from = np.zeros(n, dtype=np.int64)
    in_tree[0] = True
    best_cost[1:] = sym[0, 1:]
    best_from[1:] = 0
    edges = np.zeros((n - 1, 2), dtype=np.int32)
    for k in range(n - 1):
        cand = np.where(~in_tree, best_cost, np.inf)
        v = int(np.argmin(cand))
        edges[k] = (best_from[v], v)
        in_tree[v] = True
        improve = ~in_tree & (sym[v] < best_cost)
        best_cost[improve] = sym[v][improve]
        best_from[improve] = v
    return edges


def neighbour_mask(edges: np.ndarray, n: int, rank: int) -> np.ndarray:
    """Bool mask of ranks adjacent to `rank` in the edge list
    (reference: KungfuGetNeighbour, cpu/topology.cpp:110-142)."""
    mask = np.zeros(n, dtype=bool)
    for a, b in np.asarray(edges).reshape(-1, 2):
        if a == rank:
            mask[int(b)] = True
        elif b == rank:
            mask[int(a)] = True
    return mask


def get_neighbour(peer, weights: Optional[np.ndarray] = None) -> List[int]:
    """Ranks adjacent to this peer in the latency MST."""
    if weights is None:
        weights = all_gather_latency_matrix(peer)
    edges = minimum_spanning_tree(weights)
    mask = neighbour_mask(edges, peer.size, peer.rank)
    return [int(r) for r in np.nonzero(mask)[0]]


def round_robin(mask: Sequence[bool], state: int = 0) -> Tuple[int, int]:
    """Pick the next True index after `state`, cycling.

    Returns (choice, next_state); choice is -1 when the mask is empty
    (reference: KungfuRoundRobin, cpu/topology.cpp:144-187).
    """
    mask = list(mask)
    n = len(mask)
    for off in range(1, n + 1):
        idx = (state + off) % n
        if mask[idx]:
            return idx, idx
    return -1, state
