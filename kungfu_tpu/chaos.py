"""Deterministic fault-schedule engine for the elastic runtime.

Every failure the test-suite injects — a worker SIGKILLed at step k, a
config server refusing or delaying requests, a dropped control message,
a corrupted checkpoint blob, a partitioned emulated host — is expressed
as a first-class **schedule** instead of ad-hoc subprocess killing
sprinkled through tests. A schedule is JSON, injected through the
environment (the same channel the KF_* bootstrap protocol already
uses), and is consulted at fixed hook points in the runtime:

- ``on_step(rank, step)``        — ElasticCallback.after_step
- ``on_http_request(path)``      — elastic/config_server handlers
- ``on_replica_request(path, replica, role)``
                                 — elastic/replica.py handlers
- ``on_wal_append(replica, append_idx)``
                                 — elastic/replica.py WAL appends
- ``on_control_send(name)``      — ffi.NativePeer.send_control
- ``on_spawn(rank)``             — run/job.spawn_worker

Hook points fire **deterministically**: faults match on exact
(rank, step) / (path, request index) / (name, send index) coordinates
and carry bounded trigger counts, so a chaos test replays the same
failure at the same place every run. The only randomness is the byte
positions of checkpoint corruption, drawn from the schedule's own seed.

Schedule format (``KF_CHAOS`` inline JSON, or ``KF_CHAOS_FILE`` path)::

    {"seed": 0, "faults": [
        {"type": "crash_worker", "rank": 1, "step": 5, "signal": "KILL"},
        {"type": "crash_host", "host": 1, "step": 5, "signal": "KILL"},
        {"type": "refuse_http", "path": "/put", "count": 3, "status": 503},
        {"type": "delay_http", "path": "/get", "ms": 200, "count": 2},
        {"type": "die_config_server", "after_requests": 10},
        {"type": "kill_config_replica", "role": "leader",
         "path": "/addworker"},
        {"type": "restart_config_replica", "role": "follower",
         "replica": 2, "after_requests": 20},
        {"type": "wal_enospc", "replica": 0, "after_appends": 5},
        {"type": "kill_router", "router": 0, "after_requests": 20},
        {"type": "drop_control", "name": "update", "count": 1},
        {"type": "delay_control", "name": "update", "ms": 100, "count": 2},
        {"type": "spawn_delay", "rank": 2, "ms": 500, "count": 1},
        {"type": "straggler_worker", "rank": 1, "from_step": 4,
         "to_step": 8, "ms": 120, "count": 5},
        {"type": "preempt_warning", "step": 6, "lead_steps": 2}
    ]}

``crash_host`` is whole-host spot reclamation: every rank whose
HOST index matches (first-seen order over the PeerList's distinct
IPv4s — `Peer.host_index`, identical on every rank's replica) kills
itself at the step, so one scheduled fault takes out the entire
colocated set — host master, leaves, and their shm rings — at one
step boundary. Survivors on other hosts detect via ring hello-EOF /
socket error and ride the survivor-recovery path
(docs/fault_tolerance.md "host death").

``straggler_worker`` models a slow host: the matching rank sleeps
``ms`` at every step boundary inside [from_step, to_step] (``count``
bounds the total firings per process — the scenario compiler sets it
to the window length). Each firing emits a ``chaos.straggler`` SPAN
(not an instant) so the goodput plane can attribute the other ranks'
collective wait to the straggler's sleep windows by overlap.
``preempt_warning`` is the spot-VM lead-time notice: an informational
marker + trace event `lead_steps` before a scheduled preemption —
policies and traces can see it coming; nothing destructive fires.

Every fault that fires prints one ``KF_CHAOS_FIRE`` marker line with a
wall-clock timestamp — the anchor the MTTR benchmark uses to measure
detection latency from the instant of death.

The reference project injects failures with docker-compose churn
scripts (reference: benchmarks/adaptation/gen-compose.py); the netns
fabric at the bottom of this module (`FakeNet`) is the
container-runtime-free equivalent used by the churn/partition tests.
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

ENV_INLINE = "KF_CHAOS"
ENV_FILE = "KF_CHAOS_FILE"

_KNOWN_TYPES = {
    "crash_worker",
    "crash_host",
    "refuse_http",
    "delay_http",
    "die_config_server",
    "kill_config_replica",
    "restart_config_replica",
    "wal_enospc",
    "kill_router",
    "drop_control",
    "delay_control",
    "spawn_delay",
    "straggler_worker",
    "preempt_warning",
}


@dataclass
class Fault:
    type: str
    spec: Dict = field(default_factory=dict)
    remaining: int = 1

    def matches(self, **coords) -> bool:
        """True when every coordinate the SCHEDULE pins agrees with the
        hook's coordinates; unpinned coordinates are wildcards."""
        if self.remaining == 0:
            return False
        for key, have in coords.items():
            want = self.spec.get(key)
            if want is not None and want != have:
                return False
        return True

    def consume(self) -> None:
        if self.remaining > 0:
            self.remaining -= 1


class ChaosSchedule:
    """A parsed fault schedule plus the per-process trigger state."""

    def __init__(self, spec: Dict):
        faults = spec.get("faults", [])
        if not isinstance(faults, list):
            raise ValueError("chaos schedule: 'faults' must be a list")
        self.seed = int(spec.get("seed", 0))
        self.faults: List[Fault] = []
        for f in faults:
            ftype = f.get("type")
            if ftype not in _KNOWN_TYPES:
                raise ValueError(f"chaos schedule: unknown fault type "
                                 f"{ftype!r} (known: {sorted(_KNOWN_TYPES)})")
            self.faults.append(Fault(
                type=ftype,
                spec=dict(f),
                remaining=int(f.get("count", 1)),
            ))
        self._lock = threading.Lock()
        # request index for die_config_server
        self._http_requests = 0  # kf: guarded_by(_lock)

    @classmethod
    def from_env(cls, environ=None) -> Optional["ChaosSchedule"]:
        e = os.environ if environ is None else environ
        raw = e.get(ENV_INLINE, "")
        if not raw and e.get(ENV_FILE):
            with open(e[ENV_FILE]) as fh:
                raw = fh.read()
        if not raw:
            return None
        return cls(json.loads(raw))

    def take(self, ftype: str, _when=None, **coords) -> Optional[Fault]:
        """Atomically claim the first matching, non-exhausted fault.
        ``_when`` (a predicate on the fault) gates the claim — used for
        conditions beyond coordinate equality, e.g. request-count
        thresholds."""
        with self._lock:
            for f in self.faults:
                if f.type == ftype and f.matches(**coords):
                    if _when is not None and not _when(f):
                        continue
                    f.consume()
                    return f
        return None

    def next_http_index(self) -> int:
        with self._lock:
            self._http_requests += 1
            return self._http_requests


# -- per-process engine state -------------------------------------------------

_sentinel = object()
#: hooks fire from the step loop, config-server handler threads and the
#: watcher at once; the lazy parse must install exactly one schedule
_mu = threading.Lock()
_active = _sentinel  # kf: guarded_by(_mu) — lazy; _reset() re-arms


def active() -> Optional[ChaosSchedule]:
    """The process-wide schedule (parsed once from the environment)."""
    global _active
    if _active is not _sentinel:
        return _active  # benign racy read: hooks see parsed-or-armed
    with _mu:
        if _active is _sentinel:
            try:
                _active = ChaosSchedule.from_env()
            except (ValueError, OSError, json.JSONDecodeError) as e:
                # a malformed schedule must not take the training job
                # down — chaos is a test instrument, not a production
                # dependency
                print(f"[kf-chaos] ignoring bad schedule: {e}",
                      flush=True)
                _active = None
        return _active


def load(spec: Optional[Dict]) -> Optional[ChaosSchedule]:
    """Install a schedule programmatically (tests); None disarms."""
    global _active
    with _mu:
        _active = ChaosSchedule(spec) if spec is not None else None
        return _active


def _reset() -> None:
    """Forget the cached schedule so the next hook re-reads the env."""
    global _active
    with _mu:
        _active = _sentinel


def _fire(ftype: str, **info) -> None:
    """Announce a fault: marker line + structured kftrace event. The
    event is emitted BEFORE any destructive action runs (the callers'
    contract) so a fault that takes this very process down is still in
    the ring when the flight recorder dumps — an MTTR decomposition
    can then anchor on the victim's own record instead of inferring
    the crash instant from survivor-side symptoms."""
    kv = " ".join(f"{k}={v}" for k, v in info.items())
    print(f"KF_CHAOS_FIRE t={time.time() * 1e3:.1f} type={ftype} {kv}",
          flush=True)
    from . import trace

    # fault coordinates may themselves be called `name`/`cat` (e.g.
    # drop_control name=update) — remap those so they cannot collide
    # with event()'s own parameters
    args = {("fault_" + k if k in ("name", "cat") else k): v
            for k, v in info.items()
            if isinstance(v, (int, float, str, bool))}
    trace.event(f"chaos.{ftype}", cat="chaos", **args)


# -- hook points --------------------------------------------------------------

def on_step(rank: int, step: int, host: Optional[int] = None) -> None:
    """ElasticCallback.after_step (entry): scheduled worker crashes,
    whole-host crashes and preemption warnings fire here. ``host`` is
    this rank's host index (`Peer.host_index`): every colocated rank
    passes the same value, so one ``crash_host`` fault SIGKILLs the
    entire emulated host at one step boundary."""
    sched = active()
    if sched is None:
        return
    f = sched.take("preempt_warning", rank=rank, step=step)
    if f is not None:
        # informational: the spot fabric's lead-time notice. Scheduled
        # at (preempt step - lead_steps) by the scenario compiler; the
        # trace records it so goodput timelines and policies can see
        # the preemption coming (docs/fault_tolerance.md).
        _fire("preempt_warning", rank=rank, step=step,
              lead_steps=int(f.spec.get("lead_steps", 0)))
    f = sched.take("crash_worker", rank=rank, step=step)
    ftype = "crash_worker"
    if f is None and host is not None:
        # host-scoped spot reclamation: each process consults its OWN
        # schedule replica, so every rank on the matching host consumes
        # its copy of the fault and dies at the same step boundary —
        # master, leaves, and their shm rings all at once
        f = sched.take("crash_host", host=host, step=step)
        ftype = "crash_host"
    if f is None:
        return
    sig = str(f.spec.get("signal", "KILL")).upper()
    _fire(ftype, rank=rank, step=step, signal=sig,
          **({"host": host} if ftype == "crash_host" else {}))
    # flight-record the ring BEFORE the destructive action: a SIGKILL
    # leaves no second chance, and the dump carries the chaos event
    # _fire just emitted — the crash instant, from the victim itself
    from . import trace

    trace.flight_dump(reason=f"chaos-{ftype}-{sig}")
    if sig == "EXIT":
        os._exit(int(f.spec.get("code", 41)))
    os.kill(os.getpid(), getattr(signal, f"SIG{sig}", signal.SIGKILL))


def on_step_end(rank: int, step: int) -> None:
    """ElasticCallback.after_step (exit): straggler sleeps fire here,
    AFTER the consensus round — a slow host is late to the *next*
    step's gradient all-reduce (benchmarks/straggler.py's shape), so
    its peers' wait shows up in their ``step.grad_wire`` spans, which
    is where the goodput plane and the straggler policies look.
    Sleeping at the entry hook instead would stall peers inside the
    resize consensus, misattributing the wait to the control plane."""
    sched = active()
    if sched is None:
        return
    f = sched.take(
        "straggler_worker", rank=rank,
        _when=lambda f: (int(f.spec.get("from_step", 0)) <= step
                         <= int(f.spec.get("to_step", 1 << 30))))
    if f is not None:
        ms = float(f.spec.get("ms", 100))
        # a SPAN, not the usual _fire instant: the sleep window is what
        # the goodput decomposition overlaps other ranks' collective
        # waits against (trace/goodput.py). The KF_CHAOS_FIRE marker
        # still prints so harness assertions see the fault.
        print(f"KF_CHAOS_FIRE t={time.time() * 1e3:.1f} "
              f"type=straggler_worker rank={rank} step={step} ms={ms}",
              flush=True)
        from . import trace

        rec = trace.recorder() if trace.enabled() else None
        t0_us = rec.now_us() if rec is not None else 0
        time.sleep(ms / 1e3)
        if rec is not None:
            trace.complete("chaos.straggler", t0_us,
                           rec.now_us() - t0_us, cat="chaos", ms=ms)


def on_http_request(path: str) -> Optional[Dict]:
    """Config-server handler hook. Returns the action to apply:
    ``{"refuse": status}``, ``{"delay_ms": ms}``, ``{"die": True}`` or
    None. Delay faults sleep HERE (inside the handler thread) so the
    caller sees real latency, not a fast error."""
    sched = active()
    if sched is None:
        return None
    idx = sched.next_http_index()
    f = sched.take(
        "die_config_server",
        _when=lambda f: idx >= int(f.spec.get("after_requests", 0)))
    if f is not None:
        _fire("die_config_server", request=idx)
        return {"die": True}
    return _http_action(sched, idx, path)


def on_replica_request(path: str, replica: int, role: str
                       ) -> Optional[Dict]:
    """elastic/replica.py handler hook: the single-server actions plus
    ``kill_config_replica`` — PERMANENT death (``{"kill": True}``; the
    victim never restarts), distinct from the restart-shaped
    ``die_config_server`` — and ``restart_config_replica`` — crash +
    relaunch-from-WAL (``{"restart": True}``: the victim loses all
    memory, replays its write-ahead log, rejoins ``behind`` and is
    repaired by the tier). Matched on the replica index and its role
    AT REQUEST TIME (``role: "leader"`` kills whoever currently holds
    the lease — the coordinate of interest for takeover tests, since
    election order decides which index that is). ONE request-index
    increment per request; tier-internal replication/vote traffic is
    intercepted before this hook fires, so a schedule's indices count
    client requests exactly as they do against a single server."""
    sched = active()
    if sched is None:
        return None
    idx = sched.next_http_index()
    f = sched.take(
        "kill_config_replica", path=path, replica=replica, role=role,
        _when=lambda f: idx >= int(f.spec.get("after_requests", 0)))
    if f is not None:
        _fire("kill_config_replica", path=path, replica=replica,
              role=role, request=idx)
        return {"kill": True}
    f = sched.take(
        "restart_config_replica", path=path, replica=replica,
        role=role,
        _when=lambda f: idx >= int(f.spec.get("after_requests", 0)))
    if f is not None:
        _fire("restart_config_replica", path=path, replica=replica,
              role=role, request=idx)
        return {"restart": True}
    return _http_action(sched, idx, path)


def on_wal_append(replica: int, append_idx: int) -> Optional[Dict]:
    """elastic/replica.py WAL-append hook: ``wal_enospc`` — the disk
    fills exactly at the ``after_appends``-th record of one replica's
    write-ahead log (``{"enospc": True}``; the replica raises a real
    ``OSError(ENOSPC)`` and must FAIL FAST, never ack an unpersisted
    write). Matched against the WAL's OWN record counter (passed in as
    ``append_idx``) — append cadence is commit-window-dependent, so it
    must not advance the shared HTTP request index that
    ``after_requests`` schedules are pinned to."""
    sched = active()
    if sched is None:
        return None
    f = sched.take(
        "wal_enospc", replica=replica,
        _when=lambda f: append_idx >= int(
            f.spec.get("after_appends", 0)))
    if f is not None:
        _fire("wal_enospc", replica=replica, append=append_idx)
        return {"enospc": True}
    return None


def on_router_request(path: str, router: int,
                      request_idx: int) -> Optional[Dict]:
    """serve/router.py handler hook: ``kill_router`` — PERMANENT death
    of one admission router (``{"kill": True}``), the front-door
    analogue of ``kill_config_replica``. Matched on the router index
    and an ``after_requests`` threshold against the ROUTER'S OWN
    request counter (passed in as ``request_idx``): router traffic is
    serve-plane and workload-dependent, so it must not advance the
    shared control-plane request index that ``after_requests``
    schedules for config servers are pinned to."""
    sched = active()
    if sched is None:
        return None
    f = sched.take(
        "kill_router", path=path, router=router,
        _when=lambda f: request_idx >= int(
            f.spec.get("after_requests", 0)))
    if f is not None:
        _fire("kill_router", path=path, router=router,
              request=request_idx)
        return {"kill": True}
    return None


def _http_action(sched: ChaosSchedule, idx: int,
                 path: str) -> Optional[Dict]:
    """delay/refuse logic shared by both HTTP hooks — factored out so
    each hook claims exactly one request index (a double increment
    would shift every `after_requests` threshold in the schedule)."""
    # `after_requests` (optional, default 0 = immediately) arms a
    # delay/refuse fault only from that request index on — the knob
    # the scenario compiler lowers a step coordinate to (~1 GET per
    # step per rank), so a mid-run control-plane flap starts mid-run
    # instead of at boot
    f = sched.take(
        "delay_http", path=path,
        _when=lambda f: idx >= int(f.spec.get("after_requests", 0)))
    if f is not None:
        ms = float(f.spec.get("ms", 100))
        _fire("delay_http", path=path, ms=ms, request=idx)
        time.sleep(ms / 1e3)
        return {"delay_ms": ms}
    f = sched.take(
        "refuse_http", path=path,
        _when=lambda f: idx >= int(f.spec.get("after_requests", 0)))
    if f is not None:
        status = int(f.spec.get("status", 503))
        _fire("refuse_http", path=path, status=status, request=idx)
        return {"refuse": status}
    return None


def on_control_send(name: str) -> str:
    """ffi.send_control hook: 'drop' to swallow the message, 'send' to
    proceed (after any scheduled delay)."""
    sched = active()
    if sched is None:
        return "send"
    f = sched.take("drop_control", name=name)
    if f is not None:
        _fire("drop_control", name=name)
        return "drop"
    f = sched.take("delay_control", name=name)
    if f is not None:
        ms = float(f.spec.get("ms", 100))
        _fire("delay_control", name=name, ms=ms)
        time.sleep(ms / 1e3)
    return "send"


def on_spawn(rank: Optional[int]) -> None:
    """run/job.spawn_worker hook: scheduled joiner-spawn delay (models a
    slow host answering a grow proposal)."""
    sched = active()
    if sched is None:
        return
    f = sched.take("spawn_delay", rank=rank)
    if f is not None:
        ms = float(f.spec.get("ms", 100))
        _fire("spawn_delay", rank=rank, ms=ms)
        time.sleep(ms / 1e3)


def corrupt_file(path: str, nbytes: int = 8,
                 seed: Optional[int] = None) -> List[int]:
    """Flip ``nbytes`` bytes of a blob at schedule-seeded offsets — the
    "corrupt a checkpoint" fault. Returns the corrupted offsets so a
    test can assert determinism. The checkpoint loader is expected to
    FAIL LOUDLY on such a file (np.load CRC) — recovery then falls back
    to the live resync path instead of restoring garbage."""
    if seed is None:
        sched = active()
        seed = sched.seed if sched is not None else 0
    size = os.path.getsize(path)
    if size == 0:
        return []
    rng = random.Random(seed)
    # DISTINCT offsets: sampling with replacement could XOR one byte an
    # even number of times and hand back a byte-identical "corrupt" file
    offsets = sorted(rng.sample(range(size), min(nbytes, size)))
    with open(path, "r+b") as f:
        for off in offsets:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
    _fire("corrupt_checkpoint", path=path, nbytes=nbytes, seed=seed)
    return offsets


#: the three ways a sharded checkpoint generation can rot on disk
#: (kungfu_tpu/checkpoint_async.py layout); each must make restore
#: fail loudly or fall back to the previous COMPLETE generation —
#: never silently load a mix (tests/test_chaos.py holds it to that)
SHARDED_CORRUPTIONS = ("torn_shard", "missing_shard",
                      "mismatch_manifest")


def corrupt_sharded_generation(gen_dir: str, mode: str,
                               seed: Optional[int] = None) -> str:
    """Deterministically damage one sharded checkpoint generation.

    ``torn_shard`` truncates a schedule-seeded shard file to a seeded
    fraction (the power-loss-mid-write shape); ``missing_shard``
    deletes one (a lost disk / partial copy); ``mismatch_manifest``
    rewrites one rank's manifest piece with a different step (a stale
    piece surviving from an older attempt). The victim file and the
    torn length derive from the seed alone, so a failing chaos test
    replays byte-identically. Returns the damaged path."""
    import glob as _glob

    if mode not in SHARDED_CORRUPTIONS:
        raise ValueError(f"unknown sharded corruption {mode!r} "
                         f"(known: {SHARDED_CORRUPTIONS})")
    if seed is None:
        sched = active()
        seed = sched.seed if sched is not None else 0
    rng = random.Random(seed)
    if mode == "mismatch_manifest":
        victims = sorted(_glob.glob(os.path.join(gen_dir,
                                                 "manifest-r*.json")))
    else:
        victims = sorted(_glob.glob(os.path.join(gen_dir,
                                                 "shard-r*.bin")))
        if mode == "torn_shard":
            # an incremental generation legitimately leaves 0-byte
            # shards (a rank whose owned leaves were all unchanged);
            # tearing one would be a silent no-op that still FIRES —
            # a fault the schedule claims but never injected
            victims = [v for v in victims if os.path.getsize(v) > 0]
    if not victims:
        raise FileNotFoundError(
            f"no {mode} victim files under {gen_dir}")
    path = victims[rng.randrange(len(victims))]
    if mode == "torn_shard":
        size = os.path.getsize(path)
        keep = rng.randrange(size)  # strictly shorter
        with open(path, "r+b") as f:
            f.truncate(keep)
        _fire("torn_shard", path=path, kept=keep, seed=seed)
    elif mode == "missing_shard":
        os.unlink(path)
        _fire("missing_shard", path=path, seed=seed)
    else:
        with open(path) as f:
            piece = json.load(f)
        piece["step"] = int(piece.get("step", 0)) + 1  # stale piece
        with open(path, "w") as f:
            json.dump(piece, f)
        _fire("mismatch_manifest", path=path, seed=seed)
    return path


#: the two ways a control-plane WAL directory (elastic/wal.py layout)
#: can rot on disk; each must be DETECTED at replay — torn_tail
#: truncates loudly at the last good checksum, stale_snapshot refuses
#: the log and rejoins `behind` for peer repair — never replayed as
#: silently regressed state (tests/test_control_plane.py holds it)
WAL_CORRUPTIONS = ("torn_tail", "stale_snapshot")


def corrupt_wal(wal_dir: str, mode: str,
                seed: Optional[int] = None) -> str:
    """Deterministically damage one replica's write-ahead log.

    ``torn_tail`` cuts ``wal.log`` mid-record at a schedule-seeded
    offset strictly inside the LAST record (the power-loss-mid-append
    shape: earlier records stay valid, the tail fails its checksum);
    ``stale_snapshot`` rewrites the snapshot's seq stamp to a seeded
    smaller value (an old file swapped back in: the log's first op no
    longer meets the stamp, so replaying the hybrid would silently
    regress state). The cut point and the regressed stamp derive from
    the seed alone, so a failing chaos test replays byte-identically.
    Returns the damaged path."""
    from .elastic import wal as wal_mod

    if mode not in WAL_CORRUPTIONS:
        raise ValueError(f"unknown WAL corruption {mode!r} "
                         f"(known: {WAL_CORRUPTIONS})")
    if seed is None:
        sched = active()
        seed = sched.seed if sched is not None else 0
    rng = random.Random(seed)
    if mode == "torn_tail":
        path = os.path.join(wal_dir, wal_mod.LOG_FILE)
        # find the last record's start by walking the length prefixes
        with open(path, "rb") as f:
            data = f.read()
        hdr = wal_mod._HEADER
        off = last = 0
        while off + hdr <= len(data):
            (length,) = wal_mod._LEN.unpack_from(data, off)
            if off + hdr + length > len(data):
                break
            last = off
            off += hdr + length
        if off == 0:
            raise FileNotFoundError(f"no records to tear in {path}")
        # cut strictly inside the last record: keep at least one byte
        # of it (so there IS a torn tail) and drop at least one
        keep = last + 1 + rng.randrange(off - last - 1)
        with open(path, "r+b") as f:
            f.truncate(keep)
        _fire("torn_tail", path=path, kept=keep, seed=seed)
    else:
        path = os.path.join(wal_dir, wal_mod.SNAP_FILE)
        with open(path) as f:
            snap = json.load(f)
        seq = int(snap.get("seq", 0))
        if seq <= 0:
            raise ValueError(f"snapshot {path} has no seq to regress")
        snap["seq"] = rng.randrange(seq)  # strictly older stamp
        with open(path, "w") as f:
            json.dump(snap, f)
        _fire("stale_snapshot", path=path, old_seq=seq,
              new_seq=snap["seq"], seed=seed)
    return path


# -- netns fault fabric -------------------------------------------------------

_NETNS_CAPABLE: Optional[bool] = None


def netns_capable() -> bool:
    """True when this environment can create network namespaces with
    veth pairs AND the veth link state is actually honored (root +
    CAP_NET_ADMIN; denied in most unprivileged CI sandboxes, granted in
    the dev container).

    The link-state check matters: some sandboxed kernels (gVisor-style)
    report `ip netns add` / `ip link set ... down` success, yet keep
    delivering packets across the administratively-down link — a veth
    partition is then a silent no-op and every fault these namespaces
    back would pass vacuously. The probe downs one end of a fresh veth
    pair and tries to connect across it: a real stack has no route any
    more (ENETUNREACH/EHOSTUNREACH, or a timeout where only the route
    survives); a stack that ignores link state delivers the SYN and
    fails ECONNREFUSED — or even connects. The (~2 s) verdict is cached
    per process."""
    global _NETNS_CAPABLE
    if _NETNS_CAPABLE is None:
        _NETNS_CAPABLE = _probe_netns()
    return _NETNS_CAPABLE


def _probe_netns() -> bool:
    import sys
    tag = f"{os.getpid() % 10000}"
    ns_a, ns_b = f"kfcapchk{tag}a", f"kfcapchk{tag}b"
    veth_a, veth_b = f"kfcpk{tag}a", f"kfcpk{tag}b"
    try:
        r = subprocess.run(["unshare", "-n", "true"], timeout=10,
                           capture_output=True)
        if r.returncode != 0:
            return False
        for ns in (ns_a, ns_b):
            if subprocess.run(["ip", "netns", "add", ns], timeout=10,
                              capture_output=True).returncode != 0:
                return False
        r = subprocess.run(["ip", "link", "add", veth_a, "type", "veth",
                            "peer", "name", veth_b], timeout=10,
                           capture_output=True)
        if r.returncode != 0:
            return False
        _ip("link", "set", veth_a, "netns", ns_a)
        _ip("link", "set", veth_b, "netns", ns_b)
        _ip("-n", ns_a, "addr", "add", "10.254.77.1/24", "dev", veth_a)
        _ip("-n", ns_b, "addr", "add", "10.254.77.2/24", "dev", veth_b)
        _ip("-n", ns_a, "link", "set", veth_a, "up")
        _ip("-n", ns_b, "link", "set", veth_b, "up")
        _ip("-n", ns_a, "link", "set", veth_a, "down")
        r = subprocess.run(
            ["ip", "netns", "exec", ns_a, sys.executable, "-c",
             "import errno, socket, sys\n"
             "try:\n"
             "    socket.create_connection(('10.254.77.2', 9), timeout=3)\n"
             "    sys.exit(1)  # connected across a DOWNED link\n"
             "except socket.timeout:\n"
             "    sys.exit(0)  # silence: link state honored\n"
             "except OSError as e:\n"
             "    ok = e.errno in (errno.ENETUNREACH, errno.EHOSTUNREACH)\n"
             "    sys.exit(0 if ok else 1)\n"],
            timeout=20, capture_output=True)
        return r.returncode == 0
    except (OSError, subprocess.SubprocessError):
        return False
    finally:
        # the veth pair only dies with the netns AFTER the move into it;
        # a failure between 'link add' and the move would leave it in the
        # root namespace and poison every later probe with 'File exists'
        subprocess.run(["ip", "link", "del", veth_a], timeout=10,
                       capture_output=True)
        for ns in (ns_a, ns_b):
            subprocess.run(["ip", "netns", "del", ns], timeout=10,
                           capture_output=True)


def _ip(*args: str, check: bool = True) -> subprocess.CompletedProcess:
    r = subprocess.run(["ip", *args], capture_output=True, text=True,
                       timeout=15)
    if check and r.returncode != 0:
        raise RuntimeError(f"ip {' '.join(args)}: {r.stderr}")
    return r


@dataclass
class FakeHost:
    name: str
    ns: str
    ip: str
    veth_host: str  # bridge side
    veth_ns: str    # namespace side


class FakeNet:
    """N netns-backed fake hosts joined by one bridge — the
    container-free stand-in for the reference's docker-compose cluster
    (reference: benchmarks/adaptation/gen-compose.py). Hosts can be
    added and removed while the cluster runs (churn), and any host can
    be partitioned (link down, process tree stays alive) and healed.

    Each host gets an /etc/hosts-style name through
    ``publish_etc_hosts`` so hostname discovery (`run/discovery.py`)
    resolves fake hosts the way orchestrator DNS would."""

    def __init__(self, tag: str, subnet: str = "10.77.40"):
        self.tag = tag
        self.subnet = subnet
        self.bridge = f"br{tag}"[:15]
        self.hosts: Dict[str, FakeHost] = {}
        self._next = 1
        _ip("link", "add", self.bridge, "type", "bridge")
        _ip("link", "set", self.bridge, "up")
        _ip("addr", "add", f"{subnet}.254/24", "dev", self.bridge)

    def add_host(self, name: str) -> FakeHost:
        i = self._next
        self._next += 1
        ns = f"{self.tag}{name}"[:15]
        veth_h = f"vh{self.tag}{i}"[:15]
        veth_n = f"vn{self.tag}{i}"[:15]
        ip_addr = f"{self.subnet}.{i}"
        _ip("netns", "add", ns)
        _ip("-n", ns, "link", "set", "lo", "up")
        _ip("link", "add", veth_h, "type", "veth", "peer", "name", veth_n)
        _ip("link", "set", veth_h, "master", self.bridge)
        _ip("link", "set", veth_h, "up")
        _ip("link", "set", veth_n, "netns", ns)
        _ip("-n", ns, "addr", "add", f"{ip_addr}/24", "dev", veth_n)
        _ip("-n", ns, "link", "set", veth_n, "up")
        host = FakeHost(name=name, ns=ns, ip=ip_addr,
                        veth_host=veth_h, veth_ns=veth_n)
        self.hosts[name] = host
        return host

    def remove_host(self, name: str) -> None:
        host = self.hosts.pop(name)
        subprocess.run(["ip", "netns", "del", host.ns],
                       capture_output=True, timeout=15)

    def partition(self, name: str) -> None:
        """Drop the host's uplink: alive but unreachable (a PARTITION,
        distinct from a crash — the process tree keeps running)."""
        _fire("partition_host", host=name)
        _ip("link", "set", self.hosts[name].veth_host, "down")

    def heal(self, name: str) -> None:
        _fire("heal_host", host=name)
        _ip("link", "set", self.hosts[name].veth_host, "up")

    def exec_prefix(self, name: str) -> List[str]:
        """argv prefix running a command inside the fake host."""
        return ["ip", "netns", "exec", self.hosts[name].ns]

    def publish_etc_hosts(self) -> None:
        """Write every live host's name→IP into /etc/netns/<ns>/hosts:
        `ip netns exec` bind-mounts those files over /etc inside the
        namespace, so HOSTNAME discovery (`run/discovery.py`) resolves
        fake hosts exactly the way orchestrator DNS would. Call again
        after add_host/remove_host to refresh every view."""
        lines = "".join(f"{h.ip} {h.name}\n"
                        for h in sorted(self.hosts.values(),
                                        key=lambda h: h.name))
        for h in self.hosts.values():
            d = f"/etc/netns/{h.ns}"
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "hosts"), "w") as fh:
                fh.write("127.0.0.1 localhost\n" + lines)

    def cleanup(self) -> None:
        import shutil

        for name in list(self.hosts):
            ns = self.hosts[name].ns
            self.remove_host(name)
            shutil.rmtree(f"/etc/netns/{ns}", ignore_errors=True)
        subprocess.run(["ip", "link", "del", self.bridge],
                       capture_output=True, timeout=15)
