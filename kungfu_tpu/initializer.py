"""Model-state broadcast at (re)initialization time.

Rebuild of the reference's initializer family (reference:
srcs/python/kungfu/tensorflow/initializer/__init__.py — the
BroadcastGlobalVariables Op/Hook/Callback forms): every worker must start
from rank 0's weights, and joiners after an elastic resize must adopt the
survivors' weights.

Two paths, mirroring the framework's two planes:

- `broadcast_variables(tree, peer)` — host-side DCN broadcast over libkf.
  Used at process start and at elastic epoch switches, when workers are
  separate processes and the ICI mesh may not exist yet. The pytree is
  packed into one flat byte buffer (the reference fuses variables the same
  way, ops/__init__.py:22-39) so the broadcast is a single named message
  per epoch rather than one per tensor.
- `kungfu_tpu.parallel.broadcast_params` — in-mesh ICI broadcast for
  device-sharded state (already compiled into the SPMD program).
"""

from __future__ import annotations

from .ops.collective import pack_bytes, unpack_bytes


def broadcast_variables(tree, peer=None, root: int = 0, name: str = "kf_bcast_vars"):
    """Broadcast a pytree of arrays from `root` over the control plane.

    Returns the tree every rank agrees on (root's values). No-op for
    single-worker clusters. Rides the chunked streaming pipeline
    (`elastic.streaming.stream_broadcast`) — zero-copy leaf views,
    packing overlapped with the wire — unless KF_STREAM_CHUNK_MB=0
    pins the monolithic pack_bytes path.
    """
    if peer is None:
        from . import peer as _default
        peer = _default()
    if peer.size <= 1:
        return tree
    from .elastic.streaming import stream_broadcast, stream_chunk_bytes

    chunk_bytes = stream_chunk_bytes()
    if chunk_bytes > 0:
        out, _ = stream_broadcast(peer, tree, root=root,
                                  chunk_bytes=chunk_bytes, name=name)
        return out
    buf = pack_bytes(tree)
    out = peer.broadcast(buf, root=root, name=name)
    return unpack_bytes(out, tree)


class BroadcastGlobalVariablesCallback:
    """Keras-style callback form: broadcast once after the first batch.

    The reference defers the TF2 broadcast to after the first trained batch
    so optimizer slots exist (initializer/__init__.py:65-90); here the same
    hook shape lets training loops sync params+opt-state lazily.
    """

    def __init__(self, peer=None, root: int = 0):
        self.peer = peer
        self.root = root
        self._done = False

    def on_batch_end(self, tree):
        if self._done:
            return tree
        self._done = True
        return broadcast_variables(tree, peer=self.peer, root=self.root)
