/* libkf — TPU-native DCN control plane for kungfu-tpu.
 *
 * This is the C API consumed by Python via ctypes. It provides the
 * runtime the reference implements in Go (reference: srcs/go/rchannel,
 * srcs/go/kungfu/{peer,session}, srcs/go/store): framed named messages over
 * TCP, an epoch-token-fenced peer lifecycle, graph-based CPU collectives,
 * digest consensus, a named blob store with a versioned window, and P2P
 * blob request/response. The TPU *data plane* (gradient all-reduce) lives
 * in XLA/ICI and never touches this library; this is the control plane for
 * elasticity, consensus, model exchange across DCN, and non-TPU testing.
 *
 * Thread-safety: all functions on a kf_peer are safe to call from multiple
 * threads; collectives on distinct names may run concurrently.
 * All blocking calls honor the timeout configured at peer creation.
 */
#ifndef KF_H
#define KF_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct kf_peer kf_peer;

/* dtype codes (wire + kernel) */
enum {
    KF_U8 = 0,
    KF_I8 = 1,
    KF_U16 = 2,
    KF_I16 = 3,
    KF_U32 = 4,
    KF_I32 = 5,
    KF_U64 = 6,
    KF_I64 = 7,
    KF_F16 = 8,
    KF_BF16 = 9,
    KF_F32 = 10,
    KF_F64 = 11,
};

/* reduce op codes. KF_SUM_SAT is the compressed-gradient accumulate:
 * integer dtypes clamp at the dtype bounds instead of wrapping (the sum
 * of int8-quantized gradient shards must degrade to clipping — absorbed
 * by error feedback — never to sign-flipped wraparound); float dtypes
 * behave exactly like KF_SUM (they already saturate at +/-inf). */
enum { KF_SUM = 0, KF_MIN = 1, KF_MAX = 2, KF_PROD = 3, KF_SUM_SAT = 4 };

/* all-reduce topology strategies */
enum {
    KF_STRATEGY_STAR = 0,
    KF_STRATEGY_RING = 1,
    KF_STRATEGY_CLIQUE = 2,
    KF_STRATEGY_TREE = 3,
    KF_STRATEGY_BINARY_TREE = 4,
    KF_STRATEGY_BINARY_TREE_STAR = 5,
    KF_STRATEGY_MULTI_BINARY_TREE_STAR = 6,
    KF_STRATEGY_AUTO = 7,
};

/* error codes (negative returns) */
enum {
    KF_OK = 0,
    KF_ERR = -1,          /* generic failure */
    KF_ERR_TIMEOUT = -2,  /* blocking op timed out */
    KF_ERR_EPOCH = -3,    /* stale epoch token rejected */
    KF_ERR_CONN = -4,     /* cannot establish connection */
    KF_ERR_NOTFOUND = -5, /* P2P request: blob absent on responder */
    KF_ERR_ARG = -6,      /* invalid argument */
    KF_ERR_CORRUPT = -7,  /* wire-frame integrity violation (torn or
                           * corrupted shm-ring frame): the payload is
                           * untrusted and the channel is dead — callers
                           * must treat it like a peer death (recover),
                           * never deserialize the bytes */
};

/* --- lifecycle ---------------------------------------------------------- */

/* self_spec: "ip:port"; peers: comma-separated "ip:port" rank list (must
 * contain self); version: initial cluster epoch; strategy: KF_STRATEGY_*.
 * timeout_ms: per-blocking-op timeout (0 = no timeout). */
kf_peer *kf_peer_new(const char *self_spec, const char *peers,
                     uint32_t version, int strategy, int64_t timeout_ms);
int kf_peer_start(kf_peer *);                 /* start server threads */
int kf_peer_stop(kf_peer *);                  /* stop + join */
void kf_peer_free(kf_peer *);

/* Switch to a new membership epoch: bump token, drop connections to peers
 * not in the new list, rebuild the session. Does NOT barrier — callers
 * barrier explicitly once all peers updated. */
int kf_peer_update(kf_peer *, const char *peers, uint32_t version);

int kf_rank(kf_peer *);
int kf_size(kf_peer *);
int kf_local_rank(kf_peer *);
int kf_local_size(kf_peer *);
uint32_t kf_version(kf_peer *);
uint64_t kf_uid(kf_peer *);

/* --- collectives (control plane, CPU buffers) --------------------------- */

int kf_barrier(kf_peer *);
int kf_all_reduce(kf_peer *, const void *send, void *recv, int64_t count,
                  int dtype, int op, const char *name);
int kf_reduce(kf_peer *, const void *send, void *recv, int64_t count,
              int dtype, int op, int root, const char *name);
int kf_broadcast(kf_peer *, const void *send, void *recv, int64_t count,
                 int dtype, int root, const char *name);
int kf_gather(kf_peer *, const void *send, int64_t count, void *recv,
              int64_t total_count, int dtype, int root, const char *name);
int kf_all_gather(kf_peer *, const void *send, int64_t count, void *recv,
                  int dtype, const char *name);
/* returns 1 if all peers passed identical bytes, 0 if divergent, <0 error */
int kf_consensus(kf_peer *, const void *data, int64_t n, const char *name);

/* --- named blob store + P2P -------------------------------------------- */

int kf_save(kf_peer *, const char *name, const void *data, int64_t n);
int kf_save_version(kf_peer *, const char *version, const char *name,
                    const void *data, int64_t n);
/* Fetch blob `name` from peer at `rank`; out must hold n bytes. */
int kf_request(kf_peer *, int rank, const char *name, void *out, int64_t n);
int kf_request_version(kf_peer *, int rank, const char *version,
                       const char *name, void *out, int64_t n);

/* --- control channel ---------------------------------------------------- */

/* Handler invoked (on a server thread) for every Control message received. */
typedef void (*kf_control_cb)(void *user, const char *name, const void *data,
                              int64_t n);
int kf_set_control_handler(kf_peer *, kf_control_cb cb, void *user);
/* Send a control message to an arbitrary address (e.g. a runner). */
int kf_send_control(kf_peer *, const char *dest_spec, const char *name,
                    const void *data, int64_t n);

/* --- order group --------------------------------------------------------- */

/* Executes N async tasks in a scheduled order regardless of arrival order,
 * recording actual arrival order (the reference's gradient-ordering engine;
 * here it serializes host-side async control-plane ops so all ranks issue
 * named collectives in the same order). Independent of any kf_peer. */
typedef struct kf_order_group kf_order_group;
typedef void (*kf_task_cb)(void *user);

/* exec_order: permutation of 0..n-1 (position -> rank), or NULL for rank
 * order. */
kf_order_group *kf_order_group_new(int n, const int *exec_order);
/* Register task `rank` for this cycle; cb(user) runs on the executor
 * thread in scheduled order. Returns KF_ERR_ARG on bad/duplicate rank. */
int kf_order_group_start(kf_order_group *, int rank, kf_task_cb cb,
                         void *user);
/* Block until all n tasks ran; writes the arrival order (n ints, element i
 * = rank that arrived i-th) into arrival_out if non-NULL, then resets for
 * the next cycle. Returns KF_ERR (arrival_out untouched) if a concurrent
 * wait consumed this cycle's order first. */
int kf_order_group_wait(kf_order_group *, int *arrival_out);
void kf_order_group_free(kf_order_group *);

/* --- monitoring --------------------------------------------------------- */

int kf_ping(kf_peer *, int rank, int64_t *rtt_us); /* RTT to peer */
void kf_stats(kf_peer *, uint64_t *egress_bytes, uint64_t *ingress_bytes);
/* Cumulative payload bytes per wire link class, for the link-class
 * byte attribution of kf_wire_bytes_total{link=...}: out[0..2] =
 * egress over {tcp, unix, shm}, out[3..5] = ingress over the same.
 * The kf_stats totals are always the sum of the classes. */
void kf_link_stats(kf_peer *, uint64_t out[6]);
/* How many per-pair shm channels degraded to the socket path this
 * epoch-lifetime (attach/ENOSPC/hello failures; cumulative across
 * epochs). Feeds kf_link_fallback_total on /metrics — the loud twin of
 * KF_SHM_REQUIRE=1, which turns the degradation into an error. */
uint64_t kf_shm_fallback_total(kf_peer *);
/* 1 when the current session walks hierarchical (KF_HIER=1) graphs:
 * intra-host reduce -> inter-host strategy over host masters ->
 * intra-host broadcast, re-derived from the peer list on every epoch
 * switch. 0 = flat strategy graphs. */
int kf_hier(kf_peer *);

/* --- reduce kernels ------------------------------------------------------ */

/* Elementwise dst[i] = dst[i] (op) src[i] on host buffers — the kernel the
 * collectives accumulate with, exported for tests and microbenchmarks.
 * force_scalar=1 bypasses the AVX2/F16C dispatch; both paths produce
 * bit-identical results. Returns KF_OK / KF_ERR_ARG. */
int kf_accumulate(void *dst, const void *src, int64_t count, int dtype,
                  int op, int force_scalar);
/* 1 if this process will use SIMD kernels for the given dtype, else 0. */
int kf_simd_enabled(int dtype);

/* --- tracing ------------------------------------------------------------- */

/* Scoped timers around libkf hot paths (send / dial / recv_wait /
 * accumulate / collective), enabled by KF_TRACE=1 in the environment.
 * Fills `buf` with "scope count total_us max_us" lines (NUL-terminated,
 * truncated at cap-1) and returns the bytes written; 0 when tracing is
 * off or nothing has been recorded yet. Process-global. */
int64_t kf_trace_report(char *buf, int64_t cap);
void kf_trace_reset(void);
/* 1 when KF_TRACE was set at first use, else 0. */
int kf_trace_enabled(void);

/* library version string */
const char *kf_version_string(void);

#ifdef __cplusplus
}
#endif

#endif /* KF_H */
