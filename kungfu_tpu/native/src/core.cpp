#include "core.hpp"

#include "trace.hpp"

#include <cstdarg>
#include <cstdlib>
#include <ctime>
#include <limits>
#include <mutex>
#include <type_traits>

#include "halffloat.hpp"

namespace kf {

// ---------------------------------------------------------------- logging

LogLevel log_level() {
    static LogLevel lvl = [] {
        const char *e = std::getenv("KF_LOG_LEVEL");
        if (!e) return LogLevel::warn;
        std::string s(e);
        if (s == "debug") return LogLevel::debug;
        if (s == "info") return LogLevel::info;
        if (s == "error") return LogLevel::error;
        return LogLevel::warn;
    }();
    return lvl;
}

void log_at(LogLevel lvl, const char *fmt, ...) {
    if (lvl < log_level()) return;
    static std::mutex mu;
    std::lock_guard<std::mutex> lk(mu);
    static const char *names[] = {"DEBUG", "INFO", "WARN", "ERROR"};
    std::fprintf(stderr, "[kf:%s] ", names[int(lvl)]);
    va_list ap;
    va_start(ap, fmt);
    std::vfprintf(stderr, fmt, ap);
    va_end(ap);
    std::fputc('\n', stderr);
}

// ----------------------------------------------------------------- dtypes

size_t dtype_size(Dtype dt) {
    switch (dt) {
        case Dtype::u8:
        case Dtype::i8:
            return 1;
        case Dtype::u16:
        case Dtype::i16:
        case Dtype::f16:
        case Dtype::bf16:
            return 2;
        case Dtype::u32:
        case Dtype::i32:
        case Dtype::f32:
            return 4;
        default:
            return 8;
    }
}

namespace {

// Saturating integer add: clamp at the dtype bounds instead of wrapping.
// Quantized-gradient sums must degrade to clipping (absorbed by the
// error-feedback residual) — a wrapped sum flips the gradient's sign.
template <typename T>
inline T sat_add(T a, T b) {
    T r;
    if (!__builtin_add_overflow(a, b, &r))
        return r;
    return b > 0 ? std::numeric_limits<T>::max()
                 : std::numeric_limits<T>::min();
}

template <typename T>
void accumulate_typed(T *dst, const T *src, int64_t n, ROp op) {
    switch (op) {
        case ROp::sum:
            for (int64_t i = 0; i < n; i++) dst[i] = T(dst[i] + src[i]);
            break;
        case ROp::sum_sat:
            if constexpr (std::is_integral<T>::value) {
                for (int64_t i = 0; i < n; i++)
                    dst[i] = sat_add(dst[i], src[i]);
            } else {
                // floats saturate at +/-inf already: identical to sum
                for (int64_t i = 0; i < n; i++)
                    dst[i] = T(dst[i] + src[i]);
            }
            break;
        case ROp::min:
            for (int64_t i = 0; i < n; i++)
                dst[i] = src[i] < dst[i] ? src[i] : dst[i];
            break;
        case ROp::max:
            for (int64_t i = 0; i < n; i++)
                dst[i] = src[i] > dst[i] ? src[i] : dst[i];
            break;
        case ROp::prod:
            for (int64_t i = 0; i < n; i++) dst[i] = T(dst[i] * src[i]);
            break;
    }
}

template <float (*FromBits)(uint16_t), uint16_t (*ToBits)(float)>
void accumulate_16bit_float(uint16_t *dst, const uint16_t *src, int64_t n,
                            ROp op) {
    for (int64_t i = 0; i < n; i++) {
        float a = FromBits(dst[i]), b = FromBits(src[i]), r;
        switch (op) {
            case ROp::sum:
            case ROp::sum_sat:  // floats saturate at +/-inf already
                r = a + b;
                break;
            case ROp::min:
                r = b < a ? b : a;
                break;
            case ROp::max:
                r = b > a ? b : a;
                break;
            default:
                r = a * b;
                break;
        }
        dst[i] = ToBits(r);
    }
}

}  // namespace

void reduce_accumulate(void *dst, const void *src, int64_t count, Dtype dt,
                       ROp op) {
    TraceScope trace(Tracer::ACCUMULATE);
    if (reduce_accumulate_simd(dst, src, count, dt, op)) return;
    reduce_accumulate_scalar(dst, src, count, dt, op);
}

void reduce_accumulate_scalar(void *dst, const void *src, int64_t count,
                              Dtype dt, ROp op) {
    switch (dt) {
        case Dtype::u8:
            return accumulate_typed((uint8_t *)dst, (const uint8_t *)src,
                                    count, op);
        case Dtype::i8:
            return accumulate_typed((int8_t *)dst, (const int8_t *)src, count,
                                    op);
        case Dtype::u16:
            return accumulate_typed((uint16_t *)dst, (const uint16_t *)src,
                                    count, op);
        case Dtype::i16:
            return accumulate_typed((int16_t *)dst, (const int16_t *)src,
                                    count, op);
        case Dtype::u32:
            return accumulate_typed((uint32_t *)dst, (const uint32_t *)src,
                                    count, op);
        case Dtype::i32:
            return accumulate_typed((int32_t *)dst, (const int32_t *)src,
                                    count, op);
        case Dtype::u64:
            return accumulate_typed((uint64_t *)dst, (const uint64_t *)src,
                                    count, op);
        case Dtype::i64:
            return accumulate_typed((int64_t *)dst, (const int64_t *)src,
                                    count, op);
        case Dtype::f16:
            return accumulate_16bit_float<f16_to_f32, f32_to_f16>(
                (uint16_t *)dst, (const uint16_t *)src, count, op);
        case Dtype::bf16:
            return accumulate_16bit_float<bf16_to_f32, f32_to_bf16>(
                (uint16_t *)dst, (const uint16_t *)src, count, op);
        case Dtype::f32:
            return accumulate_typed((float *)dst, (const float *)src, count,
                                    op);
        case Dtype::f64:
            return accumulate_typed((double *)dst, (const double *)src, count,
                                    op);
    }
}

// ------------------------------------------------------------------ peers

std::string PeerID::str() const {
    char buf[32];
    std::snprintf(buf, sizeof(buf), "%u.%u.%u.%u:%u", (ipv4 >> 24) & 0xFF,
                  (ipv4 >> 16) & 0xFF, (ipv4 >> 8) & 0xFF, ipv4 & 0xFF, port);
    return buf;
}

bool parse_peer(const std::string &s, PeerID *out) {
    unsigned a, b, c, d, p;
    char tail;
    if (std::sscanf(s.c_str(), "%u.%u.%u.%u:%u%c", &a, &b, &c, &d, &p,
                    &tail) != 5)
        return false;
    if (a > 255 || b > 255 || c > 255 || d > 255 || p > 65535) return false;
    out->ipv4 = (a << 24) | (b << 16) | (c << 8) | d;
    out->port = uint16_t(p);
    return true;
}

bool parse_peer_list(const std::string &s, std::vector<PeerID> *out) {
    out->clear();
    if (s.empty()) return true;
    size_t pos = 0;
    while (pos <= s.size()) {
        size_t comma = s.find(',', pos);
        std::string part = s.substr(
            pos, comma == std::string::npos ? std::string::npos : comma - pos);
        PeerID id;
        if (!parse_peer(part, &id)) return false;
        out->push_back(id);
        if (comma == std::string::npos) break;
        pos = comma + 1;
    }
    return true;
}

// ----------------------------------------------------------- topologies
// Shapes mirror kungfu_tpu/plan/topology.py (reference:
// srcs/go/plan/topology.go); locality rule: only host-master ranks carry
// cross-host edges.

namespace {

void local_masters(const std::vector<PeerID> &peers, std::vector<int> *masters,
                   std::unordered_map<uint32_t, int> *host_master) {
    for (int r = 0; r < int(peers.size()); r++) {
        if (!host_master->count(peers[r].ipv4)) {
            (*host_master)[peers[r].ipv4] = r;
            masters->push_back(r);
        }
    }
}

Graph binary_tree_star(const std::vector<PeerID> &peers, int offset) {
    Graph g(int(peers.size()));
    std::vector<int> masters;
    std::unordered_map<uint32_t, int> host_master;
    local_masters(peers, &masters, &host_master);
    for (int r = 0; r < int(peers.size()); r++) {
        int m = host_master[peers[r].ipv4];
        if (m != r) g.add_edge(m, r);
    }
    int k = int(masters.size());
    if (k > 1) {
        for (int i = 0; i < k; i++) {
            for (int j : {2 * i + 1, 2 * i + 2}) {
                if (j < k)
                    g.add_edge(masters[(i + offset) % k],
                               masters[(j + offset) % k]);
            }
        }
    }
    return g;
}

std::pair<Graph, Graph> circular_pair(int k, int r) {
    Graph reduce(k), bcast(k);
    for (int i = 0; i < k; i++) reduce.add_edge(i, i);
    for (int i = 1; i < k; i++) {
        reduce.add_edge((r + i) % k, (r + i + 1) % k);
        bcast.add_edge((r + i - 1) % k, (r + i) % k);
    }
    return {reduce, bcast};
}

}  // namespace

Graph star_graph(int k, int r) {
    Graph g(k);
    for (int i = 0; i < k; i++)
        if (i != r) g.add_edge(r, i);
    return g;
}

Graph reduce_graph_of(const Graph &bcast) {
    Graph g = bcast.reverse();
    for (int i = 0; i < g.n; i++) g.add_edge(i, i);
    return g;
}

Strategy resolve_auto(Strategy s, const std::vector<PeerID> &peers) {
    if (s != Strategy::auto_select) return s;
    std::vector<int> masters;
    std::unordered_map<uint32_t, int> host_master;
    local_masters(peers, &masters, &host_master);
    return masters.size() <= 1 ? Strategy::star : Strategy::binary_tree_star;
}

namespace {

// local_masters with `root` forced to be its host's master, so host-aware
// rooted graphs converge at the requested root. masters[0] == root.
void rooted_masters(const std::vector<PeerID> &peers, int root,
                    std::vector<int> *masters,
                    std::unordered_map<uint32_t, int> *host_master) {
    (*host_master)[peers[root].ipv4] = root;
    masters->push_back(root);
    for (int r = 0; r < int(peers.size()); r++) {
        if (!host_master->count(peers[r].ipv4)) {
            (*host_master)[peers[r].ipv4] = r;
            masters->push_back(r);
        }
    }
}

// Binary tree over `order` (order[0] stays the root; the rest rotated by
// `variant`), emitting edges into g.
void binary_tree_over(Graph *g, const std::vector<int> &order, int variant) {
    const int k = int(order.size());
    if (k <= 1) return;
    auto at = [&](int pos) {
        if (pos == 0) return order[0];
        return order[1 + (pos - 1 + variant) % (k - 1)];
    };
    for (int i = 0; i < k; i++)
        for (int j : {2 * i + 1, 2 * i + 2})
            if (j < k) g->add_edge(at(i), at(j));
}

}  // namespace

int rooted_variants(Strategy s, const std::vector<PeerID> &peers) {
    const int k = int(peers.size());
    s = resolve_auto(s, peers);
    switch (s) {
        case Strategy::binary_tree:
            return std::max(1, k - 1);
        case Strategy::binary_tree_star:
        case Strategy::multi_binary_tree_star: {
            std::vector<int> masters;
            std::unordered_map<uint32_t, int> host_master;
            local_masters(peers, &masters, &host_master);
            return std::max(1, int(masters.size()) - 1);
        }
        default:
            return 1;  // star/clique/ring have one rooted shape
    }
}

GraphPair rooted_pair(Strategy s, const std::vector<PeerID> &peers, int root,
                      int variant) {
    const int k = int(peers.size());
    s = resolve_auto(s, peers);
    if (s == Strategy::ring && k > 1) {
        // chain ending (reduce) / starting (bcast) at root
        return circular_pair(k, root);
    }
    Graph bcast(k);
    switch (s) {
        case Strategy::binary_tree: {
            std::vector<int> order;
            order.push_back(root);
            for (int r = 0; r < k; r++)
                if (r != root) order.push_back(r);
            binary_tree_over(&bcast, order, variant);
            break;
        }
        case Strategy::tree:
        case Strategy::binary_tree_star:
        case Strategy::multi_binary_tree_star: {
            std::vector<int> masters;
            std::unordered_map<uint32_t, int> host_master;
            rooted_masters(peers, root, &masters, &host_master);
            for (int r = 0; r < k; r++) {
                int m = host_master[peers[r].ipv4];
                if (m != r) bcast.add_edge(m, r);
            }
            if (s == Strategy::tree) {
                for (size_t i = 1; i < masters.size(); i++)
                    bcast.add_edge(masters[0], masters[i]);
            } else {
                binary_tree_over(&bcast, masters, variant);
            }
            break;
        }
        default:  // star, clique
            bcast = star_graph(k, root);
            break;
    }
    return {reduce_graph_of(bcast), bcast};
}

namespace {

// Copy a master-level graph into the full rank space via masters[i] ->
// global rank, preserving edge order (float accumulation order is part
// of the cross-rank contract).
void embed_masters(const Graph &g, const std::vector<int> &masters,
                   Graph *out) {
    for (int i = 0; i < g.n; i++) {
        if (g.self_loop[size_t(i)]) out->add_edge(masters[i], masters[i]);
        for (int j : g.next[i]) out->add_edge(masters[i], masters[j]);
    }
}

// Compose one master-level (reduce, bcast) pair with the intra-host
// star stages: leaves reduce into their host master, masters run the
// embedded inter-host pair, masters broadcast back to their leaves.
GraphPair compose_hier_pair(const GraphPair &mp, int n,
                            const std::vector<int> &masters,
                            const std::unordered_map<uint32_t, int>
                                &host_master,
                            const std::vector<PeerID> &peers) {
    Graph rg(n), bg(n);
    embed_masters(mp.first, masters, &rg);
    embed_masters(mp.second, masters, &bg);
    for (int r = 0; r < n; r++) {
        const int m = host_master.at(peers[size_t(r)].ipv4);
        if (m == r) continue;
        rg.add_edge(r, m);  // intra-host reduce: leaf -> its master
        bg.add_edge(m, r);  // intra-host bcast: master -> its leaves
    }
    return {rg, bg};
}

}  // namespace

bool hier_enabled() {
    const char *e = std::getenv("KF_HIER");
    return e && std::strcmp(e, "1") == 0;
}

std::vector<GraphPair> build_hierarchical(Strategy s,
                                          const std::vector<PeerID> &peers) {
    const int n = int(peers.size());
    std::vector<int> masters;
    std::unordered_map<uint32_t, int> host_master;
    local_masters(peers, &masters, &host_master);
    if (int(masters.size()) == n) return build_strategy(s, peers);
    std::vector<PeerID> mpeers;
    mpeers.reserve(masters.size());
    for (int m : masters) mpeers.push_back(peers[size_t(m)]);
    // the inter-host stage IS the configured strategy, over the masters
    // (AUTO re-resolves against the master list inside build_strategy)
    auto mpairs = build_strategy(s, mpeers);
    std::vector<GraphPair> out;
    out.reserve(mpairs.size());
    for (auto &mp : mpairs)
        out.push_back(
            compose_hier_pair(mp, n, masters, host_master, peers));
    return out;
}

int hier_rooted_variants(Strategy s, const std::vector<PeerID> &peers,
                         int root) {
    std::vector<int> masters;
    std::unordered_map<uint32_t, int> host_master;
    rooted_masters(peers, root, &masters, &host_master);
    if (int(masters.size()) == int(peers.size()))
        return rooted_variants(s, peers);
    std::vector<PeerID> mpeers;
    for (int m : masters) mpeers.push_back(peers[size_t(m)]);
    return rooted_variants(s, mpeers);
}

GraphPair hier_rooted_pair(Strategy s, const std::vector<PeerID> &peers,
                           int root, int variant) {
    const int n = int(peers.size());
    std::vector<int> masters;
    std::unordered_map<uint32_t, int> host_master;
    rooted_masters(peers, root, &masters, &host_master);
    if (int(masters.size()) == n) return rooted_pair(s, peers, root, variant);
    std::vector<PeerID> mpeers;
    mpeers.reserve(masters.size());
    for (int m : masters) mpeers.push_back(peers[size_t(m)]);
    // masters[0] == root (rooted_masters forces root to master its own
    // host), so the master-level pair is rooted at master index 0
    const GraphPair mp = rooted_pair(s, mpeers, 0, variant);
    return compose_hier_pair(mp, n, masters, host_master, peers);
}

std::vector<GraphPair> build_strategy(Strategy s,
                                      const std::vector<PeerID> &peers) {
    const int k = int(peers.size());
    std::vector<int> masters;
    std::unordered_map<uint32_t, int> host_master;
    local_masters(peers, &masters, &host_master);

    s = resolve_auto(s, peers);

    std::vector<GraphPair> out;
    auto from_bcast = [&](const Graph &b) {
        out.push_back({reduce_graph_of(b), b});
    };
    switch (s) {
        case Strategy::star:
            from_bcast(star_graph(k, 0));
            break;
        case Strategy::ring:
            for (int r = 0; r < k; r++) out.push_back(circular_pair(k, r));
            break;
        case Strategy::clique:
            for (int r = 0; r < k; r++) from_bcast(star_graph(k, r));
            break;
        case Strategy::tree: {
            Graph g(k);
            for (int r = 0; r < k; r++) {
                int m = host_master[peers[r].ipv4];
                if (m != r) g.add_edge(m, r);
            }
            for (size_t i = 1; i < masters.size(); i++)
                g.add_edge(masters[0], masters[i]);
            from_bcast(g);
            break;
        }
        case Strategy::binary_tree: {
            Graph g(k);
            for (int i = 0; i < k; i++)
                for (int j : {2 * i + 1, 2 * i + 2})
                    if (j < k) g.add_edge(i, j);
            from_bcast(g);
            break;
        }
        case Strategy::binary_tree_star:
            from_bcast(binary_tree_star(peers, 0));
            break;
        case Strategy::multi_binary_tree_star:
            for (size_t i = 0; i < masters.size(); i++)
                from_bcast(binary_tree_star(peers, int(i)));
            break;
        default:
            from_bcast(star_graph(k, 0));
            break;
    }
    return out;
}

}  // namespace kf
