// TCP transport: framed named messages, connection pool, server,
// collective rendezvous, blob store.
// (Control-plane rebuild of reference srcs/go/rchannel + srcs/go/store.)
//
// Wire protocol (all integers little-endian):
//   on connect:  ConnHeader { u16 type, u16 src_port, u32 src_ipv4 }
//   server ack:  Ack        { u32 token }   -- token = cluster epoch; a
//                Collective dial whose token mismatches the dialer's epoch
//                is rejected (stale-epoch fencing).
//   then a stream of messages:
//                MsgHeader  { u32 name_len, name bytes, u32 flags }
//                Body       { u32 len, data }
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "core.hpp"

namespace kf {

enum class ConnType : uint16_t {
    ping = 0,
    control = 1,
    collective = 2,
    p2p = 3,
};

// message flags
constexpr uint32_t kFlagIsResponse = 1u << 1;
constexpr uint32_t kFlagRequestFailed = 1u << 2;

struct WireMessage {
    std::string name;
    uint32_t flags = 0;
    std::vector<uint8_t> data;
};

// ------------------------------------------------------------------- fd io

// Blocking exact-length read/write on a socket fd; false on EOF/error.
bool read_exact(int fd, void *buf, size_t n);
bool write_exact(int fd, const void *buf, size_t n);
bool write_message(int fd, const std::string &name, uint32_t flags,
                   const void *data, size_t len);
// max_len guards allocations against corrupt/hostile length prefixes
bool read_message(int fd, WireMessage *out, size_t max_len = size_t(1) << 33);

// ------------------------------------------------------------- rendezvous

// Named FIFO mailboxes for collective traffic: key = (src peer, tensor
// name). FIFO per key matches per-connection message order, which is what
// makes reduce-phase and bcast-phase messages on the same name unambiguous.
class Rendezvous {
  public:
    void push(const PeerID &src, WireMessage msg);
    // Blocks until a message for (src,name) arrives; KF_OK / KF_ERR_TIMEOUT.
    int pop(const PeerID &src, const std::string &name,
            std::vector<uint8_t> *out, int64_t timeout_ms);
    void clear();

  private:
    std::mutex mu_;
    std::condition_variable cv_;
    std::unordered_map<std::string, std::deque<std::vector<uint8_t>>> q_;
};

// ------------------------------------------------------------------ store

// Named blobs; size-checked on re-save like the reference store.
class Store {
  public:
    int save(const std::string &name, const void *data, int64_t n);
    // returns KF_OK and copies into out (must be exact size), or
    // KF_ERR_NOTFOUND / KF_ERR_ARG on size mismatch
    int load(const std::string &name, std::vector<uint8_t> *out);

  private:
    std::mutex mu_;
    std::unordered_map<std::string, std::vector<uint8_t>> blobs_;
};

// Sliding window of `window` versioned stores (reference keeps 3 so async
// peers can fetch slightly-stale models while new ones are written).
class VersionedStore {
  public:
    explicit VersionedStore(int window = 3) : window_(window) {}
    int save(const std::string &version, const std::string &name,
             const void *data, int64_t n);
    int load(const std::string &version, const std::string &name,
             std::vector<uint8_t> *out);

  private:
    int window_;
    std::mutex mu_;
    std::deque<std::pair<std::string, std::shared_ptr<Store>>> stores_;
};

// ----------------------------------------------------------------- client

struct Counters {
    std::atomic<uint64_t> egress{0}, ingress{0};
};

// Connection pool: one persistent connection per (dest, type). Sends are
// serialized per connection; P2P request/response holds the connection lock
// across the round trip.
class Client {
  public:
    Client(PeerID self, Counters *counters)
        : self_(self), counters_(counters) {}
    ~Client();

    void set_token(uint32_t token);
    // send framed message; establishes the connection on first use
    int send(const PeerID &dest, ConnType t, const std::string &name,
             uint32_t flags, const void *data, size_t len);
    // P2P RPC: request blob `name` (body = version string, may be empty)
    int request(const PeerID &dest, const std::string &version,
                const std::string &name, std::vector<uint8_t> *out);
    int ping(const PeerID &dest, int64_t *rtt_us);
    // Drop connections to peers outside `keep` and adopt the new token.
    void reset(const std::vector<PeerID> &keep, uint32_t token);

    int epoch_retries = 20;         // epoch-token mismatch budget (resize
                                    // convergence window), then fail fast
    int connect_retries = 120;      // x period = dial patience for peers
    int connect_retry_ms = 250;     // that are still starting up

  private:
    struct Conn {
        std::mutex mu;
        int fd = -1;
    };
    std::shared_ptr<Conn> get(const PeerID &dest, ConnType t);
    int dial(const PeerID &dest, ConnType t);  // returns fd or negative err
    int ensure_connected(Conn *c, const PeerID &dest, ConnType t);

    PeerID self_;
    Counters *counters_;
    std::mutex mu_;
    std::atomic<uint32_t> token_{0};
    std::unordered_map<uint64_t, std::shared_ptr<Conn>> conns_;
};

// ----------------------------------------------------------------- server

using ControlHandler =
    std::function<void(const std::string &name, const std::vector<uint8_t> &)>;
// Resolve a P2P request to blob bytes; returns KF_OK or KF_ERR_NOTFOUND.
using RequestHandler = std::function<int(
    const std::string &version, const std::string &name,
    std::vector<uint8_t> *out)>;

// Accept loop + one reader thread per connection. Collective messages land
// in the Rendezvous; P2P requests are answered inline on the same socket;
// Control messages invoke the handler; Pings echo.
class Server {
  public:
    Server(PeerID self, Rendezvous *rdv, Counters *counters)
        : self_(self), rdv_(rdv), counters_(counters) {}
    ~Server() { stop(); }

    int start();
    void stop();
    void set_token(uint32_t token) { token_ = token; }
    // Kick every live connection (used at epoch switch so stale-epoch
    // senders must re-handshake against the new token).
    void drop_connections();
    void set_control_handler(ControlHandler h);
    void set_request_handler(RequestHandler h);

  private:
    void accept_loop();
    void serve_conn(int fd);

    PeerID self_;
    Rendezvous *rdv_;
    Counters *counters_;
    std::atomic<uint32_t> token_{0};
    std::atomic<bool> running_{false};
    int listen_fd_ = -1;
    std::thread accept_thread_;
    std::mutex mu_;
    std::condition_variable conns_done_cv_;
    int active_conns_ = 0;
    ControlHandler control_handler_;
    RequestHandler request_handler_;
    std::unordered_set<int> live_fds_;
};

}  // namespace kf
