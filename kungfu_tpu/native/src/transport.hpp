// TCP transport: framed named messages, connection pool, server,
// collective rendezvous, blob store.
// (Control-plane rebuild of reference srcs/go/rchannel + srcs/go/store.)
//
// Wire protocol (all integers little-endian):
//   on connect:  ConnHeader { u16 type, u16 src_port, u32 src_ipv4 }
//   server ack:  Ack        { u32 token }   -- token = cluster epoch; a
//                Collective dial whose token mismatches the dialer's epoch
//                is rejected (stale-epoch fencing).
//   then a stream of messages:
//                MsgHeader  { u32 name_len, name bytes, u32 flags }
//                Body       { u32 len, data }
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "core.hpp"
#include "shm.hpp"

namespace kf {

enum class ConnType : uint16_t {
    ping = 0,
    control = 1,
    collective = 2,
    p2p = 3,
    // shm hello/liveness channel: the dial carries the normal epoch
    // token handshake, then exactly one message naming the sender's
    // ring segment; afterwards the socket is silent and its EOF is the
    // (only) death/epoch-reset signal for the ring reader. Ring frames
    // prepend a u32 FNV-1a checksum of the frame header (name_len,
    // name, flags, len) to the socket frame format, so a torn or
    // header-corrupted frame surfaces as KF_ERR_CORRUPT instead of
    // being mis-framed into a reduce (docs/collectives.md "Failure
    // semantics").
    shm = 4,
};

// Wire link classes for byte attribution (kf_link_stats; kftrace's
// kf_wire_bytes_total{link=...} renders these): TCP socket, AF_UNIX
// socket, shared-memory ring.
enum class LinkClass : int { tcp = 0, uds = 1, shm = 2 };
constexpr int kNumLinkClasses = 3;

// message flags
constexpr uint32_t kFlagIsResponse = 1u << 1;
constexpr uint32_t kFlagRequestFailed = 1u << 2;

struct WireMessage {
    std::string name;
    uint32_t flags = 0;
    std::vector<uint8_t> data;
};

// ------------------------------------------------------------- buffer pool

// Power-of-2 free-list pool for receive buffers (reference:
// srcs/go/rchannel/connection/byte_slice_pool.go keeps per-size-class
// sync.Pools behind recvQ). Vectors handed out have size()==n and a pow-2
// capacity; put() recycles them up to a global cap so steady-state
// collective traffic stops allocating.
class BufferPool {
  public:
    static BufferPool &instance();
    std::vector<uint8_t> get(size_t n);
    void put(std::vector<uint8_t> &&v);
    // bytes currently cached (for tests/metrics)
    size_t cached_bytes();

  private:
    static constexpr int kBuckets = 33;  // capacities 2^0 .. 2^32
    static constexpr size_t kMaxCachedBytes = size_t(1) << 28;  // 256 MiB
    std::mutex mu_;
    std::deque<std::vector<uint8_t>> buckets_[kBuckets];
    size_t cached_ = 0;
};

// RAII pooled buffer: releases back to the pool on scope exit.
class PooledBuf {
  public:
    explicit PooledBuf(size_t n) : v_(BufferPool::instance().get(n)) {}
    ~PooledBuf() { BufferPool::instance().put(std::move(v_)); }
    PooledBuf(const PooledBuf &) = delete;
    PooledBuf &operator=(const PooledBuf &) = delete;
    uint8_t *data() { return v_.data(); }
    size_t size() const { return v_.size(); }

  private:
    std::vector<uint8_t> v_;
};

// Filesystem path of a peer's colocated-peer Unix socket. Derived from
// (uid, ipv4, port) so parallel test clusters of different users cannot
// collide (reference: plan/addr.go:50-59 SockFile). Colocated peers dial
// this instead of TCP loopback; KF_NO_UNIX_SOCKET=1 disables.
std::string sock_path(const PeerID &p);

// ------------------------------------------------------------------- fd io

// Blocking exact-length read/write on a socket fd; false on EOF/error.
bool read_exact(int fd, void *buf, size_t n);
bool write_exact(int fd, const void *buf, size_t n);
bool write_message(int fd, const std::string &name, uint32_t flags,
                   const void *data, size_t len);
// max_len guards allocations against corrupt/hostile length prefixes
bool read_message(int fd, WireMessage *out, size_t max_len = size_t(1) << 33);

// ------------------------------------------------------------- rendezvous

// Named FIFO mailboxes for collective traffic: key = (src peer, tensor
// name). FIFO per key matches per-connection message order, which is what
// makes reduce-phase and bcast-phase messages on the same name unambiguous.
class Rendezvous {
  public:
    // Registered in-place receive: the socket reader writes the message
    // body straight into a slot's caller-owned buffer, skipping the queue
    // allocation + copy (reference: WaitRecvBuf flag, message.go:70-75 +
    // handler/collective.go:34-41 RecvInto).
    struct RecvSlot {
        uint8_t *buf = nullptr;
        size_t cap = 0;
        size_t len = 0;  // filled body length
        enum { waiting, claimed, done, failed } state = waiting;
    };

    void push(const PeerID &src, WireMessage msg);
    // In-place receive into caller memory. Takes an already-queued message
    // if present (recycling its buffer), else registers `buf` so the reader
    // thread fills it directly. Fails with KF_ERR if the message is larger
    // than cap, KF_ERR_CONN if the connection died mid-body or clear() ran.
    int pop_into(const PeerID &src, const std::string &name, void *buf,
                 size_t cap, size_t *len, int64_t timeout_ms);
    // Reader side: claim a waiting slot for (src,name) if one exists and
    // the queue is empty (FIFO order); nullptr = read into a pooled vector
    // and push(). A slot too small for `len` is failed and nullptr returned.
    RecvSlot *begin_recv(const PeerID &src, const std::string &name,
                         size_t len);
    void commit_recv(RecvSlot *slot, bool ok);
    // Drops queued messages and fails all waiting slots (epoch switch).
    void clear();
    // Inbound collective-connection lifecycle, driving peer liveness:
    // when a peer's LAST live conn is lost mid-epoch (may_fail=true, i.e.
    // not an epoch-switch close), the peer is marked dead and every
    // waiting slot registered against it fails — receivers get
    // KF_ERR_CONN immediately instead of blocking out their full timeout
    // (the fail-fast the reference's runner gets from watch.go:136-149
    // process supervision). Queued messages are kept: data that already
    // arrived is still valid. The live-conn count makes a same-epoch
    // client re-dial race harmless: the old conn's EOF is a no-op while
    // the newer conn is open, and a fresh conn lifts any death mark.
    void conn_opened(const PeerID &src);
    void conn_lost(const PeerID &src, bool may_fail);
    // Frame-integrity violation on an inbound channel (shm ring frame
    // failed its header checksum / length validation): the stream
    // position is untrusted, so the whole channel dies and receivers
    // blocked on this peer fail with KF_ERR_CORRUPT — the same
    // fail-fast-into-recovery shape as a peer death, but with a
    // distinct code so a silent-garbage bug class is visible as
    // itself. Lifted like a death mark: clear() (epoch switch) or a
    // fresh conn from the peer.
    void conn_corrupt(const PeerID &src);

  private:
    std::mutex mu_;
    std::condition_variable cv_;
    std::unordered_map<std::string, std::deque<std::vector<uint8_t>>> q_;
    std::unordered_map<std::string, std::deque<RecvSlot *>> slots_;
    std::unordered_set<std::string> dead_;  // peers whose conn died mid-epoch
    // peers whose inbound frames failed integrity checks: receives
    // fail with KF_ERR_CORRUPT instead of KF_ERR_CONN
    std::unordered_set<std::string> corrupt_;
    std::unordered_map<std::string, int> live_conns_;  // inbound, per peer
};

// ------------------------------------------------------------------ store

// Named blobs; size-checked on re-save like the reference store.
class Store {
  public:
    int save(const std::string &name, const void *data, int64_t n);
    // returns KF_OK and copies into out (must be exact size), or
    // KF_ERR_NOTFOUND / KF_ERR_ARG on size mismatch
    int load(const std::string &name, std::vector<uint8_t> *out);

  private:
    std::mutex mu_;
    std::unordered_map<std::string, std::vector<uint8_t>> blobs_;
};

// Sliding window of `window` versioned stores (reference keeps 3 so async
// peers can fetch slightly-stale models while new ones are written).
class VersionedStore {
  public:
    explicit VersionedStore(int window = 3) : window_(window) {}
    int save(const std::string &version, const std::string &name,
             const void *data, int64_t n);
    int load(const std::string &version, const std::string &name,
             std::vector<uint8_t> *out);

  private:
    int window_;
    std::mutex mu_;
    std::deque<std::pair<std::string, std::shared_ptr<Store>>> stores_;
};

// ----------------------------------------------------------------- client

struct Counters {
    std::atomic<uint64_t> egress{0}, ingress{0};
    // per link class (LinkClass order: tcp, unix, shm) — the totals
    // above stay the sum so existing consumers keep their meaning
    std::atomic<uint64_t> egress_link[kNumLinkClasses]{{0}, {0}, {0}};
    std::atomic<uint64_t> ingress_link[kNumLinkClasses]{{0}, {0}, {0}};
    // per-pair shm establishment failures that degraded to sockets
    // (kf_link_fallback_total): the degraded-transport mode is counted
    // and logged, never silent (docs/collectives.md)
    std::atomic<uint64_t> shm_fallback{0};

    void add_egress(LinkClass lc, uint64_t n) {
        egress += n;
        egress_link[int(lc)] += n;
    }
    void add_ingress(LinkClass lc, uint64_t n) {
        ingress += n;
        ingress_link[int(lc)] += n;
    }
};

// Connection pool: one persistent connection per (dest, type). Sends are
// serialized per connection; P2P request/response holds the connection lock
// across the round trip.
class Client {
  public:
    Client(PeerID self, Counters *counters)
        : self_(self), counters_(counters),
          shm_enabled_(shm_transport_enabled()) {}
    ~Client();

    void set_token(uint32_t token);
    // send framed message; establishes the connection on first use
    int send(const PeerID &dest, ConnType t, const std::string &name,
             uint32_t flags, const void *data, size_t len);
    // P2P RPC: request blob `name` (body = version string, may be empty)
    int request(const PeerID &dest, const std::string &version,
                const std::string &name, std::vector<uint8_t> *out);
    int ping(const PeerID &dest, int64_t *rtt_us);
    // Drop connections to peers outside `keep` and adopt the new token.
    void reset(const std::vector<PeerID> &keep, uint32_t token);

    int epoch_retries = 20;         // epoch-token mismatch budget (resize
                                    // convergence window), then fail fast
    int connect_retries = 120;      // x period = dial patience for peers
    int connect_retry_ms = 250;     // that are still starting up
    int reconnect_retries = 6;      // budget once a peer was reached and
                                    // lost: died mid-epoch => fail fast

  private:
    struct Conn {
        std::mutex mu;
        int fd = -1;
        bool was_connected = false;  // ever reached: lost => short retries
        LinkClass link = LinkClass::tcp;  // what dial_fd chose
    };
    // One shm channel per colocated destination: the ring plus its
    // hello/liveness socket. `abort` lets reset()/teardown unstick a
    // writer blocked on a full ring WITHOUT taking `mu` (the writer
    // holds it) — the shm analog of close(fd) kicking write_exact.
    struct ShmChan {
        std::mutex mu;
        int fd = -1;
        std::unique_ptr<ShmRing> ring;  // kf: guarded_by(mu)
        bool failed = false;   // establishment failed: socket fallback
        bool was_connected = false;  // ever established: lost => short
                                     // re-dial budget, fail fast
        std::atomic<bool> abort{false};
    };
    std::shared_ptr<Conn> get(const PeerID &dest, ConnType t);
    std::shared_ptr<ShmChan> get_shm(const PeerID &dest);
    int dial(const PeerID &dest, ConnType t,
             LinkClass *link = nullptr);   // returns fd or negative err
    int dial_fd(const PeerID &dest, LinkClass *link);  // raw connect
    int ensure_connected(Conn *c, const PeerID &dest, ConnType t);
    // Collective send over the shm ring; returns kShmFallback when the
    // channel cannot be (or was never) established — caller falls back
    // to the socket path for the rest of the epoch.
    static constexpr int kShmFallback = 1;
    int send_shm(const PeerID &dest, const std::string &name,
                 uint32_t flags, const void *data, size_t len);

    PeerID self_;
    Counters *counters_;
    std::mutex mu_;
    std::atomic<uint32_t> token_{0};
    bool shm_enabled_ = false;  // snapshot of KF_SHM at construction
    std::atomic<uint32_t> shm_seq_{0};  // unique ring paths per process
    std::unordered_map<uint64_t, std::shared_ptr<Conn>> conns_;
    std::unordered_map<uint64_t, std::shared_ptr<ShmChan>> shm_;
};

// ----------------------------------------------------------------- server

using ControlHandler =
    std::function<void(const std::string &name, const std::vector<uint8_t> &)>;
// Resolve a P2P request to blob bytes; returns KF_OK or KF_ERR_NOTFOUND.
using RequestHandler = std::function<int(
    const std::string &version, const std::string &name,
    std::vector<uint8_t> *out)>;

// Accept loop + one reader thread per connection. Collective messages land
// in the Rendezvous; P2P requests are answered inline on the same socket;
// Control messages invoke the handler; Pings echo.
class Server {
  public:
    Server(PeerID self, Rendezvous *rdv, Counters *counters)
        : self_(self), rdv_(rdv), counters_(counters) {}
    ~Server() { stop(); }

    int start();
    void stop();
    void set_token(uint32_t token) { token_ = token; }
    // Kick every live connection (used at epoch switch so stale-epoch
    // senders must re-handshake against the new token).
    void drop_connections();
    void set_control_handler(ControlHandler h);
    void set_request_handler(RequestHandler h);

  private:
    void accept_loop(int listen_fd, bool tcp);
    void serve_conn(int fd, LinkClass link);
    // Ring-reader loop of one inbound shm channel: attach the segment
    // named by the hello message, ack one byte, then parse framed
    // messages out of the ring into the Rendezvous until the producer
    // closes, the hello socket drops (sender death / epoch reset), or
    // the server stops.
    void serve_shm(int fd, const PeerID &src, bool same_epoch,
                   uint32_t epoch_token);

    PeerID self_;
    Rendezvous *rdv_;
    Counters *counters_;
    std::atomic<uint32_t> token_{0};
    std::atomic<bool> running_{false};
    int listen_fd_ = -1;
    int unix_fd_ = -1;  // colocated-peer listener (AF_UNIX)
    // self-pipe waking the poll-driven accept loops: shutdown(2) on a
    // LISTENING AF_UNIX socket is ENOTCONN on Linux and leaves a blocked
    // accept() blocked forever, so stop() must have a wakeup channel that
    // does not depend on socket semantics at all
    int wake_r_ = -1, wake_w_ = -1;
    std::string unix_path_;
    std::thread accept_thread_;
    std::thread unix_accept_thread_;
    std::mutex mu_;
    std::condition_variable conns_done_cv_;
    int active_conns_ = 0;
    ControlHandler control_handler_;
    RequestHandler request_handler_;
    std::unordered_set<int> live_fds_;
};

}  // namespace kf
