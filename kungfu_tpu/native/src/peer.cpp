#include "peer.hpp"

#include "../include/kf.h"

namespace kf {

Peer::Peer(PeerID self, std::vector<PeerID> peers, uint32_t version,
           Strategy strategy, int64_t timeout_ms_)
    : client(self, &counters),
      server(self, &rdv, &counters),
      timeout_ms(timeout_ms_),
      self_(self),
      peers_(std::move(peers)),
      version_(version),
      init_version_(version),
      strategy_(strategy) {
    server.set_request_handler([this](const std::string &version,
                                      const std::string &name,
                                      std::vector<uint8_t> *out) {
        if (version.empty()) return store.load(name, out);
        return vstore.load(version, name, out);
    });
}

int Peer::start() {
    if (running_) return KF_OK;
    server.set_token(version_);
    client.set_token(version_);
    int rc = server.start();
    if (rc != KF_OK) return rc;
    {
        std::unique_lock<std::shared_mutex> lk(session_mu_);
        session_ = std::make_unique<Session>(self_, peers_, strategy_,
                                             &client, &rdv, timeout_ms);
        if (!peers_.empty() && session_->rank() < 0) {
            KF_ERROR("self %s not in peer list", self_.str().c_str());
            return KF_ERR_ARG;
        }
    }
    running_ = true;
    return KF_OK;
}

int Peer::stop() {
    if (!running_) return KF_OK;
    running_ = false;
    server.stop();
    return KF_OK;
}

int Peer::update(std::vector<PeerID> peers, uint32_t version) {
    std::unique_lock<std::shared_mutex> lk(session_mu_);
    // token bump first: new dials from stale-epoch peers now get rejected,
    // and existing inbound connections are kicked so stale senders must
    // re-handshake against the new token
    server.set_token(version);
    server.drop_connections();
    client.reset(peers, version);
    rdv.clear();
    version_ = version;
    peers_ = std::move(peers);
    session_ = std::make_unique<Session>(self_, peers_, strategy_, &client,
                                         &rdv, timeout_ms);
    if (session_->rank() < 0) {
        KF_ERROR("self %s not in new peer list (epoch %u)",
                 self_.str().c_str(), version);
        return KF_ERR_ARG;
    }
    return KF_OK;
}

}  // namespace kf
